"""Attention dispatcher.

One entry point, four implementations (SURVEY.md §2.3 build targets —
the reference has none of these, grep-verified SURVEY.md §5):

- ``dot``     — plain XLA einsum attention (always available; the
                numerics reference for every other impl's tests);
- ``flash``   — blockwise pallas TPU kernel, O(seq) memory
                (:mod:`tensorflowonspark_tpu.ops.flash_attention`);
- ``ring``    — sequence-parallel ring attention over the ``seq`` mesh
                axis (:mod:`tensorflowonspark_tpu.ops.ring_attention`);
- ``ulysses`` — all-to-all sequence↔head re-sharding
                (:mod:`tensorflowonspark_tpu.ops.ulysses`).

Shapes follow the ``[batch, seq, heads, head_dim]`` convention
throughout (the TPU-friendly layout: heads*head_dim contiguous for the
MXU, seq shardable for context parallelism).
"""

import jax
import jax.numpy as jnp

_IMPLS = ("dot", "flash", "ring", "ulysses")


def dot_attention(q, k, v, causal=True, scale=None, mask=None, window=0,
                  k_scale=None, v_scale=None):
    """Plain softmax attention via XLA einsums.

    Args:
      q: ``[B, Sq, H, D]``; k, v: ``[B, Sk, Hkv, D]`` where ``Hkv``
        divides ``H`` (grouped-query attention: each kv head serves
        ``H/Hkv`` query heads; ``Hkv == H`` is ordinary MHA).  The
        grouped einsums never materialize repeated k/v.
      causal: apply a causal mask (positions aligned at the end).
      mask: optional additive mask broadcastable to ``[B, H, Sq, Sk]``.
      window: ``> 0`` restricts each query to the last ``window``
        positions (sliding-window attention; requires ``causal``).
      k_scale, v_scale: optional per-position/per-head dequant scales
        ``[B, Sk, Hkv, 1]`` for int8 ``k``/``v`` banks (the quantized
        KV cache).  Instead of dequantizing the banks (which would
        materialize a full-width copy), the factored identities are
        used: ``q·(k*ks) == (q·k)*ks`` scales the LOGITS, and
        ``Σ p·(v*vs) == Σ (p*vs)·v`` folds into the probabilities —
        the int8 banks reach the einsums as pure converts, which XLA
        fuses into the operand read.
    Returns ``[B, Sq, H, D]`` in ``q.dtype``.
    """
    if window:
        if window < 0:
            raise ValueError(
                "window must be positive, got {0}".format(window)
            )
        if not causal:
            raise ValueError("window attention requires causal=True")
    orig_dtype = q.dtype
    # int8 (quantized-cache) banks convert up WITHOUT their scales —
    # a bare convert fuses into the dot; convert-multiply does not
    if k.dtype != orig_dtype:
        k = k.astype(orig_dtype)
    if v.dtype != orig_dtype:
        v = v.astype(orig_dtype)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    h, hkv = q.shape[2], k.shape[2]
    if h % hkv != 0:
        raise ValueError(
            "query heads ({0}) must be a multiple of kv heads "
            "({1})".format(h, hkv)
        )
    g = h // hkv
    # [B, Sk, Hkv, 1] -> [B, Hkv, 1, Sk] (broadcast over queries)
    ks_t = (
        jnp.transpose(k_scale, (0, 2, 3, 1))
        if k_scale is not None else None
    )
    vs_t = (
        jnp.transpose(v_scale, (0, 2, 3, 1))
        if v_scale is not None else None
    )
    # accumulate logits/softmax in f32 for stability (bf16 inputs stay
    # bf16 through the matmuls — MXU native — but the reduction is f32)
    if g == 1:
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
        if ks_t is not None:
            logits = logits * ks_t
    else:
        qg = q.reshape(q.shape[0], q.shape[1], hkv, g, q.shape[3])
        logits = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qg, k,
            preferred_element_type=jnp.float32,
        )
        if ks_t is not None:
            logits = logits * ks_t[:, :, None]
        logits = logits.reshape(
            q.shape[0], h, q.shape[1], k.shape[1]
        )
    logits = logits * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        # queries occupy the LAST sq positions of the key timeline, which
        # makes the same mask correct for full self-attention (sq == sk)
        # and decode steps (sq == 1)
        qpos = jnp.arange(sq)[:, None] + (sk - sq)
        kpos = jnp.arange(sk)[None, :]
        visible = qpos >= kpos
        if window:
            visible = jnp.logical_and(visible, kpos > qpos - window)
        logits = jnp.where(visible, logits, -jnp.inf)
    if mask is not None:
        logits = logits + mask
    weights = jax.nn.softmax(logits, axis=-1)
    if g == 1:
        if vs_t is not None:
            weights = weights * vs_t
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", weights.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
    else:
        wg = weights.reshape(
            q.shape[0], hkv, g, q.shape[1], k.shape[1]
        )
        if vs_t is not None:
            wg = wg * vs_t[:, :, None]
        out = jnp.einsum(
            "bhgqk,bkhd->bqhgd", wg.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        ).reshape(q.shape[0], q.shape[1], h, q.shape[3])
    return out.astype(orig_dtype)


def attention(q, k, v, impl="dot", causal=True, scale=None, mesh=None,
              seq_axis="seq", block_q=1024, block_k=1024,
              ring_impl="flash", window=0):
    """Dispatch to an attention implementation (see module docstring).

    ``ring``/``ulysses`` dispatch on ``mesh``: with ``mesh=None`` the
    inputs must be local shards and the call must already be inside
    ``shard_map``-decorated code where ``seq_axis`` is bound; with a mesh
    given, the inputs are *global* arrays and the op wraps itself in a
    ``shard_map`` over the mesh's ``seq`` axis (via
    :func:`tensorflowonspark_tpu.compat.shard_map`, which falls back to
    ``jax.experimental.shard_map`` on builds without ``jax.shard_map``;
    do NOT pass a mesh from code that is itself under ``shard_map``).  ``flash`` runs the pallas
    kernels in interpret mode off-TPU so the same model runs in CPU
    tests.  ``block_q``/``block_k`` bound the pallas tiles for both the
    ``flash`` impl and ``ring``'s flash inner step; ``ring_impl``
    selects ring's inner step (``"flash"`` or the dense einsum
    numerics reference).
    """
    if impl not in _IMPLS:
        raise ValueError("unknown attention impl {0!r}; one of {1}".format(impl, _IMPLS))
    if impl == "flash":
        from tensorflowonspark_tpu.ops.flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=causal, scale=scale, block_q=block_q,
            block_k=block_k, window=window,
        )
    if impl == "ring":
        from tensorflowonspark_tpu.ops.ring_attention import (
            ring_attention,
            ring_attention_sharded,
        )

        if mesh is not None:
            return ring_attention_sharded(
                q, k, v, mesh, causal=causal, scale=scale,
                axis_name=seq_axis, impl=ring_impl,
                block_q=block_q, block_k=block_k, window=window,
            )
        return ring_attention(
            q, k, v, causal=causal, scale=scale, axis_name=seq_axis,
            impl=ring_impl, block_q=block_q, block_k=block_k,
            window=window,
        )
    if impl == "ulysses":
        from tensorflowonspark_tpu.ops.ulysses import (
            ulysses_attention,
            ulysses_attention_sharded,
        )

        if mesh is not None:
            return ulysses_attention_sharded(
                q, k, v, mesh, causal=causal, scale=scale,
                axis_name=seq_axis, block_q=block_q, block_k=block_k,
                window=window,
            )
        return ulysses_attention(
            q, k, v, causal=causal, scale=scale, axis_name=seq_axis,
            block_q=block_q, block_k=block_k, window=window,
        )
    return dot_attention(q, k, v, causal=causal, scale=scale, window=window)
