"""Paged (block-gather) decode attention over a physical KV page pool.

The continuous-batching engine's decode hot loop used to read
*contiguous per-slot banks* ``[slots, bank_len, heads, dim]``: every
cached-prefix admit paid a physical segment copy into the admitted
lane, and a block shared by N slots occupied N copies of HBM.  This
kernel makes attention consume the prefix cache's block pool DIRECTLY:

- K/V live in ONE physical pool per layer, ``[num_pages, page_tokens,
  kv_heads, head_dim]`` (:class:`~tensorflowonspark_tpu.prefix_cache.
  PagePool` allocates the page indices);
- each slot addresses the pool through a per-slot **block table**
  ``[slots, blocks_per_slot]`` of page indices — a cached admit
  *installs indices* (host bookkeeping, zero device copies) and one
  physical page serves every table that references it;
- the kernel is a flash-style online softmax whose k/v grid dimension
  walks the slot's block table via scalar-prefetch index maps (the
  same Mosaic mechanism :mod:`.gmm` uses for expert tiles): block j of
  slot b fetches physical page ``table[b, j]`` through the BlockSpec,
  so the gather IS the DMA schedule — no materialized contiguous copy.

Handles GQA (grouped queries reshape per kv head), sliding-window
attention (whole pages behind the horizon are skipped, in-page
positions masked), int8-KV dequant scales (logit/probability scaling,
the same factored identities ``dot_attention`` uses), and ragged final
pages (positions past the slot's live length masked via the prefetched
``lengths``).

Two entry points:

- :func:`paged_attention` — the pallas kernel for single-token decode
  steps (``q [B, H, D]``), the bandwidth-bound hot loop.  Off-TPU it
  runs under ``interpret=True`` (via the :mod:`~tensorflowonspark_tpu.
  compat` pallas shims) so CPU tier-1 exercises the real kernel path;
  tiny test shapes are legal there — hardware callers own Mosaic tile
  legality for their head/page geometry, like the gmm kernels.
- :func:`paged_gather_attention` — the jnp fallback for MULTI-token
  query spans (suffix prefill at canonical positions, speculative
  verify blocks): gathers the table's pages into a transient
  contiguous view and reuses :func:`..attention.dot_attention`'s
  masked einsums.  Those paths are compute-bound (prefill) or
  verify-batched, so the transient gather costs what the contiguous
  layout *stored permanently*.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tensorflowonspark_tpu import compat

NEG_INF = -1e30  # finite mask sentinel: exp() underflows to 0, no NaNs

#: Mosaic's minimum tile is (sublane, lane) with lane fixed at 128 and
#: the sublane minimum set by element width: 4-byte types pack 8
#: sublanes, 2-byte 16, 1-byte 32.
LANE = 128
_SUBLANE_BY_ITEMSIZE = {4: 8, 2: 16, 1: 32}


class TileLegalityError(ValueError):
    """A paged-KV geometry that Mosaic cannot tile on hardware.

    Raised by :func:`check_tiles` at *build* time (``serving_builder``
    with ``kv_layout="paged"``) so an off-bar ``page_tokens`` /
    ``head_dim`` choice fails with a named, actionable error instead of
    a Mosaic lowering failure deep inside the first decode dispatch.
    """


def min_tile(dtype):
    """Mosaic minimum ``(sublane, lane)`` tile for ``dtype``."""
    itemsize = jnp.dtype(dtype).itemsize
    try:
        return (_SUBLANE_BY_ITEMSIZE[itemsize], LANE)
    except KeyError:
        raise TileLegalityError(
            "no Mosaic tile rule for dtype {0} (itemsize {1})".format(
                jnp.dtype(dtype).name, itemsize
            )
        )


def check_tiles(page_tokens, head_dim, dtype):
    """Validate a paged-KV page geometry against Mosaic tile minimums.

    The kernel's per-page K/V block is ``[page_tokens, kv_heads,
    head_dim]``; Mosaic tiles the trailing two dims of each 2D slice as
    (sublane, lane) = (page_tokens, head_dim) after the head dim is
    folded, so hardware legality requires ``head_dim`` to be a multiple
    of the 128-wide lane and ``page_tokens`` a multiple of the dtype's
    sublane minimum (8 for 4-byte, 16 for 2-byte, 32 for 1-byte
    elements).  CPU interpret mode accepts anything — this preflight
    exists so builds destined for TPU fail early with a named error.

    Returns ``{"sublane": S, "lane": L}`` (the minimums checked
    against) when legal; raises :class:`TileLegalityError` otherwise.
    """
    sub, lane = min_tile(dtype)
    page_tokens = int(page_tokens)
    head_dim = int(head_dim)
    problems = []
    if page_tokens <= 0 or page_tokens % sub != 0:
        problems.append(
            "page_tokens={0} must be a positive multiple of the "
            "{1}-dtype sublane minimum {2}".format(
                page_tokens, jnp.dtype(dtype).name, sub
            )
        )
    if head_dim <= 0 or head_dim % lane != 0:
        problems.append(
            "head_dim={0} must be a positive multiple of the lane "
            "width {1}".format(head_dim, lane)
        )
    if problems:
        raise TileLegalityError(
            "paged-KV geometry illegal for Mosaic: " + "; ".join(problems)
        )
    return {"sublane": sub, "lane": lane}


def _grid_spec(num_scalar_prefetch, grid, in_specs, out_specs,
               scratch_shapes=()):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=num_scalar_prefetch,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=list(scratch_shapes),
    )


def _scratch(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


def _paged_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                  num_blocks, page_tokens, hkv, group, scale, window,
                  int8_scales):
    """One (slot, page) grid step of the online softmax.  ``rest`` is
    ``[ks_ref, vs_ref,] o_ref, acc_ref, m_ref, l_ref``."""
    if int8_scales:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(1)
    h = hkv * group
    t = page_tokens

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    base = j * t
    relevant = base < length
    if window:
        # the query sits at position length-1; pages entirely behind
        # the horizon (base + t <= length - window) contribute nothing
        relevant = jnp.logical_and(relevant, base + t > length - window)

    @pl.when(relevant)
    def _compute():
        q = q_ref[0]  # [H, D]
        k = k_ref[0].astype(q.dtype)  # [T, Hkv, D] (int8 converts bare)
        v = v_ref[0].astype(q.dtype)
        d = q.shape[-1]
        q3 = q.reshape(hkv, group, d)
        kh = jnp.swapaxes(k, 0, 1)  # [Hkv, T, D]
        logits = jax.lax.dot_general(
            q3, kh, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [Hkv, G, T]
        if int8_scales:
            ks = jnp.swapaxes(ks_ref[0][:, :, 0], 0, 1)  # [Hkv, T]
            logits = logits * ks[:, None, :]
        logits = logits * scale
        pos = base + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, t), 2
        )
        keep = pos < length
        if window:
            keep = jnp.logical_and(keep, pos >= length - window)
        logits = jnp.where(keep, logits, NEG_INF)
        lg = logits.reshape(h, t)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_cur = jnp.max(lg, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(lg - m_new)  # [H, T]
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        p3 = p.reshape(hkv, group, t)
        if int8_scales:
            vs = jnp.swapaxes(vs_ref[0][:, :, 0], 0, 1)  # [Hkv, T]
            p3 = p3 * vs[:, None, :]
        vh = jnp.swapaxes(v, 0, 1)  # [Hkv, T, D]
        pv = jax.lax.dot_general(
            p3.astype(v.dtype), vh, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [Hkv, G, D]
        acc_ref[...] = acc_ref[...] * alpha + pv.reshape(h, d)

    @pl.when(j == num_blocks - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, block_tables, lengths, *,
                    scale=None, window=0, k_scale_pool=None,
                    v_scale_pool=None, interpret=None):
    """Single-token decode attention over a paged KV pool.

    Args:
      q: ``[B, H, D]`` — one query per slot (the token being decoded,
        whose K/V the caller already wrote at position
        ``lengths[b] - 1`` of slot ``b``'s table span).
      k_pool, v_pool: ``[P, T, Hkv, D]`` physical page pools; ``Hkv``
        divides ``H`` (GQA).  int8 pools compose with the scale pools.
      block_tables: ``[B, NB]`` int32 page indices — slot ``b``'s
        logical block ``j`` lives in physical page
        ``block_tables[b, j]``.  Entries past the live length must
        still be VALID indices (the engine points idle/unused entries
        at the reserved trash page); they are masked, not skipped.
      lengths: ``[B]`` int32 — tokens visible to slot ``b``'s query
        (``>= 1``; the query attends positions ``[0, lengths[b])``,
        its own slot included).
      scale: logit scale (default ``D ** -0.5``).
      window: sliding-window width (0 = full causal) — pages fully
        behind the horizon are skipped, partial pages masked.
      k_scale_pool, v_scale_pool: ``[P, T, Hkv, 1]`` f32 dequant
        scales for int8 pools (per-position/per-head, the int8-KV
        cache layout).
      interpret: force/deny interpret mode (default: off-TPU).
    Returns ``[B, H, D]`` in ``q.dtype``.
    """
    if interpret is None:
        interpret = compat.pallas_interpret()
    b, h, d = q.shape
    p, t, hkv, dk = k_pool.shape
    assert dk == d, (q.shape, k_pool.shape)
    assert v_pool.shape == k_pool.shape, (k_pool.shape, v_pool.shape)
    if h % hkv != 0:
        raise ValueError(
            "query heads ({0}) must be a multiple of kv heads "
            "({1})".format(h, hkv)
        )
    nb = block_tables.shape[1]
    assert block_tables.shape == (b, nb), block_tables.shape
    assert lengths.shape == (b,), lengths.shape
    int8_scales = k_scale_pool is not None
    if int8_scales and v_scale_pool is None:
        raise ValueError("k_scale_pool needs v_scale_pool (and vice versa)")
    group = h // hkv
    scale = scale if scale is not None else d ** -0.5

    kernel = functools.partial(
        _paged_kernel,
        num_blocks=nb, page_tokens=t, hkv=hkv, group=group,
        scale=scale, window=int(window), int8_scales=int8_scales,
    )
    page_map = lambda bi, j, tbl, ln: (tbl[bi, j], 0, 0, 0)  # noqa: E731
    in_specs = [
        pl.BlockSpec((1, h, d), lambda bi, j, tbl, ln: (bi, 0, 0)),
        pl.BlockSpec((1, t, hkv, d), page_map),
        pl.BlockSpec((1, t, hkv, d), page_map),
    ]
    operands = [q, k_pool, v_pool]
    if int8_scales:
        in_specs += [
            pl.BlockSpec((1, t, hkv, 1), page_map),
            pl.BlockSpec((1, t, hkv, 1), page_map),
        ]
        operands += [k_scale_pool, v_scale_pool]
    grid_spec = _grid_spec(
        2,
        (b, nb),
        in_specs,
        pl.BlockSpec((1, h, d), lambda bi, j, tbl, ln: (bi, 0, 0)),
        scratch_shapes=[
            _scratch((h, d), jnp.float32),
            _scratch((h, 1), jnp.float32),
            _scratch((h, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=compat.pallas_compiler_params(
            ("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(lengths, jnp.int32), *operands)


def gather_pool(pool, block_tables, span=None):
    """Materialize per-slot contiguous banks from a paged pool:
    ``[P, T, Hkv, Dx]`` gathered through ``[B, NB]`` tables →
    ``[B, NB*T, Hkv, Dx]`` (sliced to ``span`` positions when given,
    so downstream einsum shapes match the contiguous layout's banks
    exactly — bit-identical masks and reductions)."""
    b, nb = block_tables.shape
    t = pool.shape[1]
    g = jnp.take(pool, block_tables.reshape(-1), axis=0)
    g = g.reshape((b, nb * t) + pool.shape[2:])
    return g[:, :span] if span is not None else g


def paged_gather_attention(q, k_pool, v_pool, block_tables, positions, *,
                           span=None, scale=None, window=0,
                           k_scale_pool=None, v_scale_pool=None):
    """Multi-token-query paged attention via gather + masked einsums.

    The canonical-position prefill and speculative-verify paths feed
    ``S > 1`` contiguous query rows per slot; they are compute-bound,
    so a transient gather of the slot's pages into contiguous banks
    (what the contiguous layout stored *permanently*) plus
    :func:`..attention.dot_attention` is the right tool — and reusing
    the exact einsum/mask graph keeps those paths bit-identical to the
    contiguous layout (the paged-vs-contiguous token-exactness tests
    rely on it).

    ``q`` is ``[B, S, H, D]``; ``positions`` ``[B, S]`` gives each
    query row's absolute cache position (its causal horizon).
    """
    from tensorflowonspark_tpu.ops.attention import dot_attention

    k = gather_pool(k_pool, block_tables, span)
    v = gather_pool(v_pool, block_tables, span)
    ks = (
        gather_pool(k_scale_pool, block_tables, span)
        if k_scale_pool is not None else None
    )
    vs = (
        gather_pool(v_scale_pool, block_tables, span)
        if v_scale_pool is not None else None
    )
    kpos = jnp.arange(k.shape[1])
    qpos = positions  # [B, S]
    vis = kpos[None, None, :] <= qpos[:, :, None]
    if window:
        vis = jnp.logical_and(
            vis, kpos[None, None, :] > qpos[:, :, None] - window
        )
    mask = jnp.where(vis, 0.0, -jnp.inf)[:, None]
    return dot_attention(
        q, k, v, causal=False, scale=scale, mask=mask,
        k_scale=ks, v_scale=vs,
    )
