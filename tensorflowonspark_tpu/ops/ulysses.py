"""Ulysses (DeepSpeed-style) sequence parallelism via all-to-all.

New TPU-first capability with no reference analogue (SURVEY.md §5
'Long-context / sequence parallelism: absent').

Idea: attention is independent across *heads* but global across
*sequence*.  So flip the sharding just around the attention op:

    [B, S/P, H, D]  --all_to_all-->  [B, S, H/P, D]   (heads sharded)
          attend over the full sequence locally
    [B, S, H/P, D]  --all_to_all-->  [B, S/P, H, D]   (seq sharded)

Two all-to-alls per layer ride the ICI all-to-all bandwidth (cheaper
than a full ring when H >= P); the local attention uses the flash
kernel on TPU, so the composition is "Ulysses outside, flash inside".

Requires ``num_heads % axis_size == 0``; otherwise use
:mod:`.ring_attention` (which has no head-count constraint).
"""

from jax import lax

from tensorflowonspark_tpu import compat
from tensorflowonspark_tpu.ops.attention import dot_attention
from tensorflowonspark_tpu.ops.flash_attention import flash_supported


def ulysses_attention(q, k, v, causal=True, scale=None, axis_name="seq",
                      local_impl="flash", block_q=1024, block_k=1024,
                      window=0):
    """Attention over sequence shards; call under ``shard_map``.

    Args:
      q, k, v: local shards ``[B, S_local, H, D]``.
      local_impl: attention used on the re-sharded full sequence:
        ``"flash"`` (pallas kernel — the default: after the all-to-all
        each device attends over the FULL sequence length, exactly
        where O(block) memory matters) or ``"dot"`` (XLA einsums; the
        numerics reference).  Falls back to ``dot`` for traced scale
        values or sequence lengths the kernels cannot tile (same
        contract as ring attention's fallback).
    Returns the local ``[B, S_local, H, D]`` output shard.
    """
    p = compat.axis_size(axis_name)
    h, hkv = q.shape[2], k.shape[2]
    if h % p != 0 or hkv % p != 0:
        raise ValueError(
            "ulysses needs query heads ({0}) and kv heads ({1}) "
            "divisible by the seq axis size ({2}); use ring attention "
            "instead".format(h, hkv, p)
        )
    if local_impl == "flash":
        s_val = scale if scale is not None else q.shape[-1] ** -0.5
        if not flash_supported(s_val, q.shape[1] * p, block_q, block_k):
            local_impl = "dot"

    def seq_to_heads(x):
        # [B, S/P, H, D] -> [B, S, H/P, D]
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # after the all-to-all the local sequence is GLOBAL, so the
    # window mask applies directly
    if local_impl == "flash":
        from tensorflowonspark_tpu.ops.flash_attention import flash_attention

        out = flash_attention(
            qh, kh, vh, causal=causal, scale=scale,
            block_q=block_q, block_k=block_k, window=window,
        )
    else:
        out = dot_attention(
            qh, kh, vh, causal=causal, scale=scale, window=window
        )
    return heads_to_seq(out)


def ulysses_attention_sharded(q, k, v, mesh, causal=True, scale=None,
                              axis_name="seq", local_impl="flash",
                              block_q=1024, block_k=1024, window=0):
    """Global-array entry point: shard_map wrapper usable inside jit
    (sequence dim sharded on ``axis_name``, batch on the data axes)."""
    from jax.sharding import PartitionSpec as P

    batch_axes = tuple(
        a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1
    ) or None
    spec = P(batch_axes, axis_name, None, None)

    def _local(ql, kl, vl):
        return ulysses_attention(
            ql, kl, vl, causal=causal, scale=scale, axis_name=axis_name,
            local_impl=local_impl, block_q=block_q, block_k=block_k,
            window=window,
        )

    return compat.shard_map(
        _local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
