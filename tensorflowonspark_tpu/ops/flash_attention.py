"""Blockwise (flash) attention as a pallas TPU kernel.

New TPU-first capability with no reference analogue (the reference
delegated all compute to TensorFlow, SURVEY.md §2 'Native-code reality
check'; long-context support is absent there, SURVEY.md §5).  This is
the single-chip building block that :mod:`.ring_attention` composes into
sequence parallelism.

Algorithm: FlashAttention-2-style online softmax.  The forward kernel
streams key/value blocks through VMEM against a resident query block,
keeping a running max ``m``, normalizer ``l``, and accumulator — O(seq)
memory instead of the O(seq²) logits matrix.  The backward pass is two
more pallas kernels (dq, and dk/dv) that recompute probabilities from
the saved log-sum-exp rather than storing them.

TPU mapping:
- grid = (batch, heads, q-blocks, k-blocks): the K/V *blocks* stream
  through VMEM via the trailing (sequential, "arbitrary") grid
  dimension while running state lives in VMEM scratch — only
  O(block) memory per core, so sequence length is HBM-bound, not
  VMEM-bound (full-array K/V blocks capped usable seq at ~8k);
- causal q/k block pairs that are fully masked are skipped with
  ``pl.when`` (no wasted MXU work on the upper triangle);
- the matmuls hit the MXU with ``preferred_element_type=f32`` (bf16
  operands stay MXU-native — no f32 upcast; softmax state alone is
  f32); block sizes default to 1024×1024 (swept fastest on v5e at
  head_dim 128) — multiples of the (8,128) f32 / (16,128) bf16 tiles;
- lse/delta tensors carry a trailing singleton lane axis
  ``(B, H, S, 1)``: Mosaic requires the last two block dims to be
  (8k, 128k) or equal to the array's;
- off-TPU (CPU tests) the same kernels run under ``interpret=True`` so
  numerics are verified against :func:`..attention.dot_attention`
  without TPU hardware (mirrors the reference's shrink-don't-mock test
  stance, SURVEY.md §4).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30  # finite mask sentinel: keeps exp() at 0 without NaNs


def _interpret():
    return jax.default_backend() != "tpu"


def _scratch(shape, dtype):
    """VMEM scratch allocation that also works in interpret mode."""
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


def _compiler_params():
    """Grid semantics for Mosaic: batch/heads/outer-block dims are
    embarrassingly parallel; only the trailing streaming dim (the
    online-softmax / gradient accumulation) is order-dependent.
    Declaring this lets the compiler schedule/pipeline the parallel
    dims freely instead of assuming a fully sequential grid."""
    from jax.experimental.pallas import tpu as pltpu

    # renamed TPUCompilerParams -> CompilerParams across jax versions
    params_cls = getattr(pltpu, "CompilerParams", None) or (
        pltpu.TPUCompilerParams
    )
    return params_cls(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
    )


def _causal_mask(qi, kj, block_q, block_k, window=0, q_offset=0):
    """Causal mask for block (qi, kj); ``window > 0`` additionally
    drops keys more than ``window - 1`` positions behind the query
    (sliding-window / local attention).  ``q_offset`` shifts the query
    positions — ring attention uses it for visiting kv chunks from
    ``q_offset`` positions earlier in the global sequence (the offset
    is static per ring distance, so each distance gets its own
    specialized kernel)."""
    qpos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    kpos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = qpos >= kpos
    if window:
        mask = jnp.logical_and(mask, kpos > qpos - window)
    return mask


def _block_relevant(qi, kj, block_q, block_k, causal, window, q_offset=0):
    """Whether block (qi, kj) contributes anything: causal skips blocks
    strictly above the diagonal; a window additionally skips blocks
    entirely behind the horizon — the compute saving that makes local
    attention O(S*W) instead of O(S^2/2)."""
    relevant = True
    if causal:
        relevant = kj * block_k < (qi + 1) * block_q + q_offset
    if window:
        relevant = jnp.logical_and(
            relevant,
            (kj + 1) * block_k > qi * block_q + q_offset - window + 1,
        )
    return relevant


def _diag_block(qi, jj, block_q, block_k):
    """Banded kv walk: j-th step visits kv block (diagonal - j).  The
    ONE definition both the kernels and the BlockSpec index maps use —
    fetch and compute must address the same block."""
    return ((qi + 1) * block_q - 1) // block_k - jj


def _q_band_block(kj, jj, block_q, block_k):
    """Banded q walk for dk/dv: j-th step visits q block
    (first-on-or-after-diagonal + j)."""
    return kj * block_k // block_q + jj


def _band_steps(window, block_a, block_b, total_b):
    """Grid size of the trailing (streamed) dim when windowed: how many
    ``block_b``-wide blocks a ``block_a``-wide resident block can touch
    under a ``window`` horizon (plus the diagonal spill).  Falling back
    to the full count means banding is off (window >= seq)."""
    band = (block_a + window - 2) // block_b + 2
    return min(total_b, band)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k, grid_steps,
                window=0, banded=False, q_offset=0):
    qi = pl.program_id(2)
    jj = pl.program_id(3)
    if banded:
        # only blocks inside the window band are ever fetched
        # (O(S*W) DMA, not O(S^2))
        kj = _diag_block(qi, jj, block_q, block_k)
        in_range = kj >= 0
    else:
        kj = jj
        in_range = True

    @pl.when(jj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    relevant = jnp.logical_and(
        in_range,
        _block_relevant(
            qi, kj, block_q, block_k, causal, window, q_offset
        ),
    )

    @pl.when(relevant)
    def _compute():
        # operands stay in their input dtype (bf16 on TPU): the MXU
        # multiplies bf16 natively with f32 accumulation via
        # preferred_element_type — upcasting to f32 first would run the
        # matmuls at the ~4x slower f32 rate.  Softmax state (m, l, p)
        # is f32 for stability; p is cast back to the operand dtype for
        # the PV matmul (FlashAttention-2's mixed-precision recipe).
        q = q_ref[0, 0]  # [block_q, d]
        k = k_ref[0, 0]  # [block_k, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k] f32
        if causal:
            s = jnp.where(
                _causal_mask(qi, kj, block_q, block_k, window, q_offset),
                s, NEG_INF,
            )
        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        m_scr[:, 0] = m_new
        l_scr[:, 0] = l_prev * alpha + jnp.sum(p, axis=1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jj == grid_steps - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0, 0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, :, 0] = m_scr[:, 0] + jnp.log(l_safe)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale, causal, block_q, block_k, grid_steps,
               window=0, banded=False, q_offset=0):
    qi = pl.program_id(2)
    jj = pl.program_id(3)
    if banded:
        kj = _diag_block(qi, jj, block_q, block_k)
        in_range = kj >= 0
    else:
        kj = jj
        in_range = True

    @pl.when(jj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    relevant = jnp.logical_and(
        in_range,
        _block_relevant(
            qi, kj, block_q, block_k, causal, window, q_offset
        ),
    )

    @pl.when(relevant)
    def _compute():
        # operand-dtype matmuls (see _fwd_kernel note); p/ds are f32
        # intermediates cast to the operand dtype at the MXU boundary
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0]  # [block_q]
        delta = delta_ref[0, 0, :, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = jnp.where(
                _causal_mask(qi, kj, block_q, block_k, window, q_offset),
                s, NEG_INF,
            )
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jj == grid_steps - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *,
                scale, causal, block_q, block_k, num_q_blocks,
                grid_steps, window=0, banded=False, q_offset=0):
    kj = pl.program_id(2)
    jj = pl.program_id(3)
    if banded:
        qi = _q_band_block(kj, jj, block_q, block_k)
        in_range = qi < num_q_blocks
    else:
        qi = jj
        in_range = True

    @pl.when(jj == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    relevant = jnp.logical_and(
        in_range,
        _block_relevant(
            qi, kj, block_q, block_k, causal, window, q_offset
        ),
    )

    @pl.when(relevant)
    def _compute():
        # operand-dtype matmuls (see _fwd_kernel note)
        k = k_ref[0, 0]  # [block_k, d]
        v = v_ref[0, 0]
        q = q_ref[0, 0]  # [block_q, d]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = jnp.where(
                _causal_mask(qi, kj, block_q, block_k, window, q_offset),
                s, NEG_INF,
            )
        p = jnp.exp(s - lse[:, None])  # [block_q, block_k] f32
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jj == grid_steps - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _fit_block(requested, seq_len):
    """Largest lane-aligned block <= requested that divides seq_len
    (so raising the *default* block size never breaks a sequence length
    that worked before; S=1536 fits 768, not 1024)."""
    b = min(requested, seq_len)
    if seq_len % b == 0:
        return b
    b -= b % 128  # lane-aligned candidates only
    while b >= 128:
        if seq_len % b == 0:
            return b
        b -= 128
    return None


def flash_supported(scale, seq_len, block_q, block_k):
    """Whether the pallas kernels can run this shape/config: seq_len
    must tile by a lane-aligned block under both requested sizes, and
    scale must be concrete (custom_vjp nondiff args).  Head dim needs
    no gate — Mosaic compiles arbitrary D via relayout (verified on
    v5e down to D=20).  Shared by the ring and Ulysses fallbacks so
    the \"can flash run\" predicate lives in one place."""
    return (
        _fit_block(block_q, seq_len) is not None
        and _fit_block(block_k, seq_len) is not None
        and not isinstance(scale, jax.core.Tracer)
    )


def _block_sizes(seq_len, block_q, block_k):
    bq = _fit_block(block_q, seq_len)
    bk = _fit_block(block_k, seq_len)
    if bq is None or bk is None:
        raise ValueError(
            "flash attention needs seq_len {0} divisible by a "
            "lane-aligned block <= the requested sizes; pad the "
            "sequence or pass block_q/block_k".format(seq_len)
        )
    return bq, bk


def _fwd(q, k, v, scale, causal, block_q, block_k, window=0):
    # [B,S,H,D] -> [B,H,S,D]: heads become a grid dim, seq stays blocked
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    out_t, lse = _fwd_core(
        qt, kt, vt, scale, causal, block_q, block_k, window=window
    )
    out = jnp.swapaxes(out_t, 1, 2)
    return out, (q, k, v, out, lse)


def _fwd_core(qt, kt, vt, scale, causal, block_q, block_k, out_dtype=None,
              window=0, q_offset=0):
    """Forward on ``[B,H,S,D]`` (transposed) tensors; returns
    ``(out_t [B,H,S,D], lse [B,H,S,1])``.  Split out so callers that
    loop over kv chunks (ring attention) can keep everything in the
    kernel layout and transpose exactly once.  ``out_dtype`` lets such
    callers take the partial outputs in f32 straight from the kernel's
    f32 accumulator (one final downcast instead of one per chunk).

    Grouped-query attention: ``kt``/``vt`` may carry ``Hkv`` heads with
    ``H % Hkv == 0`` — the kv BlockSpec index maps divide the q-head
    grid index by the group size, so each kv head's blocks stream to
    its whole query group with no repeated-kv materialization."""
    b, h, s, d = qt.shape
    g = h // kt.shape[1]
    bq, bk = _block_sizes(s, block_q, block_k)
    # windowed: stream only the band of kv blocks the horizon can
    # touch, descending from the diagonal — blocks outside the window
    # are never DMA'd (banding off when the band wouldn't shrink)
    # banding assumes the zero-offset diagonal walk; offset chunks
    # (ring hops) use the full grid with pl.when skipping
    steps = _band_steps(window, bq, bk, s // bk) if (
        causal and window and q_offset == 0
    ) else s // bk
    banded = steps < s // bk
    grid = (b, h, s // bq, steps)

    def _kv_idx(bi, hi, qi, jj, g=g):
        if banded:
            kj = _diag_block(qi, jj, bq, bk)
            return (bi, hi // g, jnp.maximum(kj, 0), 0)
        return (bi, hi // g, jj, 0)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=bq, block_k=bk, grid_steps=steps, window=window,
        banded=banded, q_offset=q_offset,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bk, d), _kv_idx),
            pl.BlockSpec((1, 1, bk, d), _kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), out_dtype or qt.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((bq, 1), jnp.float32),  # running max
            _scratch((bq, 1), jnp.float32),  # running normalizer
            _scratch((bq, d), jnp.float32),  # output accumulator
        ],
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(qt, kt, vt)
    return out, lse


def _bwd(scale, causal, block_q, block_k, window, residuals, dout):
    q, k, v, out, lse = residuals
    qt, kt, vt, ot, dot_ = (
        jnp.swapaxes(x, 1, 2) for x in (q, k, v, out, dout)
    )
    # delta_i = rowsum(dout * out): the softmax-jacobian correction term
    delta = jnp.sum(
        dot_.astype(jnp.float32) * ot.astype(jnp.float32), axis=-1
    )[..., None]  # [B,H,S,1] (lane axis; see lse layout note)
    dqt, dkt, dvt = _bwd_core(
        scale, causal, block_q, block_k, qt, kt, vt, dot_, lse, delta,
        window=window,
    )
    return (
        jnp.swapaxes(dqt, 1, 2),
        jnp.swapaxes(dkt, 1, 2),
        jnp.swapaxes(dvt, 1, 2),
    )


def _bwd_core(scale, causal, block_q, block_k, qt, kt, vt, dot_, lse,
              delta, window=0, q_offset=0):
    """Backward on ``[B,H,S,D]`` (transposed) tensors with the
    loop-invariant ``delta`` precomputed by the caller; returns
    ``(dqt, dkt, dvt)`` in the same layout (``dkt``/``dvt`` carry the
    kv head count).  Ring attention calls this once per visiting chunk,
    hoisting delta and the q/dout transposes out of its hop loop.

    GQA backward: dq uses the same ``hi // g`` kv index maps as the
    forward; dk/dv are computed PER QUERY HEAD (the q-head grid dim is
    parallel, so different group members must not write one kv block)
    and group-summed outside the kernel."""
    b, h, s, d = qt.shape
    hkv = kt.shape[1]
    g = h // hkv
    bq, bk = _block_sizes(s, block_q, block_k)
    # banded grids mirror the forward (see _fwd_core): dq streams kv
    # blocks down from the diagonal, dk/dv stream q blocks up from it
    band_ok = causal and window and q_offset == 0
    kv_steps = _band_steps(window, bq, bk, s // bk) if band_ok else s // bk
    kv_banded = kv_steps < s // bk
    q_steps = _band_steps(window, bk, bq, s // bq) if band_ok else s // bq
    q_banded = q_steps < s // bq

    def _kv_idx(bi, hi, qi, jj, g=g):
        if kv_banded:
            kj = _diag_block(qi, jj, bq, bk)
            return (bi, hi // g, jnp.maximum(kj, 0), 0)
        return (bi, hi // g, jj, 0)

    dq_kernel = functools.partial(
        _dq_kernel, scale=scale, causal=causal,
        block_q=bq, block_k=bk, grid_steps=kv_steps, window=window,
        banded=kv_banded, q_offset=q_offset,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, s // bq, kv_steps),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bk, d), _kv_idx),
            pl.BlockSpec((1, 1, bk, d), _kv_idx),
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, bq, d), lambda bi, hi, qi, kj: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), qt.dtype),
        scratch_shapes=[_scratch((bq, d), jnp.float32)],
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(qt, kt, vt, dot_, lse, delta)

    def _q_idx(bi, hi, kj, jj):
        if q_banded:
            qi = _q_band_block(kj, jj, bq, bk)
            return (bi, hi, jnp.minimum(qi, s // bq - 1), 0)
        return (bi, hi, jj, 0)

    dkv_kernel = functools.partial(
        _dkv_kernel, scale=scale, causal=causal,
        block_q=bq, block_k=bk, num_q_blocks=s // bq,
        grid_steps=q_steps, window=window, banded=q_banded,
        q_offset=q_offset,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, s // bk, q_steps),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), _q_idx),
            pl.BlockSpec(
                (1, 1, bk, d),
                lambda bi, hi, kj, qi, g=g: (bi, hi // g, kj, 0),
            ),
            pl.BlockSpec(
                (1, 1, bk, d),
                lambda bi, hi, kj, qi, g=g: (bi, hi // g, kj, 0),
            ),
            pl.BlockSpec((1, 1, bq, d), _q_idx),
            pl.BlockSpec((1, 1, bq, 1), _q_idx),
            pl.BlockSpec((1, 1, bq, 1), _q_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, kj, qi: (bi, hi, kj, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, kj, qi: (bi, hi, kj, 0)),
        ],
        out_shape=[
            # per-q-head partials stay f32 when they will be
            # group-summed (casting each to bf16 first would round g
            # times; MHA keeps the operand dtype as before)
            jax.ShapeDtypeStruct(
                (b, h, s, d), jnp.float32 if g > 1 else kt.dtype
            ),
            jax.ShapeDtypeStruct(
                (b, h, s, d), jnp.float32 if g > 1 else vt.dtype
            ),
        ],
        scratch_shapes=[
            _scratch((bk, d), jnp.float32),
            _scratch((bk, d), jnp.float32),
        ],
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(qt, kt, vt, dot_, lse, delta)

    if g > 1:
        # per-q-head f32 contributions -> kv heads, ONE final downcast
        dk = dk.reshape(b, hkv, g, s, d).sum(2).astype(kt.dtype)
        dv = dv.reshape(b, hkv, g, s, d).sum(2).astype(vt.dtype)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, window):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k, window)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, window):
    return _fwd(q, k, v, scale, causal, block_q, block_k, window)


_flash.defvjp(_flash_fwd, _bwd)


def flash_attention(q, k, v, causal=True, scale=None, block_q=1024,
                    block_k=1024, window=0):
    """Flash attention on ``[B, S, H, D]`` tensors (self-attention:
    q/k/v share the sequence length).

    Grouped-query attention: k/v may carry ``Hkv`` heads with
    ``H % Hkv == 0`` (each kv head serves ``H/Hkv`` query heads) — the
    kernels stream each kv head's blocks to its whole query group, no
    repeated-kv materialization.

    ``window > 0`` is sliding-window (local) attention: position ``i``
    attends to ``[i-window+1, i]``; requires ``causal``.  Blocks
    entirely behind the horizon are skipped, so compute is O(S·window)
    instead of O(S²/2).

    Differentiable via custom pallas backward kernels.  ``seq_len`` must
    divide by the (clamped) block sizes — pad upstream if not.  The
    1024x1024 default blocks measured fastest on v5e at S=2048 (+9%
    over 512x512; 2048-wide blocks overflow VMEM).
    """
    if k.shape != v.shape:
        raise ValueError(
            "k/v must match, got {0} {1}".format(k.shape, v.shape)
        )
    b, s, h, d = q.shape
    bk_, sk_, hkv, dk_ = k.shape
    if (b, s, d) != (bk_, sk_, dk_) or h % hkv != 0:
        raise ValueError(
            "flash attention is self-attention-shaped with grouped kv: "
            "q [B,S,H,D] vs k/v [B,S,Hkv,D], H % Hkv == 0; got q={0} "
            "k={1}".format(q.shape, k.shape)
        )
    if window:
        if window < 0:
            raise ValueError(
                "window must be positive, got {0}".format(window)
            )
        if not causal:
            raise ValueError("window attention requires causal=True")
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash(
        q, k, v, float(scale), bool(causal), block_q, block_k, int(window)
    )
