"""Weight-only int8 / int4 quantization for inference and decode.

New TPU-first capability with no reference analogue (the reference
serves f32 TF SavedModels; `/root/reference/src/main/scala/com/yahoo/
tensorflowonspark/TFModel.scala` has no quantized path).  Rationale:
single-token decode and small-batch serving are HBM-bandwidth-bound on
the *weight read* (BASELINE.md decode row), and the MXU dequantizes
int8 operands on the fly — storing matmul weights as int8 + per-channel
scales halves their HBM traffic.  Measured on the flagship decode
config: 1.48× on an isolated HBM-bound weight-read probe; the
activations, cache, and numerics-sensitive small tensors stay bf16.

Scheme: symmetric per-channel int8.  For a flax kernel the contraction
axes always precede the output axes, so scales are computed over every
axis but the last — constant along all contracted axes, which is what
lets ``(x @ q) * scale`` factor out of the dot exactly.  Embedding
tables are a lookup, not a contraction, so they quantize per ROW (each
token id gets its own scale).  1-D leaves (norm gains) and tiny leaves
stay float: they are numerics-critical and contribute nothing to
bandwidth.

Usage::

    qparams = quantize_tree(params)        # QTensor leaves for weights
    tokens  = generate(model, qparams, ...)  # dequant fused per step

``generate``/serving detect :class:`QTensor` leaves and dequantize
INSIDE the decode step under ``lax.optimization_barrier`` — without the
barrier XLA may hoist the int8→bf16 convert out of the scan and
materialize full-precision weights once, silently forfeiting the
bandwidth win.

**int4 (ISSUE 12).**  Decode is bandwidth-bound on the weight read
(int8 already measured 1.64× with int8-KV at long cache), so halving
it again is a direct tok/s multiplier: :class:`QTensor4` stores matmul
weights as signed int4 codes packed TWO PER BYTE along the flattened
contraction axis, with **group-wise scales** — one f32 scale per
``group_size`` contraction rows per output channel.  Group scales are
what keep 15 levels usable: a per-channel int4 scale would clip any
channel whose magnitudes vary along the contraction.  Because the
scale varies ALONG the contraction, the dequant cannot factor out of
the dot like int8's per-channel scales — it fuses into the matmul
*epilogue* instead: the unpack + scale runs under the same
``optimization_barrier`` contract, so the weights cross HBM as packed
nibbles every decode step and XLA fuses the widening into the operand
read.  ``quantize_tree_int4`` targets the dense matmul kernels;
embedding (a gather, not a contraction) and expert-stacked MoE leaves
keep the int8 scheme — the int8 path itself is byte-for-byte untouched
(guarded in tests/test_quantize.py).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QTensor(NamedTuple):
    """Symmetric per-channel int8 weight: ``w ≈ q * scale``."""

    q: jax.Array  # int8, original shape
    scale: jax.Array  # f32, keepdims-reduced over the quantized axes


@jax.tree_util.register_pytree_node_class
class QTensor4(object):
    """Symmetric group-wise int4 weight, packed two codes per byte.

    ``packed`` is ``uint8 [Kp // 2, N]`` where ``Kp`` is the flattened
    contraction length padded up to ``group_size`` (consecutive
    contraction rows share a byte: row ``2i`` in the low nibble, row
    ``2i + 1`` in the high nibble); ``scale`` is ``f32 [Kp //
    group_size, N]``.  ``shape``/``group_size`` ride as static pytree
    aux data, so a :class:`QTensor4` traces through jit/donation like
    any array pair.
    """

    __slots__ = ("packed", "scale", "shape", "group_size")

    def __init__(self, packed, scale, shape, group_size):
        self.packed = packed
        self.scale = scale
        self.shape = tuple(shape)
        self.group_size = int(group_size)

    def tree_flatten(self):
        return (self.packed, self.scale), (self.shape, self.group_size)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    def __repr__(self):
        return "QTensor4(shape={0}, group_size={1})".format(
            self.shape, self.group_size
        )


def _is_q(x):
    return isinstance(x, QTensor)


def _is_q4(x):
    return isinstance(x, QTensor4)


def _is_any_q(x):
    return isinstance(x, (QTensor, QTensor4))


def quantize_leaf(w, reduce_axes):
    """Quantize one float array to int8 over ``reduce_axes``."""
    wf = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale)


def dequantize_leaf(qt, dtype=jnp.bfloat16):
    return qt.q.astype(dtype) * qt.scale.astype(dtype)


# ----------------------------------------------------------------------
# int4: group-wise scales, two codes per byte
# ----------------------------------------------------------------------


def pack_int4(q):
    """Pack signed int4 codes (int8 values in ``[-8, 7]``) along axis 0
    into ``uint8`` nibbles: row ``2i`` low, row ``2i + 1`` high.  Axis
    0 must be even (the quantizer's group padding guarantees it)."""
    q = jnp.asarray(q, jnp.int8)
    if q.shape[0] % 2:
        raise ValueError(
            "pack_int4 needs an even leading dim, got {0}".format(q.shape)
        )
    u = jnp.asarray(q, jnp.uint8) & jnp.uint8(0xF)  # two's-complement nibble
    return (u[0::2] | (u[1::2] << 4)).astype(jnp.uint8)


def unpack_int4(packed):
    """Inverse of :func:`pack_int4`: ``uint8 [K/2, ...]`` → signed int8
    codes ``[K, ...]`` in ``[-8, 7]`` (exact round trip, tested incl.
    the nibble sign boundary at -8/7)."""
    p = jnp.asarray(packed, jnp.uint8)
    lo = (p & jnp.uint8(0xF)).astype(jnp.int8)
    hi = (p >> 4).astype(jnp.int8)
    sign = lambda n: jnp.where(n >= 8, n - 16, n)  # noqa: E731
    pair = jnp.stack([sign(lo), sign(hi)], axis=1)  # [K/2, 2, ...]
    return pair.reshape((p.shape[0] * 2,) + p.shape[1:]).astype(jnp.int8)


def quantize_leaf_int4(w, group_size=64):
    """Quantize one float array to packed int4 with group-wise scales.

    The array is viewed as ``[K, N]`` (``N`` the last axis — the flax
    kernel output channels; ``K`` the flattened contraction axes) and
    split into contraction groups of ``group_size`` rows; each
    ``(group, output-channel)`` pair gets its own symmetric scale over
    the 15-level code book ``[-7, 7]``.  ``K`` pads up to a whole
    group (zero rows — odd channel counts round-trip exactly, the pad
    is sliced back off at dequant)."""
    g = int(group_size)
    if g < 2 or g % 2:
        raise ValueError(
            "group_size must be an even int >= 2, got {0}".format(group_size)
        )
    wf = jnp.asarray(w, jnp.float32)
    shape = wf.shape
    n = shape[-1]
    k = 1
    for s in shape[:-1]:
        k *= s
    w2 = wf.reshape(k, n)
    kp = ((k + g - 1) // g) * g
    if kp != k:
        w2 = jnp.concatenate(
            [w2, jnp.zeros((kp - k, n), jnp.float32)], axis=0
        )
    wg = w2.reshape(kp // g, g, n)
    amax = jnp.max(jnp.abs(wg), axis=1, keepdims=True)  # [G, 1, N]
    scale = jnp.maximum(amax, 1e-12) / 7.0
    q = jnp.clip(jnp.round(wg / scale), -7, 7).astype(jnp.int8)
    return QTensor4(
        pack_int4(q.reshape(kp, n)), scale[:, 0, :], shape, g
    )


def dequantize_leaf_int4(qt, dtype=jnp.bfloat16):
    """Unpack + group-scale a :class:`QTensor4` back to ``dtype`` at
    its original shape — the matmul-epilogue dequant (the caller pins
    it in place with ``optimization_barrier``, see
    :func:`dequantize_tree`)."""
    g = qt.group_size
    q = unpack_int4(qt.packed)  # [Kp, N] int8
    kp, n = q.shape
    w = q.reshape(kp // g, g, n).astype(jnp.float32) * qt.scale[:, None, :]
    k = 1
    for s in qt.shape[:-1]:
        k *= s
    return w.reshape(kp, n)[:k].reshape(qt.shape).astype(dtype)


def quantize_tree(params, min_size=16384, embed_key="embedding",
                  expert_keys=("wi", "wg", "wo")):
    """Quantize every matmul-sized weight in a param pytree.

    Leaves with ``ndim >= 2`` and ``size >= min_size`` become
    :class:`QTensor`; everything else passes through unchanged.  Leaves
    whose path contains ``embed_key`` reduce over the last axis
    (per-row scales — lookups have no contraction).  3-D leaves named
    in ``expert_keys`` are expert-STACKED MoE weights ``[E, D, M]``:
    axis 0 is a batch of independent matmuls, not a contraction, so
    each expert gets its own scales (reduce axis 1 only — sharing one
    scale across experts would inflate the error of any expert whose
    magnitudes sit below the loudest one's).  All others reduce over
    every axis but the last (constant along the contracted axes of any
    flax kernel, where contraction axes precede output axes).
    """

    def _one(path, w):
        if _is_any_q(w):
            # already quantized: pass through unchanged (descending into
            # the QTensor would re-quantize large float scale leaves —
            # e.g. an embedding's [V, 1] scales — nesting QTensors and
            # breaking dequantize later); double application is a no-op
            return w
        if not hasattr(w, "ndim") or w.ndim < 2:
            return w
        if w.size < min_size or not jnp.issubdtype(w.dtype, jnp.floating):
            return w
        names = [str(getattr(k, "key", k)) for k in path]
        if any(embed_key in n for n in names):
            return quantize_leaf(w, reduce_axes=(w.ndim - 1,))
        if w.ndim == 3 and names and names[-1] in expert_keys:
            return quantize_leaf(w, reduce_axes=(1,))
        return quantize_leaf(w, reduce_axes=tuple(range(w.ndim - 1)))

    # is_leaf=_is_any_q: QTensor is itself a pytree (NamedTuple) —
    # without the leaf predicate, tree_map would descend into an
    # already-quantized tree and hand _one the raw q/scale children (a
    # large float scale, e.g. an embedding's [V, 1], would then
    # re-quantize into a NESTED QTensor that crashes dequantize)
    return jax.tree_util.tree_map_with_path(_one, params, is_leaf=_is_any_q)


def quantize_tree_int4(params, group_size=64, min_size=16384,
                       embed_key="embedding", expert_keys=("wi", "wg", "wo")):
    """int4 twin of :func:`quantize_tree` (the ``weights="int4"``
    deployment): dense matmul kernels become packed group-wise
    :class:`QTensor4`; embedding leaves (a gather — per-row int8 stays
    the right scheme) and expert-stacked MoE leaves (per-expert scales)
    keep the int8 path; everything else passes through.  A mixed
    int4/int8 tree dequantizes through the one :func:`dequantize_tree`.
    """

    def _one(path, w):
        if _is_any_q(w):
            return w
        if not hasattr(w, "ndim") or w.ndim < 2:
            return w
        if w.size < min_size or not jnp.issubdtype(w.dtype, jnp.floating):
            return w
        names = [str(getattr(k, "key", k)) for k in path]
        if any(embed_key in n for n in names):
            return quantize_leaf(w, reduce_axes=(w.ndim - 1,))
        if w.ndim == 3 and names and names[-1] in expert_keys:
            return quantize_leaf(w, reduce_axes=(1,))
        return quantize_leaf_int4(w, group_size=group_size)

    return jax.tree_util.tree_map_with_path(_one, params, is_leaf=_is_any_q)


def is_quantized(params):
    """True if any leaf of ``params`` is a :class:`QTensor` /
    :class:`QTensor4`."""
    return any(
        _is_any_q(x) for x in jax.tree.leaves(params, is_leaf=_is_any_q)
    )


def quantization_of(params):
    """The tree's weight scheme: ``"int4"`` when any packed leaf is
    present (mixed trees count as int4 — that's the deployment that
    produced them), ``"int8"`` for pure :class:`QTensor` trees, else
    ``None``.  The hot-swap ingest path re-quantizes with the SAME
    scheme the live decoder serves."""
    leaves = jax.tree.leaves(params, is_leaf=_is_any_q)
    if any(_is_q4(x) for x in leaves):
        return "int4"
    if any(_is_q(x) for x in leaves):
        return "int8"
    return None


def dequantize_tree(params, dtype=jnp.bfloat16, barrier=True):
    """Materialize a float param tree from a (partially) quantized one.

    With ``barrier=True`` each quantized leaf passes through
    ``lax.optimization_barrier`` first, pinning the dequant to the
    surrounding trace position (inside a decode scan body) so XLA
    cannot hoist it out and cache bf16 weights — the int8 (or packed
    int4) HBM read IS the optimization.
    """

    def _one(x):
        if _is_q4(x):
            if barrier:
                packed, scale = jax.lax.optimization_barrier(
                    (x.packed, x.scale)
                )
                x = QTensor4(packed, scale, x.shape, x.group_size)
            return dequantize_leaf_int4(x, dtype)
        if not _is_q(x):
            return x
        if barrier:
            x = QTensor(*jax.lax.optimization_barrier(tuple(x)))
        return dequantize_leaf(x, dtype)

    return jax.tree.map(_one, params, is_leaf=_is_any_q)


def quantization_error(params, qparams):
    """Max relative error per quantized leaf (diagnostics/tests)."""
    out = {}
    flat = jax.tree_util.tree_flatten_with_path(
        qparams, is_leaf=_is_any_q
    )[0]
    orig = dict(jax.tree_util.tree_flatten_with_path(params)[0])
    for path, leaf in flat:
        if _is_any_q(leaf):
            w = jnp.asarray(orig[path], jnp.float32)
            deq = (
                dequantize_leaf_int4(leaf, jnp.float32) if _is_q4(leaf)
                else dequantize_leaf(leaf, jnp.float32)
            )
            err = jnp.max(jnp.abs(deq - w))
            denom = jnp.max(jnp.abs(w))
            out[jax.tree_util.keystr(path)] = float(err / denom)
    return out
