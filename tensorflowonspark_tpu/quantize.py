"""Weight-only int8 quantization for inference and decode.

New TPU-first capability with no reference analogue (the reference
serves f32 TF SavedModels; `/root/reference/src/main/scala/com/yahoo/
tensorflowonspark/TFModel.scala` has no quantized path).  Rationale:
single-token decode and small-batch serving are HBM-bandwidth-bound on
the *weight read* (BASELINE.md decode row), and the MXU dequantizes
int8 operands on the fly — storing matmul weights as int8 + per-channel
scales halves their HBM traffic.  Measured on the flagship decode
config: 1.48× on an isolated HBM-bound weight-read probe; the
activations, cache, and numerics-sensitive small tensors stay bf16.

Scheme: symmetric per-channel int8.  For a flax kernel the contraction
axes always precede the output axes, so scales are computed over every
axis but the last — constant along all contracted axes, which is what
lets ``(x @ q) * scale`` factor out of the dot exactly.  Embedding
tables are a lookup, not a contraction, so they quantize per ROW (each
token id gets its own scale).  1-D leaves (norm gains) and tiny leaves
stay float: they are numerics-critical and contribute nothing to
bandwidth.

Usage::

    qparams = quantize_tree(params)        # QTensor leaves for weights
    tokens  = generate(model, qparams, ...)  # dequant fused per step

``generate``/serving detect :class:`QTensor` leaves and dequantize
INSIDE the decode step under ``lax.optimization_barrier`` — without the
barrier XLA may hoist the int8→bf16 convert out of the scan and
materialize full-precision weights once, silently forfeiting the
bandwidth win.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QTensor(NamedTuple):
    """Symmetric per-channel int8 weight: ``w ≈ q * scale``."""

    q: jax.Array  # int8, original shape
    scale: jax.Array  # f32, keepdims-reduced over the quantized axes


def _is_q(x):
    return isinstance(x, QTensor)


def quantize_leaf(w, reduce_axes):
    """Quantize one float array to int8 over ``reduce_axes``."""
    wf = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale)


def dequantize_leaf(qt, dtype=jnp.bfloat16):
    return qt.q.astype(dtype) * qt.scale.astype(dtype)


def quantize_tree(params, min_size=16384, embed_key="embedding",
                  expert_keys=("wi", "wg", "wo")):
    """Quantize every matmul-sized weight in a param pytree.

    Leaves with ``ndim >= 2`` and ``size >= min_size`` become
    :class:`QTensor`; everything else passes through unchanged.  Leaves
    whose path contains ``embed_key`` reduce over the last axis
    (per-row scales — lookups have no contraction).  3-D leaves named
    in ``expert_keys`` are expert-STACKED MoE weights ``[E, D, M]``:
    axis 0 is a batch of independent matmuls, not a contraction, so
    each expert gets its own scales (reduce axis 1 only — sharing one
    scale across experts would inflate the error of any expert whose
    magnitudes sit below the loudest one's).  All others reduce over
    every axis but the last (constant along the contracted axes of any
    flax kernel, where contraction axes precede output axes).
    """

    def _one(path, w):
        if _is_q(w):
            # already quantized: pass through unchanged (descending into
            # the QTensor would re-quantize large float scale leaves —
            # e.g. an embedding's [V, 1] scales — nesting QTensors and
            # breaking dequantize later); double application is a no-op
            return w
        if not hasattr(w, "ndim") or w.ndim < 2:
            return w
        if w.size < min_size or not jnp.issubdtype(w.dtype, jnp.floating):
            return w
        names = [str(getattr(k, "key", k)) for k in path]
        if any(embed_key in n for n in names):
            return quantize_leaf(w, reduce_axes=(w.ndim - 1,))
        if w.ndim == 3 and names and names[-1] in expert_keys:
            return quantize_leaf(w, reduce_axes=(1,))
        return quantize_leaf(w, reduce_axes=tuple(range(w.ndim - 1)))

    # is_leaf=_is_q: QTensor is itself a pytree (NamedTuple) — without
    # the leaf predicate, tree_map would descend into an already-
    # quantized tree and hand _one the raw q/scale children (a large
    # float scale, e.g. an embedding's [V, 1], would then re-quantize
    # into a NESTED QTensor that crashes dequantize)
    return jax.tree_util.tree_map_with_path(_one, params, is_leaf=_is_q)


def is_quantized(params):
    """True if any leaf of ``params`` is a :class:`QTensor`."""
    return any(
        _is_q(x) for x in jax.tree.leaves(params, is_leaf=_is_q)
    )


def dequantize_tree(params, dtype=jnp.bfloat16, barrier=True):
    """Materialize a float param tree from a (partially) quantized one.

    With ``barrier=True`` each int8 leaf passes through
    ``lax.optimization_barrier`` first, pinning the dequant to the
    surrounding trace position (inside a decode scan body) so XLA
    cannot hoist it out and cache bf16 weights — the int8 HBM read IS
    the optimization.
    """

    def _one(x):
        if not _is_q(x):
            return x
        if barrier:
            x = QTensor(*jax.lax.optimization_barrier(tuple(x)))
        return dequantize_leaf(x, dtype)

    return jax.tree.map(_one, params, is_leaf=_is_q)


def quantization_error(params, qparams):
    """Max relative error per quantized leaf (diagnostics/tests)."""
    out = {}
    flat = jax.tree_util.tree_flatten_with_path(
        qparams, is_leaf=_is_q
    )[0]
    orig = dict(jax.tree_util.tree_flatten_with_path(params)[0])
    for path, leaf in flat:
        if _is_q(leaf):
            w = jnp.asarray(orig[path], jnp.float32)
            err = jnp.max(
                jnp.abs(dequantize_leaf(leaf, jnp.float32) - w)
            )
            denom = jnp.max(jnp.abs(w))
            out[jax.tree_util.keystr(path)] = float(err / denom)
    return out
