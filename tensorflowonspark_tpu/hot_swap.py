"""Zero-downtime serving lifecycle: validated live weight hot-swap.

Production fleets never stop to redeploy, but until this module the
ServingEngine served one frozen weight set for its whole life —
pushing a new checkpoint meant tearing the engine down and dropping
every in-flight request (ROADMAP item 5).  This is the serving-side
counterpart of PR 1's training auto-resume, in the spirit of
TF-Replicator's "researchers never restart the fleet" contract and
the reference's long-lived-cluster model: the fleet stays up, the
weights move.

The plane has three parts (docs/serving.md "Live weight swap &
rollback"):

- **publish** — training publishes step-numbered serving exports with
  :func:`~tensorflowonspark_tpu.checkpoint.publish_for_serving`
  (atomic: temp dir + rename, manifest written last), so a poller can
  never observe a torn checkpoint;
- **watch + validate** — :class:`CheckpointWatcher` polls the root
  for new steps and walks each candidate through the validation
  stages below; a checkpoint that fails ANY stage is **quarantined**
  with a typed reason (a ``quarantine.json`` marker in the step
  directory — it is never offered again, and serving continues on
  the old generation):

  1. *manifest* — present, parseable, ``complete: true``
     (``bad_manifest`` / ``incomplete``);
  2. *load* — the orbax restore itself; truncated/corrupt array
     files surface here (``load_failed``);
  3. *tree/shape/dtype* — the loaded tree against the live model's
     :meth:`~tensorflowonspark_tpu.models.transformer.SlotDecoder.
     param_spec` census: structure (``tree_mismatch``), per-leaf
     shapes (``shape_mismatch``), dtype KIND (``dtype_mismatch`` —
     exact dtype is not required, ingest re-casts/re-quantizes);
  4. *canary* — one forward pass off the hot path
     (``canary_failed``), when the watcher carries a ``canary_fn``.

  Ingest (the orbax load + validation) runs on the watcher's
  background thread by default, so a slow store never stalls decode
  (the ``slow_ingest`` chaos fault pins this down);
- **swap** — the ServingEngine drains admissions for the length of
  the swap transaction, quiesces in-flight requests through the PR 4
  watchdog teardown/re-admit path (reused for PLANNED swaps, not
  just wedges — committed tokens are preserved exactly), installs
  the new generation via :meth:`SlotDecoder.swap_weights` (int8
  re-quantization on ingest, prefix cache flushed, no recompiles —
  avals are identical by construction), then runs a post-install
  canary.  The previous generation stays **resident** (params are
  never donated through the jitted programs) until the new one
  serves ``rollback_window`` clean requests; a post-swap canary
  failure or an error spike during that probation flips back
  automatically and quarantines the offending step.

Every transition is telemetry (docs/observability.md): spans
``swap_ingest``/``swap``; marks ``checkpoint_quarantined``,
``swap_requeue``, ``swap_apply``, ``swap_commit``, ``swap_rollback``;
counters ``serving.swaps`` / ``serving.swap_commits`` /
``serving.swap_rollbacks`` / ``serving.checkpoints_quarantined``; and
the ``serving.weight_generation`` gauge.
"""

import logging
import os
import threading
import time

from tensorflowonspark_tpu import telemetry

logger = logging.getLogger(__name__)

#: Typed quarantine marker written into a rejected step directory —
#: its presence keeps the watcher from ever re-offering the step.
QUARANTINE_NAME = "quarantine.json"

#: validation failure kinds, in stage order (module docstring)
VALIDATION_KINDS = (
    "bad_manifest", "incomplete", "load_failed", "tree_mismatch",
    "shape_mismatch", "dtype_mismatch", "canary_failed",
)


class SwapError(Exception):
    """Base for hot-swap plane failures."""


class CheckpointRejected(SwapError):
    """A checkpoint failed validation.  Carries the typed ``kind``
    (one of :data:`VALIDATION_KINDS`) and the step it belongs to —
    the same pair the quarantine marker records."""

    def __init__(self, message, kind, step=None):
        super(CheckpointRejected, self).__init__(message)
        self.kind = str(kind)
        self.step = step


class WeightSet(object):
    """A validated, ready-to-swap weight generation: the raw flagship
    ``params`` (plus the optional ``draft`` sibling a speculative
    export ships), the publishing ``step``, and its directory."""

    def __init__(self, step, path, params, draft_params=None,
                 metadata=None):
        self.step = int(step)
        self.path = path
        self.params = params
        self.draft_params = draft_params
        self.metadata = metadata or {}

    def __repr__(self):
        return "WeightSet(step={0}, path={1!r})".format(
            self.step, self.path
        )


# ----------------------------------------------------------------------
# quarantine markers
# ----------------------------------------------------------------------


def quarantine(step_dir, kind, message):
    """Write the typed quarantine marker into ``step_dir`` (the
    checkpoint's bytes are kept for the operator's post-mortem — the
    marker only makes the step invisible to every future poll)."""
    import json

    rec = {"kind": str(kind), "message": str(message)}
    try:
        with open(os.path.join(step_dir, QUARANTINE_NAME), "w") as f:
            json.dump(rec, f)
    except OSError:
        # an unwritable store still quarantines in-session via the
        # watcher's memory; the marker is belt-and-braces persistence
        logger.warning("could not persist quarantine marker in %s",
                       step_dir, exc_info=True)
    return rec


def read_quarantine(step_dir):
    """The step's quarantine record, or None."""
    import json

    try:
        with open(os.path.join(step_dir, QUARANTINE_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ----------------------------------------------------------------------
# validation
# ----------------------------------------------------------------------


def _dtype_kind(dtype_str):
    import numpy as np

    try:
        return np.dtype(dtype_str).kind
    except TypeError:
        return "?"


def check_tree(expect, got_manifest):
    """Compare an ingested checkpoint's param census against the live
    model's ``expect`` spec; raises :class:`CheckpointRejected` with
    the stage-appropriate kind, naming the first offending leaf."""
    missing = sorted(set(expect) - set(got_manifest))
    extra = sorted(set(got_manifest) - set(expect))
    if missing or extra:
        raise CheckpointRejected(
            "param tree mismatch vs live model: missing {0}, "
            "unexpected {1}".format(missing[:4], extra[:4]),
            kind="tree_mismatch",
        )
    for path in sorted(expect):
        if got_manifest[path]["shape"] != expect[path]["shape"]:
            raise CheckpointRejected(
                "shape mismatch at {0}: live {1} vs checkpoint "
                "{2}".format(path, expect[path]["shape"],
                             got_manifest[path]["shape"]),
                kind="shape_mismatch",
            )
        if (_dtype_kind(got_manifest[path]["dtype"])
                != _dtype_kind(expect[path]["dtype"])):
            raise CheckpointRejected(
                "dtype kind mismatch at {0}: live {1} vs checkpoint "
                "{2} (exact dtype may differ — ingest re-casts; the "
                "KIND must match)".format(
                    path, expect[path]["dtype"],
                    got_manifest[path]["dtype"],
                ),
                kind="dtype_mismatch",
            )


def validate_checkpoint(step_dir, step, expect=None, canary_fn=None):
    """Run the full validation pipeline over one step directory and
    return its :class:`WeightSet`; raises :class:`CheckpointRejected`
    (typed) at the first failing stage.  Stage order matters: a torn
    manifest must never reach the loader, and a mis-shaped tree must
    never reach the canary (whose jitted forward would retrace)."""
    from tensorflowonspark_tpu import checkpoint as ckpt

    mpath = os.path.join(step_dir, ckpt.MANIFEST_NAME)
    if not os.path.exists(mpath):
        raise CheckpointRejected(
            "step {0}: manifest missing — a torn or foreign "
            "directory (atomic publishes always carry one)".format(step),
            kind="bad_manifest", step=step,
        )
    manifest = ckpt.read_manifest(step_dir)
    if manifest is None:
        raise CheckpointRejected(
            "step {0}: manifest present but unparseable".format(step),
            kind="bad_manifest", step=step,
        )
    if not manifest.get("complete"):
        raise CheckpointRejected(
            "step {0}: manifest lacks complete=true (writer died "
            "mid-save?)".format(step),
            kind="incomplete", step=step,
        )
    try:
        params, meta = ckpt.load_for_serving(step_dir)
    except Exception as e:  # noqa: BLE001 - corrupt stores throw anything
        raise CheckpointRejected(
            "step {0}: checkpoint failed to load (corrupt/truncated "
            "array files?): {1}".format(step, e),
            kind="load_failed", step=step,
        )
    draft = None
    if isinstance(params, dict) and "draft" in params:
        params = dict(params)
        draft = params.pop("draft")
    if expect is not None:
        try:
            check_tree(expect, ckpt.param_manifest(params))
        except CheckpointRejected as e:
            e.step = step
            raise
    if canary_fn is not None:
        try:
            ok = canary_fn(params)
        except Exception as e:  # noqa: BLE001 - canary faults are typed
            raise CheckpointRejected(
                "step {0}: canary raised: {1}".format(step, e),
                kind="canary_failed", step=step,
            )
        if ok is False:
            raise CheckpointRejected(
                "step {0}: canary forward pass failed (non-finite "
                "logits or explicit False)".format(step),
                kind="canary_failed", step=step,
            )
    return WeightSet(step, step_dir, params, draft_params=draft,
                     metadata=meta)


# ----------------------------------------------------------------------
# the watcher
# ----------------------------------------------------------------------


class CheckpointWatcher(object):
    """Poll a step-numbered serving-export root for new weight
    generations, validating each candidate before it can ever serve.

    Args:
      root: directory of :func:`~tensorflowonspark_tpu.checkpoint.
        publish_for_serving` step exports.
      poll_interval: seconds between directory scans.
      expect: live param census (:meth:`SlotDecoder.param_spec`) the
        tree/shape/dtype stage checks against; the ServingEngine
        binds it automatically when the watcher arrives unbound.
      canary_fn: optional ``fn(params) -> bool`` run as the LAST
        validation stage, off the hot path (in the ingest thread);
        raise or return False to quarantine with ``canary_failed``.
        Independent of the engine's post-install canary.
      background: ingest (orbax load + validation) on a daemon
        thread (default), so a slow store never stalls the decode
        loop; ``False`` ingests synchronously inside :meth:`poll`
        (deterministic — what the unit tests use).
      start_step: only steps STRICTLY greater are ever offered
        (default: offer anything present — a freshly started engine
        adopts the newest published weights via its first poll).
      clock: monotonic clock override (tests).
      ingest_delay: seconds to sleep at the top of every ingest;
        defaults to the chaos plan's ``slow_ingest`` order (None
        without a plan — zero overhead).
    """

    def __init__(self, root, *, poll_interval=5.0, expect=None,
                 canary_fn=None, background=True, start_step=None,
                 clock=None, ingest_delay=None):
        self.root = os.path.abspath(os.fspath(root))
        self.poll_interval = float(poll_interval)
        self.expect = expect
        self.canary_fn = canary_fn
        self._clock = clock if clock is not None else time.monotonic
        if ingest_delay is None:
            from tensorflowonspark_tpu.testing import chaos

            ingest_delay = chaos.ingest_delay()
        self._ingest_delay = ingest_delay
        self._floor = -1 if start_step is None else int(start_step)
        self._lock = threading.Lock()
        self._ready = None
        self._last_scan = None
        self._quarantined = {}  # step -> record (session memory)
        self.quarantined = []   # ordered records for callers/tests
        self.stats = {"scans": 0, "ingested": 0, "quarantined": 0,
                      "offered": 0}
        self._tracer = telemetry.get_tracer()
        self._m_quar = telemetry.get_registry().counter(
            "serving.checkpoints_quarantined"
        )
        self._stop = threading.Event()
        self._thread = None
        if background:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="ckpt-watcher"
            )
            self._thread.start()

    # -- scanning ------------------------------------------------------

    def _candidates(self):
        """Step numbers visible under root, newest first, excluding
        quarantined steps and anything at/below the floor.  A step
        directory is a candidate as soon as it EXISTS — manifest
        validation decides completeness (torn dirs quarantine with a
        typed reason; in-progress atomic publishes are invisible by
        construction)."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        steps = []
        for name in names:
            try:
                step = int(name)
            except ValueError:
                continue
            if step <= self._floor or step in self._quarantined:
                continue
            if read_quarantine(os.path.join(self.root, name)):
                self._quarantined[step] = True
                continue
            steps.append(step)
        return sorted(steps, reverse=True)

    def _ingest(self, step):
        step_dir = os.path.join(self.root, str(step))
        with self._tracer.span("swap_ingest", trace="swap", step=step):
            if self._ingest_delay:
                time.sleep(float(self._ingest_delay))
            try:
                w = validate_checkpoint(
                    step_dir, step, expect=self.expect,
                    canary_fn=self.canary_fn,
                )
            except CheckpointRejected as e:
                self._record_quarantine(step, step_dir, e.kind, e)
                return None
        self.stats["ingested"] += 1
        return w

    def _record_quarantine(self, step, step_dir, kind, message):
        rec = quarantine(step_dir, kind, message)
        rec["step"] = step
        self._quarantined[step] = rec
        self.quarantined.append(rec)
        self.stats["quarantined"] += 1
        self._m_quar.inc()
        self._tracer.mark(
            "checkpoint_quarantined", trace="swap", severity="warn",
            step=step, kind=kind,
        )
        logger.warning(
            "hot-swap: quarantined checkpoint step %s (%s): %s",
            step, kind, message,
        )

    def _scan_once(self):
        """One scan-and-ingest pass: validate candidates newest-first
        until one passes (older torn steps still get their typed
        quarantine instead of lingering)."""
        self.stats["scans"] += 1
        for step in self._candidates():
            w = self._ingest(step)
            if w is not None:
                with self._lock:
                    # latest wins: an untaken older set is superseded
                    self._ready = w
                    self._floor = max(self._floor, w.step)
                return w
        return None

    def _run(self):
        while not self._stop.wait(self.poll_interval):
            try:
                self._scan_once()
            except Exception:  # noqa: BLE001 - the watcher must survive
                logger.warning("checkpoint watcher scan failed",
                               exc_info=True)

    # -- the engine-facing surface -------------------------------------

    def poll(self):
        """The newest validated :class:`WeightSet` not yet taken, or
        None.  Never blocks on ingest in background mode; in
        synchronous mode a scan runs inline at most every
        ``poll_interval`` seconds."""
        if self._thread is None:
            now = self._clock()
            if (self._last_scan is None
                    or now - self._last_scan >= self.poll_interval):
                self._last_scan = now
                self._scan_once()
        with self._lock:
            w, self._ready = self._ready, None
        if w is not None:
            self._floor = max(self._floor, w.step)
            self.stats["offered"] += 1
        return w

    def quarantine_step(self, weightset, kind, message):
        """Engine-side quarantine: a step that passed validation but
        failed AFTER install (post-swap canary, probation error
        spike) must never be offered again."""
        self._record_quarantine(
            weightset.step, weightset.path, kind, message
        )

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ----------------------------------------------------------------------
# SLO-probation rollback (ISSUE 16)
# ----------------------------------------------------------------------


def flag_probation_fault(engine, reason="slo_burn", count=1):
    """Count an EXTERNAL fault against ``engine``'s post-swap
    probation window, extending probation from request-level errors
    (device faults, watchdog wedges) to fleet-level signals — the
    remediation engine calls this when post-swap SLO burn exceeds
    budget.

    Returns True when the engine is inside a probation window (the
    rollback lands on its next scheduling pass, via the same
    ``_maybe_swap`` path as a request-error rollback — never
    concurrently with a dispatch); False when there is nothing to
    roll back (no swap on probation), so the caller can journal a
    no-op instead of pretending it acted.
    """
    if getattr(engine, "_prev_weights", None) is None:
        return False
    # same cross-thread contract as the watchdog's wedge accounting:
    # a plain int bump the scheduler thread reads between chunks
    engine._probation_errors += max(1, int(count))
    from tensorflowonspark_tpu import telemetry

    telemetry.get_tracer().mark(
        "probation_slo_fault", trace="serve", severity="warn",
        reason=str(reason),
        weight_generation=engine.stats.get("weight_generation"),
    )
    return True
