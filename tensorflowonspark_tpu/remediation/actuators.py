"""Actuator bindings: the verbs the remediation engine may drive.

An actuator object exposes (a subset of) the
:data:`~tensorflowonspark_tpu.remediation.policy.ACTIONS` vocabulary
as methods; the engine resolves ``intent.action`` by ``getattr`` and
journals a failure instead of crashing when a verb is missing or
raises.  Production wiring composes:

- :class:`FleetActuators` — serving-side verbs over a
  :class:`~tensorflowonspark_tpu.fleet.router.FleetRouter`:
  spawn/retire replicas (PR 13's lifecycle verbs as autoscaling),
  degrade/restore admission, and the SLO-probation rollback
  (:func:`~tensorflowonspark_tpu.hot_swap.flag_probation_fault` over
  every probation engine);
- :class:`ClusterActuators` — training-side elastic shrink/grow over
  a :class:`~tensorflowonspark_tpu.cluster.cluster.TPUCluster`:
  ``hold_executor`` quiesces a straggler's compute and
  re-rendezvouses the survivors at reduced width,
  ``release_executor`` grows it back in;
- :class:`CombinedActuators` — first-match dispatch over both.

Tests pass a recording fake instead; the engine cannot tell the
difference, which is the point — the decision/guardrail/audit layer
is identical against fakes and against the live fleet.
"""

import logging

logger = logging.getLogger(__name__)


class UnsupportedAction(RuntimeError):
    """This actuator set has no binding for the requested verb —
    journaled as a failed decision, never a crash."""


class Actuators(object):
    """Base: every verb unsupported.  Subclass and override what the
    deployment can actually drive."""

    def elastic_shrink(self, executor, **kw):
        raise UnsupportedAction("elastic_shrink unbound")

    def elastic_grow(self, executor, **kw):
        raise UnsupportedAction("elastic_grow unbound")

    def spawn_replica(self, **kw):
        raise UnsupportedAction("spawn_replica unbound")

    def retire_replica(self, replica_id=None, **kw):
        raise UnsupportedAction("retire_replica unbound")

    def probe_replica(self, replica_id=None, **kw):
        raise UnsupportedAction("probe_replica unbound")

    def degrade_admission(self, **kw):
        raise UnsupportedAction("degrade_admission unbound")

    def restore_admission(self, **kw):
        raise UnsupportedAction("restore_admission unbound")

    def rollback_generation(self, replicas=None, **kw):
        raise UnsupportedAction("rollback_generation unbound")

    def restart_prefill(self, replica_id=None, **kw):
        raise UnsupportedAction("restart_prefill unbound")


class FleetActuators(Actuators):
    """Serving-side verbs over a live FleetRouter."""

    def __init__(self, router):
        self.router = router
        self._prior_policy = None

    def spawn_replica(self, **kw):
        return self.router.scale_up()

    def retire_replica(self, replica_id=None, **kw):
        rid = self.router.scale_down(replica_id)
        if rid is None:
            raise UnsupportedAction(
                "no retirable replica (last live replica is never "
                "retired)"
            )
        return rid

    def probe_replica(self, replica_id=None, **kw):
        """Route around ``replica_id`` and put it on probe traffic —
        the REVERSIBLE counterpart of ``retire_replica``: the router
        keeps sending it one probe request per ``probe_every``
        dispatches and readmits it after ``readmit_rounds`` clean
        probes (the straggler-detection machinery, driven here by the
        cost policy instead of the latency EWMA)."""
        if replica_id is None:
            raise UnsupportedAction(
                "probe_replica needs a replica_id target"
            )
        rid = int(replica_id)
        replica = (
            self.router.replicas[rid]
            if 0 <= rid < len(self.router.replicas) else None
        )
        if replica is None or not replica.alive:
            raise UnsupportedAction(
                "replica {0} not alive".format(replica_id)
            )
        if replica.state != "live":
            raise UnsupportedAction(
                "replica {0} already {1}".format(rid, replica.state)
            )
        live = sum(
            1 for r in self.router.replicas
            if r.alive and r.state == "live"
        )
        if live <= 1:
            raise UnsupportedAction(
                "refusing to probe the last live replica"
            )
        self.router.replica_set.evict(rid)
        return rid

    def degrade_admission(self, **kw):
        prior = self.router.set_policy("degrade")
        if self._prior_policy is None:
            self._prior_policy = prior
        return prior

    def restore_admission(self, **kw):
        prior, self._prior_policy = self._prior_policy, None
        return self.router.set_policy(prior or "block")

    def rollback_generation(self, replicas=None, **kw):
        """Flag an SLO-probation fault on every (named) replica
        engine still holding a rollback snapshot; each engine rolls
        back between decode chunks on its own scheduling pass."""
        from tensorflowonspark_tpu import hot_swap

        flagged = []
        for r in self.router.replicas:
            if replicas is not None and r.replica_id not in replicas:
                continue
            if hot_swap.flag_probation_fault(
                    r.engine, reason="slo_burn"):
                flagged.append(r.replica_id)
        if not flagged:
            raise UnsupportedAction(
                "no replica engine on post-swap probation — nothing "
                "to roll back"
            )
        return flagged

    def restart_prefill(self, replica_id=None, **kw):
        """Rebuild the PrefillWorker of every (named) disaggregated
        replica engine — the remediation response to
        ``prefill_worker_dead`` / ``prefill_watchdog_fire`` page
        events.  Idempotent with the engine's own in-line containment
        (which already rebuilt the worker it fell over on): a rebuild
        of a healthy worker is cheap (compiled program carries over)
        and re-arms the watchdog."""
        restarted = []
        for r in self.router.replicas:
            eng = r.engine
            if getattr(eng, "_prefill_worker", None) is None:
                continue
            if replica_id is not None and \
                    r.replica_id != int(replica_id):
                continue
            if not r.alive:
                continue
            eng.restart_prefill_worker(reason="remediation")
            restarted.append(r.replica_id)
        if not restarted:
            raise UnsupportedAction(
                "no live disaggregated replica engine to restart a "
                "prefill worker on"
            )
        return restarted


class ClusterActuators(Actuators):
    """Training-side elastic shrink/grow over a TPUCluster (driver
    side).  The supervisor on the held node quiesces its compute and
    bumps the gang generation so survivors re-rendezvous at reduced
    width (cluster/supervisor.py); release takes the same path back
    to full width."""

    def __init__(self, cluster, release_gate=None):
        self.cluster = cluster
        #: optional :class:`~tensorflowonspark_tpu.telemetry.health.
        #: CleanRoundsSensor`: ``elastic_grow`` (releasing a held
        #: executor back into the gang) requires N consecutive clean
        #: health rounds, not a timer — the same quality gate as fleet
        #: re-admission (ROADMAP 3 residual)
        self.release_gate = release_gate
        self._gate_blocked = False

    def elastic_shrink(self, executor, **kw):
        return self.cluster.hold_executor(
            executor, reason=kw.get("reason", "remediation")
        )

    def elastic_grow(self, executor, **kw):
        gate = self.release_gate
        if gate is not None:
            gate.poll()
            if not gate.ready():
                if not self._gate_blocked:
                    self._gate_blocked = True
                    from tensorflowonspark_tpu import telemetry

                    telemetry.get_tracer().mark(
                        "readmit_gated", trace="remediation",
                        severity="warn", executor=int(executor),
                        clean_health_rounds=gate.streak,
                        required_rounds=gate.rounds,
                    )
                raise UnsupportedAction(
                    "elastic_grow gated: health plane has {0}/{1} "
                    "clean rounds".format(gate.streak, gate.rounds)
                )
            if self._gate_blocked:
                self._gate_blocked = False
                from tensorflowonspark_tpu import telemetry

                telemetry.get_tracer().mark(
                    "readmit_cleared", trace="remediation",
                    executor=int(executor),
                    clean_health_rounds=gate.streak,
                )
        return self.cluster.release_executor(executor)


class CombinedActuators(Actuators):
    """First-match dispatch over an ordered actuator list — the full
    self-driving deployment binds ``CombinedActuators(
    ClusterActuators(cluster), FleetActuators(router))``."""

    def __init__(self, *actuators):
        self.actuators = list(actuators)

    def _dispatch(self, verb, *a, **kw):
        last = None
        for act in self.actuators:
            try:
                return getattr(act, verb)(*a, **kw)
            except UnsupportedAction as e:
                last = e
        raise last or UnsupportedAction("%s unbound" % verb)

    def elastic_shrink(self, executor, **kw):
        return self._dispatch("elastic_shrink", executor, **kw)

    def elastic_grow(self, executor, **kw):
        return self._dispatch("elastic_grow", executor, **kw)

    def spawn_replica(self, **kw):
        return self._dispatch("spawn_replica", **kw)

    def retire_replica(self, replica_id=None, **kw):
        return self._dispatch(
            "retire_replica", replica_id=replica_id, **kw
        )

    def probe_replica(self, replica_id=None, **kw):
        return self._dispatch(
            "probe_replica", replica_id=replica_id, **kw
        )

    def degrade_admission(self, **kw):
        return self._dispatch("degrade_admission", **kw)

    def restore_admission(self, **kw):
        return self._dispatch("restore_admission", **kw)

    def rollback_generation(self, replicas=None, **kw):
        return self._dispatch(
            "rollback_generation", replicas=replicas, **kw
        )

    def restart_prefill(self, replica_id=None, **kw):
        return self._dispatch(
            "restart_prefill", replica_id=replica_id, **kw
        )
