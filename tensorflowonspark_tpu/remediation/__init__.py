"""Audited remediation: the policy engine that closes the fleet's
sensor→actuator loop (ROADMAP item 3, ISSUE 16).

Every sensor the stack grew — SLO burn and straggler attribution
(telemetry/health.py), the typed event journal with clock-aligned
causality (telemetry/journal.py, forensics.py), per-request cost
rows (telemetry/ledger.py), the router's windowed admission pressure
(fleet/router.py) — and every actuator — supervised restart and
elastic re-rendezvous (cluster/supervisor.py), validated hot-swap
and probation rollback (hot_swap.py), leader re-election
(parallel/hier_ps.py), replica lifecycle verbs and rolling deploys
(fleet/) — existed before this package, with a human between them.
This package is the missing middle: policies read the sensors
through cursors, guardrails (cooldowns, rate limits, a global action
budget, hysteresis, dry-run, the deploy-conflict rule) bound what
may execute, and every decision lands in the journal as a typed
``remediation_decision`` event carrying its triggering evidence, so
``forensics explain`` answers "why did the fleet do that?" the same
way it answers "what failed?".

Quick start (serving-only)::

    from tensorflowonspark_tpu import remediation

    eng = remediation.wire(plane, router=router).start()
    ...
    eng.stop()

Docs: docs/fault_tolerance.md "Self-driving remediation".
"""

from tensorflowonspark_tpu.remediation.actuators import (  # noqa: F401
    Actuators, ClusterActuators, CombinedActuators, FleetActuators,
    UnsupportedAction,
)
from tensorflowonspark_tpu.remediation.engine import (  # noqa: F401
    Guardrails, RemediationEngine, Sensors, SensorSnapshot,
)
from tensorflowonspark_tpu.remediation.policy import (  # noqa: F401
    ACTIONS, AutoscalePolicy, CostPolicy, FaultResponsePolicy, Intent,
    PageAlertPolicy, Policy, SloRollbackPolicy, StragglerPolicy,
    default_policies,
)


def wire(plane=None, router=None, cluster=None, policies=None,
         guardrails=None, interval=1.0, clock=None, **overrides):
    """Build a :class:`RemediationEngine` over the LIVE planes.

    Args:
      plane: a :class:`~tensorflowonspark_tpu.telemetry.health.
        HealthPlane` (alerts via the ``alerts_since`` cursor +
        straggler hints).  None is allowed for router-only wiring.
      router: a :class:`~tensorflowonspark_tpu.fleet.router.
        FleetRouter` — binds the serving verbs, the pressure sensor,
        the probation sensor, and the deploy-conflict rule.
      cluster: a :class:`~tensorflowonspark_tpu.cluster.cluster.
        TPUCluster` — binds elastic shrink/grow and the fleet-shipped
        journal sensor (falls back to this process's own journal).
      policies / guardrails / interval / clock: forwarded to the
        engine; ``overrides`` forward to :func:`default_policies`
        when ``policies`` is None.
    """
    from tensorflowonspark_tpu import telemetry

    slo = hints_fn = None
    if plane is not None:
        slo = plane.slo
        hints_fn = lambda: dict(plane.hints)  # noqa: E731
    journal = events_fn = None
    if cluster is not None:
        def events_fn():
            return (cluster.journal() or {}).get("events", [])
    else:
        journal = telemetry.get_journal()
    pressure_fn = fleet_fn = probation_fn = deploy_fn = None
    if router is not None:
        pressure_fn = router.pressure

        def fleet_fn():
            status = router.health_status()
            return {
                "replicas": len(router.replicas),
                "live": sum(
                    1 for r in router.replicas
                    if r.alive and r.state == "live"
                ),
                # the usage-ledger cost rows (ISSUE 14/18): chip_sec
                # and tokens_out per replica, CostPolicy's input
                "costs": status.get("costs", {}),
            }

        def probation_fn():
            return [
                r.replica_id for r in router.replicas
                if r.alive and getattr(
                    r.engine, "_prev_weights", None
                ) is not None
            ]

        deploy_fn = router.deploy_active
    sensors = Sensors(
        slo=slo, hints_fn=hints_fn, journal=journal,
        events_fn=events_fn, pressure_fn=pressure_fn,
        fleet_fn=fleet_fn, probation_fn=probation_fn,
        deploy_active_fn=deploy_fn, clock=clock,
    )
    acts = []
    if cluster is not None:
        acts.append(ClusterActuators(cluster))
    if router is not None:
        acts.append(FleetActuators(router))
    if not acts:
        actuators = Actuators()  # every verb journals as unsupported
    elif len(acts) == 1:
        actuators = acts[0]
    else:
        actuators = CombinedActuators(*acts)
    if policies is None:
        policies = default_policies(**overrides)
    elif overrides:
        raise ValueError(
            "pass policy overrides OR an explicit policy list, "
            "not both"
        )
    return RemediationEngine(
        sensors, actuators, policies=policies,
        guardrails=guardrails, interval=interval, clock=clock,
    )
