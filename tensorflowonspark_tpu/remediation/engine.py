"""The remediation engine: sensor snapshots → policies → guardrails
→ audited actuator calls.

One evaluation round (:meth:`RemediationEngine.step`):

1. :class:`Sensors` polls every plane through CURSORS — SLO alert
   transitions via ``SloEngine.alerts_since(seq)`` (satellite: the
   bounded history can age a fired→resolved edge out from under a
   slow poller; the cursor makes the gap detectable), journal events
   via ``events_since`` / a ``(executor, pid, seq)`` seen-set,
   straggler hints, the router's windowed admission pressure, the
   probation set, and the deploy-in-progress flag.
2. Each policy turns the snapshot into :class:`~tensorflowonspark_tpu.
   remediation.policy.Intent` records (policies own hysteresis, and
   their latches move on the engine's EXECUTION feedback —
   ``Policy.on_decision`` — never on emission, so a suppressed or
   failed action stays asserted and is retried).
3. Guardrails gate execution, in order: the **conflict rule** (an
   in-progress RollingDeploy or hot-swap transaction defers ALL
   remediation — one ``remediation_deferred`` journal event per
   conflict streak, zero actuator calls), **per-action cooldowns**
   (at most one execution per ``(action, target)`` per cooldown
   window — the flapping-sensor bound), a **rate limit** (at most N
   executions per rolling window across all actions), and the
   **global action budget** (on exhaustion: one
   ``remediation_budget_exhausted`` PAGE event, then hands-off — the
   engine stops acting entirely until :meth:`RemediationEngine.rearm`).
4. What survives executes through the pluggable
   :class:`~tensorflowonspark_tpu.remediation.actuators.Actuators`
   (or is only journaled, in **dry-run** mode — which charges
   neither the rate limit nor the budget, so a rehearsal previews
   every intended action) and is journaled as a
   typed ``remediation_decision`` event carrying the policy name, the
   action, the target, and the TRIGGERING EVIDENCE (alert with its
   cursor seq, journal event ids, pressure/hint excerpt) — so
   ``forensics explain`` answers "why did the fleet do that?" from
   the journal alone.

The engine is a single thread (``remediation-engine``); every
actuator it drives is itself thread-safe or internally serialized,
and the lock-order sanitizer (TFOS_LOCKSAN=1) stays armed over the
whole remediation test lane to prove the new thread family adds no
lock cycles.
"""

import collections
import itertools
import logging
import threading
import time

from tensorflowonspark_tpu.remediation.policy import default_policies

logger = logging.getLogger(__name__)

_ENGINE_SEQ = itertools.count(1)


class SensorSnapshot(object):
    """One round's view of every sensor plane (plain data)."""

    __slots__ = ("t", "alerts", "alert_gap", "hints", "events",
                 "pressure", "fleet", "probation", "deploy_active")

    def __init__(self, t=0.0, alerts=(), alert_gap=False, hints=None,
                 events=(), pressure=None, fleet=None, probation=(),
                 deploy_active=False):
        self.t = t
        self.alerts = list(alerts)
        self.alert_gap = bool(alert_gap)
        self.hints = dict(hints or {})
        self.events = list(events)
        self.pressure = pressure
        self.fleet = fleet
        self.probation = list(probation)
        self.deploy_active = bool(deploy_active)


class Sensors(object):
    """Cursor-tracking reader over the sensor planes.  Every source
    is an optional callable (or object) so tests inject synthetic
    planes and production wires the real ones
    (:func:`~tensorflowonspark_tpu.remediation.wire`):

    Args:
      slo: a :class:`~tensorflowonspark_tpu.telemetry.health.
        SloEngine` — read via ``alerts_since`` with a cursor, so a
        slow poll can MISS no edge silently (``alert_gap`` flips when
        transitions aged out of the bounded history unseen).
      hints_fn: zero-arg → the health plane's straggler ``hints``.
      journal: an :class:`~tensorflowonspark_tpu.telemetry.journal.
        EventJournal` (local cursor via ``events_since``) — or pass
        ``events_fn`` returning event DICTS for fleet-shipped events;
        those dedup through a bounded ``(executor, pid, seq)``
        seen-set.
      pressure_fn: zero-arg → the router's windowed admission
        pressure dict.
      fleet_fn: zero-arg → ``{"live": n, "replicas": n}``.
      probation_fn: zero-arg → replica ids on post-swap probation.
      deploy_active_fn: zero-arg → True while a RollingDeploy or
        hot-swap transaction is mid-step (the conflict rule).
    """

    def __init__(self, slo=None, hints_fn=None, journal=None,
                 events_fn=None, pressure_fn=None, fleet_fn=None,
                 probation_fn=None, deploy_active_fn=None, clock=None):
        self.slo = slo
        self.hints_fn = hints_fn
        self.journal = journal
        self.events_fn = events_fn
        self.pressure_fn = pressure_fn
        self.fleet_fn = fleet_fn
        self.probation_fn = probation_fn
        self.deploy_active_fn = deploy_active_fn
        self._clock = clock or time.monotonic
        self._alert_cursor = (
            slo.last_alert_seq if slo is not None else 0
        )
        self._journal_cursor = 0
        if journal is not None:
            evs = journal.events()
            self._journal_cursor = evs[-1].seq if evs else 0
        self._seen = collections.deque(maxlen=4096)
        self._seen_set = set()

    def _call(self, fn, default=None):
        if fn is None:
            return default
        try:
            return fn()
        except Exception:  # noqa: BLE001 - a dead sensor must not
            logger.warning(  # kill the remediation loop
                "remediation sensor failed", exc_info=True
            )
            return default

    def _poll_alerts(self):
        if self.slo is None:
            return [], False
        new = self.slo.alerts_since(self._alert_cursor)
        gap = False
        if new:
            if new[0].seq > self._alert_cursor + 1:
                gap = True
            self._alert_cursor = new[-1].seq
        elif self.slo.last_alert_seq > self._alert_cursor:
            # everything since our cursor already aged out of the
            # bounded history — edges were missed; resync the cursor
            gap = True
            self._alert_cursor = self.slo.last_alert_seq
        return [a.to_dict() for a in new], gap

    def _poll_events(self):
        if self.journal is not None:
            evs = self.journal.events_since(self._journal_cursor)
            if evs:
                self._journal_cursor = evs[-1].seq
            return [e.to_dict() for e in evs]
        out = []
        for ev in self._call(self.events_fn, []) or []:
            key = (ev.get("executor"), ev.get("pid"), ev.get("seq"))
            if key in self._seen_set:
                continue
            if len(self._seen) == self._seen.maxlen:
                self._seen_set.discard(self._seen[0])
            self._seen.append(key)
            self._seen_set.add(key)
            out.append(ev)
        return out

    def poll(self):
        alerts, gap = self._poll_alerts()
        return SensorSnapshot(
            t=self._clock(),
            alerts=alerts, alert_gap=gap,
            hints=self._call(self.hints_fn, {}),
            events=self._poll_events(),
            pressure=self._call(self.pressure_fn),
            fleet=self._call(self.fleet_fn),
            probation=self._call(self.probation_fn, []) or [],
            deploy_active=bool(self._call(self.deploy_active_fn, False)),
        )


class Guardrails(object):
    """The engine's safety envelope (checked in this order):

    - ``cooldown_sec``: at most one EXECUTION per ``(action, target)``
      per window (``per_action`` overrides per action name) — a
      sensor flapping at any rate drives the actuator at most once
      per window;
    - ``rate_limit``/``rate_window_sec``: at most N executions per
      rolling window across ALL actions;
    - ``budget``: lifetime action budget; exhaustion journals
      ``remediation_budget_exhausted`` at PAGE severity and the
      engine goes hands-off (a self-driving loop that has acted this
      many times without converging is the incident);
    - ``dry_run``: journal every intended action, execute none, and
      charge neither the rate limit nor the budget — rehearsals are
      free, and the preview's audit trail is complete (a dry run
      that rate-limited intents away would journal a DIFFERENT
      sequence than the operator asked to preview).  Cooldown dedup
      still applies, bounding journal spam from a flapping sensor.

    ``stand_down`` decisions are exempt from rate limit and budget
    (they ARE the non-action), but still cooldown-deduped.
    """

    def __init__(self, cooldown_sec=30.0, per_action=None,
                 rate_limit=4, rate_window_sec=60.0, budget=25,
                 dry_run=False):
        self.cooldown_sec = float(cooldown_sec)
        self.per_action = dict(per_action or {})
        self.rate_limit = int(rate_limit)
        self.rate_window_sec = float(rate_window_sec)
        self.budget = int(budget)
        self.dry_run = bool(dry_run)

    def cooldown_for(self, action):
        return float(self.per_action.get(action, self.cooldown_sec))


class RemediationEngine(object):
    """See module docstring.  Drive it with :meth:`step` (tests, or
    any external loop) or :meth:`start` (own thread).

    Args:
      sensors: a :class:`Sensors`.
      actuators: an object exposing the :data:`~tensorflowonspark_tpu.
        remediation.policy.ACTIONS` verbs (see actuators.py); tests
        pass a recording fake.
      policies: policy list (default :func:`default_policies`).
      guardrails: a :class:`Guardrails` (default: defaults).
      interval: thread loop cadence.
      clock: injectable monotonic clock (guardrail tests).
    """

    MAX_DECISIONS = 256

    def __init__(self, sensors, actuators, policies=None,
                 guardrails=None, interval=1.0, clock=None,
                 name=None):
        from tensorflowonspark_tpu import telemetry

        self.sensors = sensors
        self.actuators = actuators
        self.policies = (
            default_policies() if policies is None else list(policies)
        )
        self.guardrails = guardrails or Guardrails()
        self.interval = float(interval)
        self._clock = clock or time.monotonic
        self.name = name or "remediation%d" % next(_ENGINE_SEQ)
        self.armed = True
        self.decisions = collections.deque(maxlen=self.MAX_DECISIONS)
        self.stats = {
            "rounds": 0, "decisions": 0, "executed": 0,
            "suppressed": 0, "deferred": 0, "failed": 0,
            "budget_spent": 0,
        }
        self._last_exec = {}       # intent.key() -> exec time
        self._exec_times = collections.deque()  # rolling rate window
        self._decision_seq = itertools.count(1)
        self._conflict_streak = False
        self._stop = threading.Event()
        self._thread = None
        self._tracer = telemetry.get_tracer()
        reg = telemetry.get_registry()
        self._m_decisions = reg.counter("remediation.decisions")
        self._m_executed = reg.counter("remediation.actions_executed")
        self._m_suppressed = reg.counter(
            "remediation.actions_suppressed"
        )
        self._m_deferred = reg.counter("remediation.actions_deferred")
        self._m_budget = reg.gauge("remediation.budget_remaining")
        self._m_budget.set(self.guardrails.budget)
        self._register_status()

    def _register_status(self):
        import weakref

        from tensorflowonspark_tpu.telemetry import health as _health

        ref = weakref.ref(self)

        def _status():
            eng = ref()
            return (
                {"finished": True} if eng is None else eng.status()
            )

        _health.register_status_provider("remediation", _status)

    # -- public surface --------------------------------------------------

    def status(self):
        g = self.guardrails
        return {
            "armed": self.armed,
            "dry_run": g.dry_run,
            "budget": g.budget,
            "budget_remaining": self.budget_remaining(),
            "cooldown_sec": g.cooldown_sec,
            "policies": [p.name for p in self.policies],
            "stats": dict(self.stats),
            "decisions": [d for d in list(self.decisions)[-20:]],
        }

    def budget_remaining(self):
        return max(0, self.guardrails.budget
                   - self.stats["budget_spent"])

    def rearm(self, budget=None):
        """Operator override: restore a hands-off engine (optionally
        with a fresh budget).  Journaled — un-pausing the
        self-driving loop is itself an audited event."""
        if budget is not None:
            self.guardrails.budget = int(budget)
            self.stats["budget_spent"] = 0
        self.armed = True
        self._m_budget.set(self.budget_remaining())
        self._tracer.mark(
            "remediation_rearmed", trace="remediation",
            budget=self.guardrails.budget, engine=self.name,
        )

    # -- one evaluation round --------------------------------------------

    def step(self):
        """One sensor→policy→guardrail→actuator round; returns the
        list of decision records journaled this round."""
        if not self.armed:
            return []
        snap = self.sensors.poll()
        self.stats["rounds"] += 1
        intents = []
        for p in self.policies:
            try:
                intents.extend(p.evaluate(snap) or [])
            except Exception:  # noqa: BLE001 - one bad policy must
                logger.warning(  # not kill the loop
                    "remediation policy %r failed", p.name,
                    exc_info=True,
                )
        if not intents:
            self._conflict_streak = False
            return []
        if snap.deploy_active:
            # the conflict rule: never fight an in-progress
            # RollingDeploy / hot-swap transaction.  Zero actuator
            # calls, zero decisions; one deferred event per streak.
            self.stats["deferred"] += len(intents)
            self._m_deferred.inc(len(intents))
            if not self._conflict_streak:
                self._conflict_streak = True
                self._tracer.mark(
                    "remediation_deferred", trace="remediation",
                    intents=[i.action for i in intents],
                    engine=self.name, reason="deploy_in_progress",
                )
            return []
        self._conflict_streak = False
        out = []
        for intent in intents:
            try:
                rec = self._consider(intent, snap)
            except Exception:  # noqa: BLE001 - one bad intent must not
                rec = None     # drop the rest of the round
                self.stats["failed"] += 1
                logger.warning(
                    "remediation intent %r failed", intent,
                    exc_info=True,
                )
            if rec is not None:
                out.append(rec)
        return out

    def _consider(self, intent, snap):
        g = self.guardrails
        now = self._clock()
        # cooldown: one execution per (action, target) per window
        last = self._last_exec.get(intent.key())
        if last is not None and now - last < g.cooldown_for(
                intent.action):
            self.stats["suppressed"] += 1
            self._m_suppressed.inc()
            return None
        virtual = intent.action == "stand_down"
        if not virtual and not g.dry_run:
            # rolling rate limit across all actions.  Dry-run is
            # exempt (and charges nothing below): a rehearsal must
            # journal EVERY intended action — rate-limit/budget
            # suppression would silence part of the preview's audit
            # trail without any actuator having moved.
            horizon = now - g.rate_window_sec
            while self._exec_times and self._exec_times[0] < horizon:
                self._exec_times.popleft()
            if len(self._exec_times) >= g.rate_limit:
                self.stats["suppressed"] += 1
                self._m_suppressed.inc()
                return None
            if self.budget_remaining() <= 0:
                self._exhaust(intent)
                return None
        executed, error = False, None
        if not g.dry_run and not virtual:
            try:
                getattr(self.actuators, intent.action)(
                    **intent.target
                )
                executed = True
            except Exception as e:  # noqa: BLE001 - a failed actuator
                error = repr(e)     # is a journaled outcome, not a crash
                self.stats["failed"] += 1
                logger.warning(
                    "remediation action %r failed", intent.action,
                    exc_info=True,
                )
        self._last_exec[intent.key()] = now
        if executed:
            self._exec_times.append(now)
            self.stats["budget_spent"] += 1
            self._m_budget.set(self.budget_remaining())
        rec = self._journal_decision(
            intent, snap, executed=executed, error=error
        )
        self._notify(rec)
        return rec

    def _notify(self, rec):
        """Execution feedback: report the journaled decision back to
        the policy that emitted it, so hysteresis latches move on
        what actually HAPPENED (executed / dry-run / failed), not on
        what was wished for."""
        for p in self.policies:
            if p.name != rec["policy"]:
                continue
            try:
                p.on_decision(rec)
            except Exception:  # noqa: BLE001 - feedback must not
                logger.warning(  # kill the round
                    "remediation policy %r on_decision failed",
                    p.name, exc_info=True,
                )

    def _exhaust(self, intent):
        """Budget exhausted: one PAGE event, then hands-off."""
        self.armed = False
        self._m_budget.set(0)
        self._tracer.mark(
            "remediation_budget_exhausted", trace="remediation",
            severity="page", engine=self.name,
            budget=self.guardrails.budget,
            last_intent=intent.to_dict(),
        )
        logger.error(
            "remediation action budget (%d) exhausted; engine %s "
            "going hands-off (rearm() to restore)",
            self.guardrails.budget, self.name,
        )

    def _journal_decision(self, intent, snap, executed, error=None):
        rec = intent.to_dict()
        rec.update({
            "decision": next(self._decision_seq),
            "engine": self.name,
            "executed": executed,
            "dry_run": self.guardrails.dry_run,
        })
        if error is not None:
            rec["error"] = error
        if snap.alert_gap:
            rec["alert_gap"] = True
        self.decisions.append(rec)
        self.stats["decisions"] += 1
        self._m_decisions.inc()
        if executed:
            self.stats["executed"] += 1
            self._m_executed.inc()
        # the decision IS a typed journal event (the tracer mark
        # auto-bridges into the journal and ships driver-ward with
        # the heartbeat piggyback) — severity from the intent so a
        # page-grade action dumps the flight recorder
        self._tracer.mark(
            "remediation_decision", trace="remediation",
            severity=intent.severity
            if intent.severity in ("info", "warn", "page") else "warn",
            **{k: rec[k] for k in (
                "decision", "engine", "action", "policy", "target",
                "evidence", "reason", "executed", "dry_run",
            )}
        )
        return rec

    # -- the loop --------------------------------------------------------

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="remediation-engine",
            )
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.step()
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.warning("remediation step failed", exc_info=True)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
