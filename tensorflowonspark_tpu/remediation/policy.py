"""Remediation policies: sensor snapshots in, typed intents out.

A policy is a small stateful object scoring ONE failure signature
against each :class:`~tensorflowonspark_tpu.remediation.engine.
SensorSnapshot` and emitting :class:`Intent` records when it wants an
actuator driven.  Policies carry their OWN hysteresis (``sustain``
consecutive asserting rounds before the first intent — the engine's
cooldowns then bound how often an intent may EXECUTE), and every
intent names the evidence that justified it: the alert transition
(with its ``seq`` cursor), the journal event ids
``(executor, pid, seq)``, the straggler hint with its phase
attribution, or the admission-pressure excerpt — whatever the policy
actually read.  ``forensics explain`` renders that evidence back, so
"why did the fleet do that?" has a literal answer in the journal.

Hysteresis state flips on EXECUTION feedback, not on emission: the
engine reports every journaled decision back through
:meth:`Policy.on_decision`, and only an executed (or dry-run
rehearsed) action moves a policy's latches (``held``, ``degraded``).
An intent suppressed by the rate limit / budget or failed by the
actuator leaves the policy asserting, so the action is retried once
the guardrails allow — a page can never wedge half-applied.

The default set (:func:`default_policies`) closes the four loops
ISSUE 16 names:

- :class:`StragglerPolicy` — straggler-flagged executor → elastic
  shrink (hold + re-rendezvous at reduced width); N clean rounds →
  elastic grow (release + re-rendezvous at full width);
- :class:`AutoscalePolicy` — sustained admission pressure → spawn a
  serving replica; sustained idle slots → retire one;
- :class:`PageAlertPolicy` — page-severity SLO alert → degrade
  admission (spill work instead of shedding it); resolve → restore;
- :class:`SloRollbackPolicy` — SLO burn while a weight generation is
  on post-swap probation → roll the generation back (extends PR 8's
  probation from request errors to fleet-level SLOs);
- :class:`FaultResponsePolicy` — journal fault events the lower
  planes already handled: a dead replica is re-spawned (capacity
  restore); automatic recoveries (leader re-election, checkpoint
  quarantine) get an explicit ``stand_down`` decision so the journal
  records that remediation saw the fault and deliberately did not
  pile a second actuator on top of a recovery in progress;
- :class:`CostPolicy` — the usage ledger's per-replica cost rows
  (ISSUE 18): probe, then evict, the replica burning the most
  chip-seconds per emitted token relative to the fleet median — the
  cost outlier, not merely the slowest.
"""

import logging

logger = logging.getLogger(__name__)

#: the actuator verb vocabulary (attribute names on an Actuators
#: implementation); ``stand_down`` is virtual — it never reaches an
#: actuator, it IS the decision
ACTIONS = (
    "elastic_shrink", "elastic_grow", "spawn_replica",
    "retire_replica", "degrade_admission", "restore_admission",
    "rollback_generation", "probe_replica", "restart_prefill",
    "stand_down",
)


def _freeze(v):
    """Recursively turn ``v`` into a hashable canonical form."""
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return tuple(sorted(_freeze(x) for x in v))
    return v


class Intent(object):
    """One policy's wish: drive ``action`` against ``target`` because
    of ``evidence``.  Plain data; the engine turns it into an audited
    decision (or a suppression)."""

    __slots__ = ("action", "policy", "target", "evidence", "severity",
                 "reason")

    def __init__(self, action, policy, target=None, evidence=None,
                 severity="warn", reason=""):
        if action not in ACTIONS:
            raise ValueError(
                "unknown remediation action {0!r}; one of {1}".format(
                    action, ACTIONS
                )
            )
        self.action = action
        self.policy = policy
        self.target = dict(target or {})
        self.evidence = dict(evidence or {})
        self.severity = severity
        self.reason = reason

    def key(self):
        """Cooldown identity: the action plus its stable target.
        Target values are canonicalized (lists/dicts/sets frozen) so
        the key is always hashable — ``rollback_generation`` targets
        a replica LIST."""
        return (self.action, _freeze(self.target))

    def to_dict(self):
        return {
            "action": self.action, "policy": self.policy,
            "target": self.target, "evidence": self.evidence,
            "severity": self.severity, "reason": self.reason,
        }

    def __repr__(self):
        return "Intent({0} by {1} on {2})".format(
            self.action, self.policy, self.target
        )


class Policy(object):
    """Base policy: subclasses set ``name`` and implement
    :meth:`evaluate` returning a list of :class:`Intent`.  Policies
    are single-threaded — only the engine's loop calls them."""

    name = "policy"

    def evaluate(self, snap):
        raise NotImplementedError

    def on_decision(self, rec):
        """Execution feedback: the engine calls this with every
        decision record it journals for this policy (``executed``,
        ``dry_run``, ``error`` tell the outcome).  Suppressed intents
        get NO callback — stateful policies flip their hysteresis
        latches only here, so a suppressed or failed action is
        re-intended and retried once the guardrails allow."""

    @staticmethod
    def _acted(rec):
        """True when the decision took effect (a dry-run rehearsal
        counts — the preview must walk the same state sequence the
        armed engine would)."""
        return bool(rec.get("executed") or rec.get("dry_run"))

    def _intent(self, action, **kw):
        return Intent(action, self.name, **kw)


class StragglerPolicy(Policy):
    """Elastic shrink/grow from the health plane's straggler hints.

    An executor flagged for ``sustain`` consecutive rounds is shrunk
    out of the gang (``elastic_shrink`` — the cluster actuator holds
    its compute and re-rendezvouses the survivors at reduced width).
    A held executor absent from the hints for ``grow_after``
    consecutive rounds is grown back in (``elastic_grow``).  Evidence
    is the hint itself — it carries the detector's phase attribution
    (the measured dominant phase, feed/h2d/dispatch/wire/host), so
    the decision names WHY the executor was slow, not just that it
    was.

    ``held`` moves on execution feedback (:meth:`on_decision`), never
    on emission: a shrink suppressed by the rate limit or failed by
    the actuator leaves the executor un-held and the intent retried,
    and a grow is never emitted for an executor that was never
    actually held.
    """

    name = "straggler-elastic"

    def __init__(self, sustain=2, grow_after=3):
        self.sustain = max(1, int(sustain))
        self.grow_after = max(1, int(grow_after))
        self._rounds = {}        # executor -> consecutive flagged rounds
        self._clean = {}         # held executor -> consecutive clean rounds
        self.held = set()

    def evaluate(self, snap):
        out = []
        hints = snap.hints or {}
        for eid, hint in sorted(hints.items()):
            if eid in self.held:
                self._clean[eid] = 0
                continue
            self._rounds[eid] = self._rounds.get(eid, 0) + 1
            if self._rounds[eid] >= self.sustain:
                out.append(self._intent(
                    "elastic_shrink", target={"executor": eid},
                    evidence={"hint": dict(hint)},
                    reason="straggler flagged {0} consecutive rounds "
                           "(phase {1!r})".format(
                               self._rounds[eid], hint.get("phase")
                           ),
                ))
        for eid in list(self._rounds):
            if eid not in hints:
                self._rounds.pop(eid, None)
        for eid in sorted(self.held):
            if eid in hints:
                continue
            self._clean[eid] = self._clean.get(eid, 0) + 1
            if self._clean[eid] >= self.grow_after:
                out.append(self._intent(
                    "elastic_grow", target={"executor": eid},
                    evidence={"clean_rounds": self._clean[eid]},
                    severity="info",
                    reason="held executor clean for {0} rounds".format(
                        self._clean[eid]
                    ),
                ))
        return out

    def on_decision(self, rec):
        if not self._acted(rec):
            return
        eid = (rec.get("target") or {}).get("executor")
        if eid is None:
            return
        if rec.get("action") == "elastic_shrink":
            self.held.add(eid)
            self._rounds.pop(eid, None)
            self._clean[eid] = 0
        elif rec.get("action") == "elastic_grow":
            self.held.discard(eid)
            self._clean.pop(eid, None)


class AutoscalePolicy(Policy):
    """Serving autoscale from the router's windowed admission
    pressure (PR 13's lifecycle verbs as a closed loop): mean queue
    occupancy above ``high`` (or any shedding) for ``sustain``
    consecutive rounds spawns a replica; occupancy below ``low`` with
    idle slots for ``sustain_down`` rounds retires one.  Bounded by
    ``min_replicas``/``max_replicas`` so a runaway signal can never
    scale to zero or to infinity.  Evidence is the pressure excerpt
    itself — the SAME statistic ``/status`` shows an operator."""

    name = "fleet-autoscale"

    def __init__(self, high=0.75, low=0.10, sustain=3,
                 sustain_down=6, min_replicas=1, max_replicas=8):
        self.high = float(high)
        self.low = float(low)
        self.sustain = max(1, int(sustain))
        self.sustain_down = max(1, int(sustain_down))
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = int(max_replicas)
        self._hot = 0
        self._cold = 0

    def evaluate(self, snap):
        p = snap.pressure
        fleet = snap.fleet or {}
        if not p:
            return []
        live = int(fleet.get("live", fleet.get("replicas", 0)) or 0)
        hot = (p.get("occupancy_mean", 0.0) >= self.high
               or p.get("shed_per_sec", 0.0) > 0.0)
        cold = (p.get("occupancy_peak", 0.0) <= self.low
                and p.get("free_slots", 0) > 0
                and p.get("shed_per_sec", 0.0) == 0.0)
        self._hot = self._hot + 1 if hot else 0
        self._cold = self._cold + 1 if cold else 0
        excerpt = {k: p.get(k) for k in (
            "window_sec", "occupancy", "occupancy_mean",
            "occupancy_peak", "shed_per_sec", "spill_per_sec",
            "free_slots",
        )}
        if self._hot >= self.sustain and live < self.max_replicas:
            self._hot = 0
            return [self._intent(
                "spawn_replica", evidence={"pressure": excerpt},
                reason="admission pressure sustained {0} rounds "
                       "(occupancy_mean {1}, shed/s {2})".format(
                           self.sustain, excerpt["occupancy_mean"],
                           excerpt["shed_per_sec"],
                       ),
            )]
        if self._cold >= self.sustain_down and live > self.min_replicas:
            self._cold = 0
            return [self._intent(
                "retire_replica", evidence={"pressure": excerpt},
                severity="info",
                reason="idle slots sustained {0} rounds".format(
                    self.sustain_down
                ),
            )]
        return []


class PageAlertPolicy(Policy):
    """Degrade admission on any PAGE-severity alert firing; restore
    when the pages that caused the degrade have all resolved.
    Evidence is the alert transition (with its ``alerts_since``
    cursor seq) — the decision and the page that caused it share a
    journal-visible id.

    ``degraded`` flips on execution feedback (:meth:`on_decision`):
    a degrade suppressed or failed while the pages still fire is
    re-intended every round until it actually lands — the latch can
    never read "degraded" while admission was left untouched."""

    name = "page-degrade"

    def __init__(self):
        self._paging = {}   # rule -> firing alert dict
        self.degraded = False

    def evaluate(self, snap):
        out = []
        for a in snap.alerts:
            if a.get("severity") != "page":
                continue
            if a.get("state") == "firing":
                self._paging[a.get("rule")] = dict(a)
            elif a.get("state") == "resolved":
                self._paging.pop(a.get("rule"), None)
        if self._paging and not self.degraded:
            worst = sorted(self._paging.values(),
                           key=lambda d: d.get("seq", 0))[-1]
            out.append(self._intent(
                "degrade_admission",
                evidence={"alert": worst,
                          "paging_rules": sorted(self._paging)},
                severity="page",
                reason="page alert {0!r} firing".format(
                    worst.get("rule")
                ),
            ))
        elif not self._paging and self.degraded:
            out.append(self._intent(
                "restore_admission",
                evidence={"resolved": True}, severity="info",
                reason="all page alerts resolved",
            ))
        return out

    def on_decision(self, rec):
        if not self._acted(rec):
            return
        if rec.get("action") == "degrade_admission":
            self.degraded = True
        elif rec.get("action") == "restore_admission":
            self.degraded = False


class SloRollbackPolicy(Policy):
    """Roll a weight generation back when fleet-level SLO burn
    exceeds budget while the generation is still on post-swap
    probation — PR 8's probation window, extended from request-level
    errors to the SLO plane.  Fires on ``burn:`` / ``burn_rate``
    alerts (any warn+ severity) only when ``snap.probation`` names
    replicas whose engines hold a rollback snapshot; the rollback
    itself is the engine's own (applied between decode chunks, via
    :func:`~tensorflowonspark_tpu.hot_swap.flag_probation_fault`)."""

    name = "slo-rollback"

    def __init__(self, rules=None):
        #: None = any firing alert whose rule name contains "burn" or
        #: whose message names a burn_rate breach; else an explicit
        #: rule-name allowlist
        self.rules = set(rules) if rules else None

    def _matches(self, a):
        if self.rules is not None:
            return a.get("rule") in self.rules
        rule = a.get("rule") or ""
        return "burn" in rule or "burn_rate" in (a.get("message") or "")

    def evaluate(self, snap):
        if not snap.probation:
            return []
        for a in snap.alerts:
            if a.get("state") == "firing" and self._matches(a):
                return [self._intent(
                    "rollback_generation",
                    target={"replicas": sorted(snap.probation)},
                    evidence={"alert": dict(a),
                              "probation": sorted(snap.probation)},
                    severity="page",
                    reason="SLO burn {0!r} while generation on "
                           "probation".format(a.get("rule")),
                )]
        return []


class CostPolicy(Policy):
    """Cost-efficiency policy over the usage ledger's per-replica
    cost rows (ISSUE 18 satellite): probe — and, if it stays bad,
    evict — the replica with the worst **chip-seconds per emitted
    token**, not merely the slowest one.  A replica can be perfectly
    responsive yet burn 3x the chips per token (quantization fell
    back to float, a cold pallas path, thermal throttling): latency
    policies never see it, the ledger does.

    Reads the PR 14 cost rows off ``snap.fleet["costs"]`` (the
    router's ``health_status()`` mirror of the usage ledger) — or an
    injected ``ledger_fn`` (the fake-ledger unit test's seam).  A
    replica whose ratio exceeds ``ratio_factor`` x the fleet median
    for ``sustain`` consecutive rounds gets a ``probe_replica``
    intent (the router routes around it and probes it for recovery —
    reversible); one that STAYS the outlier for ``evict_after``
    further rounds after the probe executed gets ``retire_replica``
    (permanent).  Every intent carries the ratio table it was judged
    on.  Replicas with fewer than ``min_tokens`` emitted are not
    judged — a cold replica's ratio is all prefill."""

    name = "cost-efficiency"

    def __init__(self, ratio_factor=2.0, min_tokens=256, sustain=3,
                 evict_after=3, ledger_fn=None):
        self.ratio_factor = float(ratio_factor)
        self.min_tokens = int(min_tokens)
        self.sustain = max(1, int(sustain))
        self.evict_after = max(1, int(evict_after))
        self.ledger_fn = ledger_fn
        self._worst_rounds = {}   # rid -> consecutive outlier rounds
        self._post_probe = {}     # probed rid -> outlier rounds since
        self.probed = set()

    def _rows(self, snap):
        if self.ledger_fn is not None:
            return self.ledger_fn() or {}
        return ((snap.fleet or {}).get("costs")
                if isinstance(snap.fleet, dict) else None) or {}

    def evaluate(self, snap):
        from tensorflowonspark_tpu.telemetry.ledger import (
            chip_sec_per_token,
        )

        rows = self._rows(snap)
        judged = {
            rid: row for rid, row in rows.items()
            if row.get("state") in (None, "live", "routed_around")
        }
        ratios = chip_sec_per_token(judged, min_tokens=self.min_tokens)
        if len(ratios) < 2:
            self._worst_rounds.clear()
            return []
        med = sorted(ratios.values())[len(ratios) // 2]
        worst = max(sorted(ratios), key=lambda r: ratios[r])
        outlier = med > 0 and ratios[worst] >= self.ratio_factor * med
        for rid in list(self._worst_rounds):
            if rid != worst or not outlier:
                self._worst_rounds.pop(rid, None)
        for rid in list(self._post_probe):
            if rid != worst or not outlier:
                # recovered (or another replica became the problem):
                # the router's probe traffic readmits it; a later
                # regression starts a fresh probe cycle
                self._post_probe.pop(rid, None)
                self.probed.discard(rid)
        if not outlier:
            return []
        evidence = {
            "ratios_chip_sec_per_token": {
                r: round(v, 6) for r, v in sorted(ratios.items())
            },
            "worst": worst,
            "median": round(med, 6),
            "threshold_factor": self.ratio_factor,
            "row": dict(rows.get(worst) or {}),
        }
        if worst in self.probed:
            self._post_probe[worst] = self._post_probe.get(worst, 0) + 1
            evidence["post_probe_rounds"] = self._post_probe[worst]
            if self._post_probe[worst] >= self.evict_after:
                return [self._intent(
                    "retire_replica",
                    target={"replica_id": worst}, evidence=evidence,
                    reason="still {0:.1f}x the median chip_sec/token "
                           "{1} rounds after probe".format(
                               ratios[worst] / med,
                               self._post_probe[worst]),
                )]
            return []
        self._worst_rounds[worst] = self._worst_rounds.get(worst, 0) + 1
        evidence["sustained_rounds"] = self._worst_rounds[worst]
        if self._worst_rounds[worst] >= self.sustain:
            return [self._intent(
                "probe_replica",
                target={"replica_id": worst}, evidence=evidence,
                reason="worst chip_sec/token at {0:.1f}x the fleet "
                       "median for {1} rounds".format(
                           ratios[worst] / med,
                           self._worst_rounds[worst]),
            )]
        return []

    def on_decision(self, rec):
        if not self._acted(rec):
            return
        rid = (rec.get("target") or {}).get("replica_id")
        if rid is None:
            return
        if rec.get("action") == "probe_replica":
            self.probed.add(rid)
            self._worst_rounds.pop(rid, None)
            self._post_probe[rid] = 0
        elif rec.get("action") == "retire_replica":
            self.probed.discard(rid)
            self._post_probe.pop(rid, None)
            self._worst_rounds.pop(rid, None)


#: journal fault kinds → the policy's response action.  Faults whose
#: recovery is ALREADY owned by a lower plane get an explicit
#: ``stand_down`` decision — the audit trail must show remediation
#: saw the fault and chose not to fight the recovery in progress,
#: the same philosophy as the deploy-conflict guardrail.
FAULT_RESPONSES = {
    "replica_dead": "spawn_replica",
    "leader_failover": "stand_down",
    "swap_rollback": "stand_down",
    "checkpoint_quarantined": "stand_down",
    "deploy_halted": "stand_down",
    # disaggregated-serving containment (ISSUE 19): the engine's
    # in-line containment already rebuilt the worker it fell over
    # on — the remediation restart re-arms supervision fleet-wide
    # (idempotent); a quarantined replica keeps serving probe
    # traffic, so lost capacity is restored by spawning; a reaped
    # lease was fully recovered by the pool (stand down, audited)
    "prefill_worker_dead": "restart_prefill",
    "prefill_watchdog_fire": "restart_prefill",
    "replica_quarantined": "spawn_replica",
    "lease_reaped": "stand_down",
}


class FaultResponsePolicy(Policy):
    """Respond to journal FAULT events (:data:`FAULT_RESPONSES`):
    re-spawn capacity lost to a replica death, and stand down —
    explicitly, in the journal — where a lower plane's automatic
    recovery (leader re-election, probation rollback, checkpoint
    quarantine, deploy halt) already owns the fault.  Evidence is the
    triggering event's ``(kind, executor, pid, seq)`` id, the exact
    coordinates ``forensics explain`` aligns on its timeline."""

    name = "fault-response"

    def __init__(self, responses=None):
        self.responses = dict(
            FAULT_RESPONSES if responses is None else responses
        )

    def evaluate(self, snap):
        out = []
        for ev in snap.events:
            action = self.responses.get(ev.get("kind"))
            if action is None:
                continue
            evid = {"event": {
                k: ev.get(k)
                for k in ("kind", "executor", "pid", "seq", "t", "ts")
                if ev.get(k) is not None
            }}
            attrs = ev.get("attrs") or {}
            for k in ("replica_id", "replica", "rule", "step",
                      "request_ids"):
                if k in attrs:
                    evid["event"][k] = attrs[k]
            target = {}
            if action == "stand_down":
                # cooldowns key on (action, target): standing down for
                # a leader failover must not suppress the stand-down
                # for a checkpoint quarantine seconds later — each
                # fault kind is its own decision
                target = {"fault": ev.get("kind")}
            if action == "spawn_replica":
                # the router's live mark says ``replica``; shipped
                # exports may say ``replica_id``
                rid = attrs.get("replica_id", attrs.get("replica"))
                evid["lost_replica"] = rid
                # cooldowns key on (action, target): each lost
                # replica is its own respawn decision, so a
                # multi-death storm restores EVERY death instead of
                # collapsing into one cooldown-suppressed spawn
                target = {"lost_replica": rid}
            if action == "restart_prefill":
                # fault marks ride the faulted request's trace, not a
                # replica id — the actuator rebuilds every (or the
                # named) disaggregated worker; cooldown per fault kind
                # so a dead worker and a wedged one stay separate
                # decisions
                target = {"fault": ev.get("kind")}
            out.append(self._intent(
                action, target=target, evidence=evid,
                severity="info" if action == "stand_down" else "warn",
                reason="journal fault {0!r}".format(ev.get("kind")),
            ))
        return out


def default_policies(**overrides):
    """The standard policy set.  Keyword overrides replace the knobs
    of the matching policy, e.g. ``default_policies(
    autoscale={"high": 0.5}, straggler={"sustain": 3})``; pass
    ``<name>=None`` to drop one."""
    specs = {
        "straggler": (StragglerPolicy, overrides.pop("straggler", {})),
        "autoscale": (AutoscalePolicy, overrides.pop("autoscale", {})),
        "page": (PageAlertPolicy, overrides.pop("page", {})),
        "slo_rollback": (
            SloRollbackPolicy, overrides.pop("slo_rollback", {})
        ),
        "faults": (
            FaultResponsePolicy, overrides.pop("faults", {})
        ),
        "cost": (CostPolicy, overrides.pop("cost", {})),
    }
    if overrides:
        raise ValueError(
            "unknown policy overrides {0}".format(sorted(overrides))
        )
    out = []
    for _key, (cls, kw) in specs.items():
        if kw is None:
            continue
        out.append(cls(**kw))
    return out
