"""HTTP exposition: OpenMetrics ``/metrics``, ``/healthz``, ``/status``.

The scrape surface of the fleet health plane (ISSUE 10 tentpole).  A
:class:`~tensorflowonspark_tpu.telemetry.health.HealthPlane` (or any
object with ``merged_snapshot()`` / ``healthz()`` / ``status()``) gets
an HTTP endpoint a Prometheus-compatible collector, a load balancer,
or a human with ``curl`` can hit:

- ``GET /metrics`` — the fleet-merged registry snapshot in OpenMetrics
  text format (:func:`to_openmetrics`): counters as ``_total``
  samples, gauges verbatim, histograms as cumulative ``_bucket{le=}``
  samples plus the exact ``_sum``/``_count`` pair (the ISSUE 10
  exact-sum satellite is what makes ``_sum`` honest rather than
  bucket-interpolated);
- ``GET /healthz`` — liveness merged from heartbeat age + compute
  state + page-severity SLO alerts; **200** when healthy, **503**
  with the reasons when not (the orchestrator-probe contract);
- ``GET /status`` — compact JSON fleet summary: per-executor rates,
  active alerts, straggler hints, and the registered subsystem
  providers (serving engine, hier-PS DCN link, partition ledger);
- ``GET /usage`` — per-tenant cost attribution (ISSUE 14): OpenMetrics
  counters labeled ``tenant="..."`` with cardinality bounded by the
  usage ledger's tenant table (round-trips :func:`parse_openmetrics`);
  ``?format=json`` returns the full JSON view including the
  heavy-hitter sketch estimates.

:func:`parse_openmetrics` is the STRICT parser the tests round-trip
``/metrics`` output through — it enforces the format invariants a real
collector relies on (TYPE-declared families, counter samples ending in
``_total``, cumulative non-decreasing buckets, a ``+Inf`` bucket equal
to ``_count``, the ``# EOF`` terminator).

Metric names are sanitized for the exposition only (dots →
underscores: ``serving.request_latency_sec`` →
``serving_request_latency_sec``); the registry keeps the dotted names.
"""

import json
import logging
import re
import threading

try:  # http.server is stdlib, but keep imports at the top gated so a
    # stripped-down interpreter can still import the telemetry package
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
except ImportError:  # pragma: no cover
    BaseHTTPRequestHandler = object
    ThreadingHTTPServer = None

logger = logging.getLogger(__name__)

#: Content type of ``/metrics`` (the OpenMetrics media type; Prometheus
#: also accepts it).
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")

#: One OpenMetrics sample line: ``name{labels} value`` (no timestamps
#: — the scraper stamps arrival time, the store keeps history).
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>[^ ]+)$"
)
_LABEL_RE = re.compile(r'^(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>[^"]*)"$')


def sanitize_name(name):
    """Registry name → OpenMetrics metric name (dots and other
    punctuation become underscores; a leading digit gets a ``_``)."""
    out = _SANITIZE.sub("_", str(name))
    if not out or not _NAME_OK.match(out):
        out = "_" + out
    return out


def _fmt(v):
    """OpenMetrics number formatting: integers bare, floats via repr
    (full precision — the exact-sum satellite must survive the text
    round trip)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def to_openmetrics(snapshot):
    """Registry snapshot (or fleet merge) → OpenMetrics text.

    Mapping (docs/observability.md "Fleet health plane" has the
    table): counters emit one ``<name>_total`` sample; gauges one
    ``<name>`` sample; histograms the cumulative
    ``<name>_bucket{le="..."}`` series (every nonzero bucket's upper
    bound, then ``+Inf``) plus ``<name>_sum`` (the exact running sum)
    and ``<name>_count``.  Ends with the mandatory ``# EOF``.
    """
    lines = []
    for name in sorted(snapshot.get("counters", {})):
        om = sanitize_name(name)
        lines.append("# TYPE {0} counter".format(om))
        lines.append(
            "{0}_total {1}".format(
                om, _fmt(snapshot["counters"][name])
            )
        )
    for name in sorted(snapshot.get("gauges", {})):
        om = sanitize_name(name)
        lines.append("# TYPE {0} gauge".format(om))
        lines.append("{0} {1}".format(om, _fmt(snapshot["gauges"][name])))
    for name in sorted(snapshot.get("histograms", {})):
        h = snapshot["histograms"][name] or {}
        om = sanitize_name(name)
        lines.append("# TYPE {0} histogram".format(om))
        cum = 0
        for _lo, hi, c in h.get("buckets", []):
            if hi is None:  # the overflow bucket folds into +Inf
                continue
            cum += c
            lines.append(
                '{0}_bucket{{le="{1}"}} {2}'.format(om, _fmt(float(hi)), cum)
            )
        total = int(h.get("count", 0))
        lines.append('{0}_bucket{{le="+Inf"}} {1}'.format(om, total))
        lines.append("{0}_sum {1}".format(om, _fmt(h.get("sum", 0.0))))
        lines.append("{0}_count {1}".format(om, total))
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_openmetrics(text):
    """STRICT OpenMetrics text parser (the test round-trip oracle).

    Returns ``{family: {"type": str, "samples": [(name, labels,
    value)]}}``.  Raises :class:`ValueError` on any violation of the
    invariants a collector relies on:

    - the exposition must end with ``# EOF`` (nothing after it);
    - every sample's family must have a prior ``# TYPE`` declaration;
    - counter samples must use the ``_total`` suffix;
    - histogram ``_bucket`` series must be cumulative (non-decreasing
      in ``le`` order), include ``le="+Inf"``, and have
      ``+Inf == _count``;
    - values must parse as numbers, labels as ``key="value"``.
    """
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    if not lines or lines[-1] != "# EOF":
        raise ValueError("exposition does not end with '# EOF'")
    families = {}
    for line in lines[:-1]:
        if not line:
            raise ValueError("blank line inside exposition")
        if line.startswith("#"):
            parts = line.split(" ")
            if len(parts) < 2:
                raise ValueError("unparseable comment line %r" % line)
            if len(parts) >= 4 and parts[1] == "TYPE":
                fam, ftype = parts[2], parts[3]
                if fam in families:
                    raise ValueError(
                        "duplicate TYPE declaration for %r" % fam
                    )
                if ftype not in ("counter", "gauge", "histogram",
                                 "summary", "unknown"):
                    raise ValueError(
                        "unknown metric type %r for %r" % (ftype, fam)
                    )
                families[fam] = {"type": ftype, "samples": []}
                continue
            if parts[1] == "EOF":
                raise ValueError("'# EOF' before the end of the exposition")
            if parts[1] in ("HELP", "UNIT"):
                continue
            raise ValueError("unparseable comment line %r" % line)
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError("unparseable sample line %r" % line)
        name = m.group("name")
        labels = {}
        if m.group("labels"):
            for part in m.group("labels").split(","):
                lm = _LABEL_RE.match(part.strip())
                if not lm:
                    raise ValueError(
                        "unparseable label %r in %r" % (part, line)
                    )
                labels[lm.group("k")] = lm.group("v")
        raw = m.group("value")
        try:
            value = float(raw)
        except ValueError:
            raise ValueError("unparseable value %r in %r" % (raw, line))
        fam = _family_of(name, families)
        if fam is None:
            raise ValueError(
                "sample %r has no TYPE-declared family" % name
            )
        families[fam]["samples"].append((name, labels, value))
    _validate_families(families)
    return families


def _family_of(sample_name, families):
    if sample_name in families:
        return sample_name
    for suffix in ("_total", "_bucket", "_sum", "_count", "_created"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if base in families:
                return base
    return None


def _validate_families(families):
    for fam, rec in families.items():
        ftype, samples = rec["type"], rec["samples"]
        if ftype == "counter":
            for name, _labels, _v in samples:
                if name != fam + "_total":
                    raise ValueError(
                        "counter %r sample %r lacks the _total "
                        "suffix" % (fam, name)
                    )
        elif ftype == "histogram":
            buckets = [
                (labels.get("le"), v)
                for name, labels, v in samples
                if name == fam + "_bucket"
            ]
            if not buckets:
                raise ValueError("histogram %r has no buckets" % fam)
            les = [le for le, _ in buckets]
            if "+Inf" not in les:
                raise ValueError(
                    "histogram %r lacks the +Inf bucket" % fam
                )
            counts = [v for _le, v in buckets]
            if any(b > a for a, b in zip(counts[1:], counts)):
                raise ValueError(
                    "histogram %r buckets are not cumulative "
                    "non-decreasing: %s" % (fam, counts)
                )
            # le values must be sorted ascending with +Inf last
            finite = [float(le) for le in les[:-1]]
            if les[-1] != "+Inf" or finite != sorted(finite):
                raise ValueError(
                    "histogram %r le series is not ascending with "
                    "+Inf last: %s" % (fam, les)
                )
            count = [
                v for name, _l, v in samples if name == fam + "_count"
            ]
            if not count:
                raise ValueError("histogram %r lacks _count" % fam)
            if counts[-1] != count[0]:
                raise ValueError(
                    "histogram %r +Inf bucket (%s) != _count (%s)"
                    % (fam, counts[-1], count[0])
                )
            if not any(
                name == fam + "_sum" for name, _l, _v in samples
            ):
                raise ValueError("histogram %r lacks _sum" % fam)
    return families


# ----------------------------------------------------------------------
# the HTTP server
# ----------------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    """Three-route handler bound to a health plane via the server."""

    server_version = "tfos-health/1.0"

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        plane = self.server.plane
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = to_openmetrics(plane.merged_snapshot()).encode(
                    "utf-8"
                )
                self._reply(200, OPENMETRICS_CONTENT_TYPE, body)
            elif path == "/healthz":
                hz = plane.healthz()
                self._reply(
                    200 if hz.get("healthy") else 503,
                    "application/json",
                    json.dumps(hz).encode("utf-8"),
                )
            elif path == "/status":
                self._reply(
                    200, "application/json",
                    json.dumps(plane.status()).encode("utf-8"),
                )
            elif path == "/journal" and hasattr(
                plane, "journal_events"
            ):
                # the fleet's typed-event record (ISSUE 11): what the
                # post-mortem analyzer consumes, as JSON
                self._reply(
                    200, "application/json",
                    json.dumps(plane.journal_events()).encode("utf-8"),
                )
            elif path == "/usage" and hasattr(plane, "usage"):
                # per-tenant cost attribution (ISSUE 14): OpenMetrics
                # counters with a bounded `tenant` label by default
                # (round-trips the strict parser), the full JSON view
                # (incl. heavy-hitter sketch estimates) on
                # ?format=json
                usage = plane.usage()
                if "format=json" in (
                    self.path.partition("?")[2] or ""
                ):
                    self._reply(
                        200, "application/json",
                        json.dumps(usage).encode("utf-8"),
                    )
                else:
                    from tensorflowonspark_tpu.telemetry import (
                        ledger as _ledger_mod,
                    )

                    body = _ledger_mod.usage_openmetrics(
                        usage.get("tenants", {})
                    ).encode("utf-8")
                    self._reply(200, OPENMETRICS_CONTENT_TYPE, body)
            else:
                self._reply(
                    404, "text/plain",
                    b"not found; routes: /metrics /healthz /status "
                    b"/journal /usage\n",
                )
        except Exception as e:  # noqa: BLE001 - a scrape must see 500,
            logger.warning(  # not a dropped connection
                "health exposition handler failed", exc_info=True
            )
            try:
                self._reply(
                    500, "text/plain", str(e).encode("utf-8", "replace")
                )
            except OSError:
                pass

    def _reply(self, code, ctype, body):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # noqa: A003 - silence stderr
        logger.debug("health http: " + fmt, *args)


class ExpositionServer(object):
    """Threaded HTTP server exposing one plane's three routes.

    ``port=0`` binds an ephemeral port (read it back from ``.port``).
    ``host`` defaults to loopback — bind ``0.0.0.0`` explicitly to
    expose the fleet's metrics beyond the driver host."""

    def __init__(self, plane, port=0, host="127.0.0.1"):
        if ThreadingHTTPServer is None:  # pragma: no cover
            raise RuntimeError("http.server unavailable in this build")
        self.plane = plane
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.plane = plane
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None

    @property
    def url(self):
        return "http://{0}:{1}".format(self.host, self.port)

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="health-exposition",
        )
        self._thread.start()
        logger.info("health exposition serving on %s", self.url)
        return self

    def stop(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:  # pragma: no cover
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
