"""Fleet telemetry plane: metrics registry, span tracing, aggregation.

Every subsystem used to invent its own counters — ``serving.py`` kept
p50/p99 in a local list, ``prefix_cache.stats()``, ``PSClient.
bytes_sent`` and ``DataFeed.wire_stats()`` were four incompatible
ad-hoc surfaces, and none of it crossed a process boundary to the
driver.  This package is the one place they all publish now
(docs/observability.md):

- :mod:`~tensorflowonspark_tpu.telemetry.registry` — a low-overhead
  process-wide metrics registry (counters, gauges, fixed-bucket
  histograms with interpolated p50/p99), lock-light, exported as
  plain dicts (``snapshot`` / ``snapshot_delta``);
- :mod:`~tensorflowonspark_tpu.telemetry.tracing` — structured span
  tracing with trace/parent-id propagation, exported as Chrome-trace
  (Perfetto-loadable) JSON;
- :mod:`~tensorflowonspark_tpu.telemetry.aggregate` — snapshot
  merging for the driver's fleet view (counters summed, histograms
  merged bucket-wise, percentiles recomputed) plus the node-side
  publisher that ships snapshots over the heartbeat plane to the
  reservation server, where ``TFCluster.metrics()`` pulls them;
- :mod:`~tensorflowonspark_tpu.telemetry.health` — the standing
  fleet health plane over the aggregation (ISSUE 10): per-executor
  time-series ring buffers with windowed queries, declarative SLO
  rules (thresholds + multiwindow error-budget burn rates) with
  hysteresis, and straggler auto-diagnosis that names the slow node
  and its dominant phase and auto-fires the profiler on it;
- :mod:`~tensorflowonspark_tpu.telemetry.exposition` — the HTTP
  scrape surface: ``/metrics`` in OpenMetrics text (with the strict
  parser the tests round-trip through), ``/healthz``, ``/status``,
  ``/journal``;
- :mod:`~tensorflowonspark_tpu.telemetry.journal` — the typed event
  journal (ISSUE 11): bounded severity-split rings + rotated JSONL
  persistence, auto-bridged from every ``Tracer.mark()`` site and
  shipped fleet-wide over the heartbeat piggyback to the reservation
  server's EventStore (clock-aligned via its heartbeat-RTT
  ``ClockSync``);
- :mod:`~tensorflowonspark_tpu.telemetry.blackbox` — the per-process
  flight recorder: always-on rings frozen into dump bundles on fault
  triggers (watchdog fire, swap rollback, supervisor restart, dead
  executor, leader failover, page-severity alerts), analyzed
  post-mortem by ``python -m tensorflowonspark_tpu.forensics
  explain``;
- :mod:`~tensorflowonspark_tpu.telemetry.ledger` — the per-request /
  per-tenant usage ledger (ISSUE 14): queue-wait, decode
  chip-seconds, KV page-seconds, prefix tokens saved, wire bytes,
  tokens in/out per request, aggregated under the reserved
  ``"tenant"`` input with bounded top-K heavy-hitter tracking, fleet
  totals riding the heartbeat piggyback as ``usage.*`` counters and
  the ``/usage`` HTTP route.

**Zero-cost-when-disabled**: ``TFOS_TELEMETRY=0`` (or
``set_enabled(False)``) makes every registry accessor return a shared
null singleton whose ``inc``/``observe`` are no-ops and makes
``tracer.span(...)`` return a shared null context manager — no
allocation, no locking, no span storage on the hot path (asserted in
tests/test_telemetry.py).
"""

from tensorflowonspark_tpu.telemetry.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NULL_COUNTER,
    NULL_GAUGE,
    NULL_HISTOGRAM,
    enabled,
    get_registry,
    histogram_percentile,
    set_enabled,
    snapshot_delta,
    tail_exemplars,
)
from tensorflowonspark_tpu.telemetry.tracing import (  # noqa: F401
    Tracer,
    get_tracer,
    merge_traces,
)
from tensorflowonspark_tpu.telemetry.journal import (  # noqa: F401
    Event,
    EventJournal,
    get_journal,
    load_journal,
)
from tensorflowonspark_tpu.telemetry.blackbox import (  # noqa: F401
    FlightRecorder,
    get_recorder,
    load_dump,
)
from tensorflowonspark_tpu.telemetry.aggregate import (  # noqa: F401
    NodePublisher,
    fleet_view,
    merge_snapshots,
    start_node_publisher,
)
from tensorflowonspark_tpu.telemetry.ledger import (  # noqa: F401
    DEFAULT_TENANT,
    SpaceSaving,
    UsageLedger,
    get_ledger,
    merge_usage,
    tenants_from_snapshot,
    usage_openmetrics,
)
from tensorflowonspark_tpu.telemetry.health import (  # noqa: F401
    Alert,
    HealthPlane,
    SloEngine,
    SloRule,
    StragglerDetector,
    TimeSeriesStore,
    load_rules,
    register_status_provider,
    unregister_status_provider,
)
from tensorflowonspark_tpu.telemetry.exposition import (  # noqa: F401
    ExpositionServer,
    parse_openmetrics,
    to_openmetrics,
)
