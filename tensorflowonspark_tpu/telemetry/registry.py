"""Process-wide metrics registry: counters, gauges, bucket histograms.

Design constraints (ISSUE 7 tentpole):

- **low overhead** — a counter ``inc`` is one lock acquire + integer
  add; a histogram ``observe`` is one ``bisect`` + two adds.  Metrics
  are resolved by name ONCE and cached by the instrumented object, so
  the hot path never touches the registry dict;
- **lock-light** — one small lock per *metric* (never a global lock on
  the observe path; the registry-level lock only guards name→metric
  resolution);
- **plain-dict export** — ``snapshot()`` returns JSON-serializable
  dicts so a snapshot can ride a heartbeat frame to the reservation
  server unchanged (telemetry/aggregate.py), and ``snapshot_delta``
  subtracts two snapshots for per-job / per-bench-window accounting;
- **zero-cost-when-disabled** — a disabled registry hands out shared
  NULL singletons whose mutators are ``pass``: no allocation, no lock,
  nothing retained (tests/test_telemetry.py pins the identity).

Histograms use FIXED geometric buckets (ratio 1.25 spanning
``1e-5 .. ~460`` seconds by default) so two processes' histograms merge
bucket-wise without resampling; ``p50``/``p99`` are interpolated within
the hit bucket — error is bounded by the 25% bucket width and measured
far tighter against numpy percentiles in tests/test_telemetry.py.
"""

import bisect
import os
import threading
import time

#: Env kill-switch: ``TFOS_TELEMETRY=0`` disables the default registry
#: and tracer at import time (docs/observability.md "Overhead budget").
TELEMETRY_ENV = "TFOS_TELEMETRY"


def _env_enabled():
    return os.environ.get(TELEMETRY_ENV, "1").lower() not in (
        "0", "false", "off", "no",
    )


# ----------------------------------------------------------------------
# metric types
# ----------------------------------------------------------------------


class Counter(object):
    """Monotonic counter.  ``inc`` is thread-safe (per-metric lock)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    add = inc

    @property
    def value(self):
        return self._value


class Gauge(object):
    """Last-write-wins scalar (queue depths, cache bytes, ages)."""

    __slots__ = ("name", "_value")

    def __init__(self, name):
        self.name = name
        self._value = 0.0

    def set(self, v):
        self._value = float(v)

    @property
    def value(self):
        return self._value


def default_buckets():
    """Geometric latency buckets: 1e-5s .. ~460s at ratio 1.25 (88
    upper bounds).  Fixed so histograms from different processes merge
    bucket-wise (telemetry/aggregate.py)."""
    out = []
    b = 1e-5
    for _ in range(88):
        out.append(b)
        b *= 1.25
    return out


class Histogram(object):
    """Fixed-bucket histogram with interpolated percentiles.

    ``observe(v)`` finds the bucket via ``bisect`` and bumps its count
    under the metric lock; ``percentile(q)`` interpolates linearly
    inside the bucket the q-th observation falls in (values above the
    top bound clamp to it).  ``snapshot()`` exports plain dicts
    including the NONZERO ``[upper_bound, count]`` pairs, which is what
    cross-process merging and delta subtraction operate on.
    """

    __slots__ = (
        "name", "bounds", "_counts", "_count", "_sum", "_min", "_max",
        "_lock", "_exemplars",
    )

    def __init__(self, name, buckets=None):
        self.name = name
        self.bounds = sorted(float(b) for b in (buckets or default_buckets()))
        # one overflow bucket past the top bound
        self._counts = [0] * (len(self.bounds) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._lock = threading.Lock()
        # bucket index -> (ref, value, wall ts): the newest observation
        # per bucket that carried an exemplar reference (ISSUE 14 —
        # trace ids, so a tail-latency bucket names the exact request
        # whose merged trace explains it).  Bounded by the fixed bucket
        # count; last-write-wins within a bucket.
        self._exemplars = {}

    def observe(self, v, exemplar=None):
        """Record ``v``; ``exemplar`` optionally attaches a reference
        (a trace id) to ``v``'s bucket — retained newest-per-bucket so
        tail buckets always name a concrete offending request."""
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if exemplar is not None:
                self._exemplars[i] = (str(exemplar), v, time.time())

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def percentile(self, q):
        """Interpolated q-th percentile (q in [0, 100]); 0.0 when
        empty."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        return _percentile_from_counts(counts, self.bounds, total, q)

    def snapshot(self):
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
            lo, hi = self._min, self._max
            exemplars = dict(self._exemplars)
        out = {
            "count": total,
            # the EXACT running sum (never rounded, never re-derived
            # from buckets): means stay exact — not bucket-interpolated
            # — through snapshot_delta, merge_snapshots, and the
            # OpenMetrics `_sum` line (ISSUE 10 satellite)
            "sum": s,
            "min": lo,
            "max": hi,
            "p50": _percentile_from_counts(counts, self.bounds, total, 50),
            "p99": _percentile_from_counts(counts, self.bounds, total, 99),
            # NONZERO buckets as [lower, upper, count] triples (upper
            # None for the overflow bucket): carrying both edges keeps
            # percentile interpolation exact on sparse snapshots,
            # deltas, and cross-process merges
            "buckets": [
                [
                    self.bounds[i - 1] if i > 0 else 0.0,
                    self.bounds[i] if i < len(self.bounds) else None,
                    c,
                ]
                for i, c in enumerate(counts)
                if c
            ],
        }
        if exemplars:
            # [[lower, upper, {"ref", "value", "ts"}], ...] — the same
            # bucket-edge convention as the count triples, so deltas
            # and merges can align them without re-deriving bounds
            out["exemplars"] = [
                [
                    self.bounds[i - 1] if i > 0 else 0.0,
                    self.bounds[i] if i < len(self.bounds) else None,
                    {"ref": ref, "value": val, "ts": ts},
                ]
                for i, (ref, val, ts) in sorted(exemplars.items())
            ]
        if total:
            out["mean"] = s / total
        return out


def _percentile_from_counts(counts, bounds, total, q):
    """Shared percentile rule over ``[count-per-bucket]`` arrays —
    used by live histograms, snapshot deltas, and cross-process merges
    so every surface reports identical semantics."""
    if not total:
        return 0.0
    rank = max(1.0, (q / 100.0) * total)
    seen = 0
    for i, c in enumerate(counts):
        if not c:
            continue
        if seen + c >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            frac = (rank - seen) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        seen += c
    return bounds[-1]


def histogram_percentile(snapshot, q):
    """Percentile from a histogram *snapshot* (or a snapshot delta /
    cross-process merge): same interpolation as the live metric,
    operating on the ``[lower, upper, count]`` bucket triples."""
    if not snapshot or not snapshot.get("count"):
        return 0.0
    triples = snapshot.get("buckets") or []
    total = int(snapshot["count"])
    rank = max(1.0, (q / 100.0) * total)
    seen = 0
    result = 0.0
    for lo, hi, c in triples:
        top = lo if hi is None else hi  # overflow clamps to its edge
        result = top
        if not c:
            continue
        if seen + c >= rank:
            frac = (rank - seen) / c
            return lo + (top - lo) * min(1.0, max(0.0, frac))
        seen += c
    return result


def tail_exemplars(snapshot, q=99):
    """Exemplars from the buckets at/above the ``q``-th percentile of
    a histogram snapshot (or delta/merge) — "name me a request that
    actually lives in the p99 tail", heaviest bucket first.  Returns
    ``[{"ref", "value", "ts", "bucket_lo", "bucket_hi"}]`` (empty when
    the histogram recorded no exemplars).  The forensics analyzer uses
    the top entry to pull the exact merged trace of a tail request
    (ISSUE 14 — docs/observability.md "Cost attribution & usage
    ledger")."""
    if not snapshot:
        return []
    p = histogram_percentile(snapshot, q)
    out = []
    for lo, hi, ex in snapshot.get("exemplars", []) or []:
        top = lo if hi is None else hi
        if top >= p:
            out.append(dict(ex, bucket_lo=lo, bucket_hi=hi))
    out.sort(key=lambda e: -e["value"])
    return out


# ----------------------------------------------------------------------
# null objects: the disabled-mode fast path
# ----------------------------------------------------------------------


class _NullCounter(object):
    __slots__ = ()
    name = "<disabled>"
    value = 0

    def inc(self, n=1):
        pass

    add = inc


class _NullGauge(object):
    __slots__ = ()
    name = "<disabled>"
    value = 0.0

    def set(self, v):
        pass


class _NullHistogram(object):
    __slots__ = ()
    name = "<disabled>"
    count = 0
    sum = 0.0

    def observe(self, v, exemplar=None):
        pass

    def percentile(self, q):
        return 0.0

    def snapshot(self):
        return {"count": 0, "sum": 0.0, "buckets": []}


#: Shared singletons a disabled registry hands out — accessor calls
#: allocate NOTHING (identity-asserted in tests/test_telemetry.py).
NULL_COUNTER = _NullCounter()
NULL_GAUGE = _NullGauge()
NULL_HISTOGRAM = _NullHistogram()


# ----------------------------------------------------------------------
# registry
# ----------------------------------------------------------------------


class MetricsRegistry(object):
    """Name → metric store.  Accessors are get-or-create and memoized;
    instrumented objects should resolve their metrics ONCE (at
    ``__init__``) and keep the references — the per-call cost is then
    only the metric's own lock."""

    def __init__(self, enabled=None):
        self._enabled = _env_enabled() if enabled is None else bool(enabled)
        self._metrics = {}
        self._lock = threading.Lock()

    # -- enable/disable -------------------------------------------------

    @property
    def enabled(self):
        return self._enabled

    def enable(self):
        self._enabled = True

    def disable(self):
        self._enabled = False

    # -- accessors ------------------------------------------------------

    def _get(self, name, cls, *args):
        if not self._enabled:
            return _NULLS[cls]
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    "metric {0!r} is a {1}, not a {2}".format(
                        name, type(m).__name__, cls.__name__
                    )
                )
            return m

    def counter(self, name):
        return self._get(name, Counter)

    def gauge(self, name):
        return self._get(name, Gauge)

    def histogram(self, name, buckets=None):
        return self._get(name, Histogram, buckets)

    # -- export ---------------------------------------------------------

    def snapshot(self):
        """Plain-dict export: ``{"counters": {name: int}, "gauges":
        {name: float}, "histograms": {name: hist-snapshot}}`` — small,
        JSON-serializable, heartbeat-frame-sized."""
        with self._lock:
            metrics = list(self._metrics.items())
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in metrics:
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            else:
                out["histograms"][name] = m.snapshot()
        return out

    def reset(self):
        """Drop every metric (tests / per-bench-window isolation)."""
        with self._lock:
            self._metrics.clear()


_NULLS = {
    Counter: NULL_COUNTER,
    Gauge: NULL_GAUGE,
    Histogram: NULL_HISTOGRAM,
}


def snapshot_delta(cur, base):
    """``cur - base`` over two :meth:`MetricsRegistry.snapshot` dicts:
    counters subtract, histogram counts/sums/buckets subtract
    (percentiles recomputed over the delta), gauges keep ``cur``'s
    value.  The per-job / per-window accounting primitive (the serving
    bench uses it to report a run's p50/p99 from the shared
    histogram)."""
    base = base or {}
    out = {"counters": {}, "gauges": dict(cur.get("gauges", {})),
           "histograms": {}}
    bc = base.get("counters", {})
    for name, v in cur.get("counters", {}).items():
        out["counters"][name] = v - bc.get(name, 0)
    bh = base.get("histograms", {})
    for name, h in cur.get("histograms", {}).items():
        b = bh.get(name)
        if not b or not b.get("count"):
            out["histograms"][name] = dict(h)
            continue
        base_counts = {
            (lo, hi): c for lo, hi, c in b.get("buckets", [])
        }
        triples = []
        for lo, hi, c in h.get("buckets", []):
            dc = c - base_counts.get((lo, hi), 0)
            if dc:
                triples.append([lo, hi, dc])
        d = {
            "count": h.get("count", 0) - b.get("count", 0),
            "sum": h.get("sum", 0.0) - b.get("sum", 0.0),
            "buckets": triples,
        }
        if h.get("exemplars"):
            # keep only exemplars whose bucket saw traffic in this
            # window — a stale reference from before the base snapshot
            # would mislead the window's tail analysis
            live = {(lo, hi) for lo, hi, _c in triples}
            ex = [e for e in h["exemplars"] if (e[0], e[1]) in live]
            if ex:
                d["exemplars"] = ex
        d["p50"] = histogram_percentile(d, 50)
        d["p99"] = histogram_percentile(d, 99)
        if d["count"]:
            d["mean"] = d["sum"] / d["count"]
        out["histograms"][name] = d
    return out


# ----------------------------------------------------------------------
# process-global default
# ----------------------------------------------------------------------

_GLOBAL = None
_GLOBAL_LOCK = threading.Lock()


def get_registry():
    """The process-wide default registry every built-in surface
    publishes into (serving engine, slot decoder, prefix cache, PS
    client, feed plane, supervisor)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = MetricsRegistry()
    return _GLOBAL


def enabled():
    return get_registry().enabled


def set_enabled(flag):
    """Flip the default registry AND tracer (tests, the bench's
    instrumented-vs-disabled window).  Note: objects that cached a
    NULL metric while disabled keep the null — set the flag before
    constructing the surfaces you want measured."""
    reg = get_registry()
    if flag:
        reg.enable()
    else:
        reg.disable()
    from tensorflowonspark_tpu.telemetry import tracing

    tracing.get_tracer().set_enabled(flag)
