"""Typed event journal: the fleet's durable incident record.

Before this module, the only trace of a fault was an in-memory tracer
mark — gone with the process, invisible across executors, and never
written anywhere an operator could read after the fact.  The journal is
the audited-event substrate ROADMAP item 4's policy engine will act
through (ISSUE 11 tentpole; docs/observability.md "Incident
forensics"):

- :class:`Event` — one typed, structured occurrence: wall-clock ``ts``,
  monotonic per-process ``seq``, ``executor``, ``severity``
  (info/warn/page), ``kind``, optional ``trace`` id, and a flat
  ``attrs`` dict.  Plain-dict serializable (``to_dict``/``from_dict``)
  so events ride heartbeat frames, kv stores, and JSONL files
  unchanged;
- :class:`EventJournal` — the bounded per-process store.  TWO rings,
  split by severity: routine ``info`` events (per-request ``emit``
  marks, leader elections) and ``warn``/``page`` fault events each get
  their own ``deque(maxlen=...)``, so a flood of routine events can
  never evict the fault record an incident analysis needs.  Optional
  size-rotated JSONL persistence (``journal.jsonl`` →
  ``journal.jsonl.1`` → ...) makes the record survive the process;
- **mark bridge** — every
  :meth:`~tensorflowonspark_tpu.telemetry.tracing.Tracer.mark` call on
  an enabled tracer forwards to its journal (the global one by
  default), so the fault/action sites instrumented since PR 7
  (supervisor restarts, watchdog fires, shed/deadline cancels,
  swap/rollback/quarantine, leader elections, SLO alerts, straggler
  flags) become journal events with zero new call-site code;
- **listeners** — ``add_listener(fn)`` is the in-process event bus the
  :mod:`~tensorflowonspark_tpu.telemetry.blackbox` flight recorder
  subscribes its dump triggers to;
- **shipping cursor** — ``drain_unshipped()`` / ``events_since(seq)``
  feed the heartbeat piggyback path (cluster/reservation.py): each
  node's supervisor ships new events to the reservation server's
  fleet-wide :class:`~tensorflowonspark_tpu.cluster.reservation.
  EventStore`, where the forensics analyzer and ``TPUCluster.
  journal()`` read the merged, clock-alignable record.

Disabled mode (``TFOS_TELEMETRY=0``): ``emit`` returns None and stores
nothing — the journal follows the registry/tracer kill switch.
"""

import collections
import itertools
import json
import logging
import os
import threading
import time

from tensorflowonspark_tpu.telemetry import registry as _registry

logger = logging.getLogger(__name__)

#: The severity vocabulary, mirroring the SLO rule severities.  An
#: unknown severity normalizes to ``warn`` — a fault site typo must
#: surface loudly (in the fault ring), never vanish quietly.
SEVERITIES = ("info", "warn", "page")

#: Ring bound PER severity class (info ring and warn/page ring each,
#: env-tunable: TFOS_JOURNAL_MAX_EVENTS).
MAX_EVENTS = int(os.environ.get("TFOS_JOURNAL_MAX_EVENTS", "4096"))

#: JSONL rotation threshold in bytes (env-tunable:
#: TFOS_JOURNAL_MAX_BYTES) and rotated-file count
#: (TFOS_JOURNAL_MAX_FILES).
MAX_BYTES = int(os.environ.get("TFOS_JOURNAL_MAX_BYTES", str(1 << 20)))
MAX_FILES = int(os.environ.get("TFOS_JOURNAL_MAX_FILES", "3"))

#: Directory for the GLOBAL journal's JSONL persistence; unset (the
#: default) keeps the global journal memory-only — zero disk writes
#: unless an operator opts in.
JOURNAL_DIR_ENV = "TFOS_JOURNAL_DIR"


class Event(object):
    """One typed journal event (see module docstring)."""

    __slots__ = ("ts", "seq", "executor", "severity", "kind", "trace",
                 "attrs", "pid")

    def __init__(self, kind, ts=None, seq=0, executor=None,
                 severity="info", trace=None, attrs=None, pid=None):
        self.kind = str(kind)
        self.ts = time.time() if ts is None else float(ts)
        self.seq = int(seq)
        self.executor = executor
        self.severity = severity if severity in SEVERITIES else "warn"
        self.trace = trace
        self.attrs = dict(attrs) if attrs else {}
        self.pid = os.getpid() if pid is None else int(pid)

    def to_dict(self):
        out = {
            "ts": self.ts, "seq": self.seq, "kind": self.kind,
            "severity": self.severity, "pid": self.pid,
        }
        if self.executor is not None:
            out["executor"] = self.executor
        if self.trace is not None:
            out["trace"] = self.trace
        if self.attrs:
            out["attrs"] = self.attrs
        return out

    @classmethod
    def from_dict(cls, d):
        return cls(
            d.get("kind", "?"), ts=d.get("ts"), seq=d.get("seq", 0),
            executor=d.get("executor"),
            severity=d.get("severity", "info"), trace=d.get("trace"),
            attrs=d.get("attrs"), pid=d.get("pid"),
        )

    def __repr__(self):
        return "Event({0} {1} seq={2} executor={3})".format(
            self.severity, self.kind, self.seq, self.executor
        )


class EventJournal(object):
    """Bounded, optionally-persisted per-process event store.

    Args:
      max_events: per-severity-class ring bound (info events and
        warn/page events are stored in SEPARATE rings so routine
        traffic cannot evict the fault record).
      path: JSONL persistence base path (None = memory only).  The
        live file is ``path``; on exceeding ``max_bytes`` it rotates to
        ``path.1`` (older generations shift up, the oldest past
        ``max_files`` is deleted).
      executor: this process's executor id, stamped on every event
        (settable later via :meth:`set_identity` — compute processes
        learn their id after the journal exists).
      clock: wall-clock source (injectable for the clock-skew tests).
    """

    def __init__(self, max_events=None, path=None, max_bytes=None,
                 max_files=None, executor=None, registry=None,
                 clock=None, enabled=None):
        n = MAX_EVENTS if max_events is None else int(max_events)
        self._info = collections.deque(maxlen=n)
        self._fault = collections.deque(maxlen=n)
        self.path = os.fspath(path) if path else None
        self.max_bytes = MAX_BYTES if max_bytes is None else int(max_bytes)
        self.max_files = MAX_FILES if max_files is None else int(max_files)
        self.executor = executor
        self._clock = clock or time.time
        self._enabled = (
            _registry._env_enabled() if enabled is None else bool(enabled)
        )
        self._registry = registry
        self._m_events = None
        self._m_dropped = None
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self._listeners = []
        self._ship_cursor = 0
        #: events evicted from either ring (truncation made visible,
        #: same contract as Tracer.dropped_spans)
        self.dropped_events = 0

    # -- identity / lifecycle -------------------------------------------

    @property
    def enabled(self):
        return self._enabled

    def set_enabled(self, flag):
        self._enabled = bool(flag)

    def set_identity(self, executor):
        """Stamp subsequent events with this executor id (compute
        processes call this once their NodeContext is bound)."""
        self.executor = executor

    def add_listener(self, fn):
        """Register ``fn(event)``, called synchronously after every
        append.  A raising listener is logged and never propagates —
        the journal must record faults, not cause them."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn):
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    # -- recording ------------------------------------------------------

    def emit(self, kind, severity="info", trace=None, executor=None,
             attrs=None, ts=None, **extra):
        """Append one event; returns it (None when disabled).  ``attrs``
        and keyword extras merge into the event's attrs dict."""
        if not self._enabled:
            return None
        merged = dict(attrs) if attrs else {}
        if extra:
            merged.update(extra)
        ev = Event(
            kind, ts=self._clock() if ts is None else ts,
            seq=next(self._seq),
            executor=self.executor if executor is None else executor,
            severity=severity, trace=trace, attrs=merged or None,
        )
        ring = self._info if ev.severity == "info" else self._fault
        with self._lock:
            if len(ring) == ring.maxlen:
                self.dropped_events += 1
                if self._m_dropped is None:
                    self._m_dropped = self._reg().counter(
                        "journal.dropped_events"
                    )
                self._m_dropped.inc()
            ring.append(ev)
            listeners = list(self._listeners)
        if self._m_events is None:
            self._m_events = self._reg().counter("journal.events")
        self._m_events.inc()
        if self.path is not None:
            try:
                self._persist(ev)
            except OSError:
                logger.warning(
                    "journal persistence to %s failed", self.path,
                    exc_info=True,
                )
        for fn in listeners:
            try:
                fn(ev)
            except Exception:  # noqa: BLE001 - see add_listener
                logger.warning("journal listener failed", exc_info=True)
        return ev

    def _reg(self):
        return self._registry or _registry.get_registry()

    # -- persistence ----------------------------------------------------

    def _persist(self, ev):
        line = json.dumps(ev.to_dict()) + "\n"
        try:
            size = os.path.getsize(self.path)
        except OSError:
            size = 0
        if size and size + len(line) > self.max_bytes:
            self._rotate()
        with open(self.path, "a") as f:
            f.write(line)

    def _rotate(self):
        """Shift ``path.N`` up one generation; the oldest past
        ``max_files`` rotations is deleted."""
        oldest = "{0}.{1}".format(self.path, self.max_files)
        if os.path.exists(oldest):
            try:
                os.remove(oldest)
            except OSError:
                pass
        for i in range(self.max_files - 1, 0, -1):
            src = "{0}.{1}".format(self.path, i)
            if os.path.exists(src):
                try:
                    os.replace(src, "{0}.{1}".format(self.path, i + 1))
                except OSError:
                    pass
        try:
            os.replace(self.path, "{0}.1".format(self.path))
        except OSError:
            pass

    # -- queries --------------------------------------------------------

    def events(self, kind=None, severity=None, trace=None, limit=None):
        """Snapshot of retained events (both rings), seq-ordered,
        optionally filtered; ``limit`` keeps the newest N."""
        with self._lock:
            out = list(self._info) + list(self._fault)
        out.sort(key=lambda e: e.seq)
        if kind is not None:
            out = [e for e in out if e.kind == kind]
        if severity is not None:
            out = [e for e in out if e.severity == severity]
        if trace is not None:
            out = [e for e in out if e.trace == trace]
        if limit is not None:
            out = out[-int(limit):]
        return out

    def tail(self, n):
        return self.events(limit=n)

    def count(self, kind, severity=None):
        return len(self.events(kind=kind, severity=severity))

    def events_since(self, seq, limit=None):
        """Events with ``seq`` strictly greater than the given cursor
        (the shipping primitive — seqs are process-monotonic)."""
        out = [e for e in self.events() if e.seq > int(seq)]
        if limit is not None:
            out = out[: int(limit)]
        return out

    def drain_unshipped(self, limit=128):
        """Events not yet returned by a previous drain (single-consumer
        cursor — the node's heartbeat events_fn).  The cursor advances
        only over what is RETURNED, so a bounded drain never skips."""
        with self._lock:
            cursor = self._ship_cursor
        out = self.events_since(cursor, limit=limit)
        if out:
            with self._lock:
                self._ship_cursor = max(self._ship_cursor, out[-1].seq)
        return out

    def clear(self):
        with self._lock:
            self._info.clear()
            self._fault.clear()
            self._ship_cursor = 0

    def save(self, path):
        """Write every retained event as JSONL (one manual snapshot —
        distinct from the rotating live persistence); returns ``path``."""
        with open(path, "w") as f:
            for ev in self.events():
                f.write(json.dumps(ev.to_dict()) + "\n")
        return path


def load_journal(path):
    """Read a JSONL journal back as ``[Event]`` — includes rotated
    generations (``path.N``, oldest first) when present.  Unparseable
    lines are skipped with a warning (a torn final line from a killed
    process must not sink the post-mortem)."""
    path = os.fspath(path)
    files = []
    for i in range(MAX_FILES + 8, 0, -1):
        p = "{0}.{1}".format(path, i)
        if os.path.exists(p):
            files.append(p)
    if os.path.exists(path):
        files.append(path)
    out = []
    for p in files:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(Event.from_dict(json.loads(line)))
                except (ValueError, TypeError):
                    logger.warning("skipping unparseable journal line "
                                   "in %s", p)
    return out


_GLOBAL = None
_GLOBAL_LOCK = threading.Lock()


def get_journal():
    """The process-wide default journal (same enable story as the
    default registry/tracer).  Persists to
    ``$TFOS_JOURNAL_DIR/journal-<pid>.jsonl`` when that env var names a
    directory; memory-only otherwise."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                path = None
                d = os.environ.get(JOURNAL_DIR_ENV)
                if d:
                    try:
                        os.makedirs(d, exist_ok=True)
                        path = os.path.join(
                            d, "journal-{0}.jsonl".format(os.getpid())
                        )
                    except OSError:
                        logger.warning(
                            "cannot create journal dir %r; journal "
                            "stays memory-only", d, exc_info=True,
                        )
                _GLOBAL = EventJournal(path=path)
    return _GLOBAL
