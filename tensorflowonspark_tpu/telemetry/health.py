"""Fleet health plane: time series, SLO burn-rate alerting, stragglers.

PR 7 built the telemetry *collection* plane — per-process registries,
heartbeat-piggybacked fleet aggregation, ``TPUCluster.metrics()``.
That view is a one-shot merged snapshot: no history, no rates, no SLO
evaluation, and no automatic answer to "which executor is slow and
why".  This module is the *consumption* half (ISSUE 10 tentpole;
docs/observability.md "Fleet health plane"):

- :class:`TimeSeriesStore` — bounded per-executor ring buffers of
  ``snapshot_delta`` frames with windowed queries (``rate()``,
  ``p99_over()``, per-executor series).  Counter resets (an executor
  restart zeroes its registry) follow the Prometheus rule: a negative
  delta is treated as a reset and the post-reset value becomes the
  delta, so rates never go negative and never double-count;
- :class:`SloEngine` — declarative rules (``slo.yaml`` or plain dict
  config, see :func:`load_rules`) evaluated against the store:
  threshold rules (``p99 < X`` over a window) and **error-budget
  burn-rate** rules (short + long window, both must burn — the
  multiwindow recipe that pages on fast burns without flapping on
  blips) with hysteresis on both edges (``for_count`` breaches to
  fire, ``clear_after`` clean evaluations to resolve).  Transitions
  emit typed :class:`Alert` records, ``health.alerts_fired`` /
  ``health.alerts_resolved`` counters, and tracer marks
  (``alert_firing`` / ``alert_resolved``);
- :class:`StragglerDetector` — per-executor outlier detection over the
  windowed series (median-absolute-deviation, with a leave-one-out
  ratio gate so 2–3 node fleets still detect) that names the slow
  executor AND its dominant phase from the PR 7 span taxonomy:
  ``feed`` (``train.feed_wait_sec``), ``h2d`` / ``dispatch``
  (``train.h2d_sec`` / ``train.dispatch_sec``), ``wire``
  (``ps.round_trip_sec``), or ``host`` (step-time residual none of the
  instrumented phases explains — GC pauses, CPU contention);
- :class:`HealthPlane` — the standing driver-side loop tying them
  together: scrape ``ClusterMonitor.metrics()`` (the METRICS wire op /
  heartbeat piggyback path — no new connections) every ``interval``,
  append frames, evaluate SLOs, diagnose stragglers, and on a fresh
  straggler fire the PR 7 profiler hook on the flagged node only
  (``profile_trigger`` → the node's ``profile_request`` kv, picked up
  by its :class:`~tensorflowonspark_tpu.telemetry.aggregate.
  NodePublisher`).  The HTTP exposition surface (`/metrics` OpenMetrics,
  `/healthz`, `/status`) lives in
  :mod:`~tensorflowonspark_tpu.telemetry.exposition`.

Everything here is driver-side host work on dict snapshots — nothing
touches the training or serving hot paths, and the whole plane is
measured at ≤2% alongside the instrumentation itself
(``bench.py telemetry_overhead`` → ``health_overhead_pct``).

Why a standing plane and not ad-hoc dumps: fleet throughput is
governed by the slowest chain through the graph (PAPERS: "The
TensorFlow Partitioning and Scheduling Problem: It's the Critical
Path!"), and diagnosing that chain needs per-link, per-phase timing
history (PAPERS: "Scalable Distributed DNN Training using TensorFlow
and CUDA-Aware MPI") — exactly what the windowed per-executor series
keep and the snapshot view throws away.
"""

import collections
import json
import logging
import os
import threading
import time

from tensorflowonspark_tpu.telemetry import aggregate as _aggregate
from tensorflowonspark_tpu.telemetry import registry as _registry

logger = logging.getLogger(__name__)

#: Seconds between driver-side scrapes (env-tunable:
#: TFOS_HEALTH_SCRAPE_INTERVAL).  Rides the same snapshots the
#: heartbeat plane already ships, so scraping faster than the node
#: publish interval (TFOS_TELEMETRY_PUBLISH_INTERVAL, 2s) only
#: re-reads unchanged data.
SCRAPE_INTERVAL = float(os.environ.get("TFOS_HEALTH_SCRAPE_INTERVAL", "2.0"))

#: Seconds of history each per-executor ring buffer answers queries
#: over (env-tunable: TFOS_HEALTH_WINDOW).
DEFAULT_WINDOW = float(os.environ.get("TFOS_HEALTH_WINDOW", "300"))


# ----------------------------------------------------------------------
# time-series store
# ----------------------------------------------------------------------


def _reset_safe_delta(cur, base):
    """``snapshot_delta`` with Prometheus counter-reset semantics: a
    restarted executor's registry starts from zero, so ``cur - base``
    goes negative — treat that as a reset and use ``cur`` itself as
    the delta (the post-reset increments are real work; a negative
    rate or a double-count are both lies)."""
    d = _registry.snapshot_delta(cur, base or {})
    for name, v in list(d.get("counters", {}).items()):
        if v < 0:
            d["counters"][name] = cur.get("counters", {}).get(name, 0)
    bh = (base or {}).get("histograms", {})
    for name, h in list(d.get("histograms", {}).items()):
        # a restarted executor can accumulate a HIGHER count than the
        # pre-restart base (count delta positive) while individual
        # buckets shrink — any bucket going backwards (or a negative
        # count/sum delta) means the base is from a previous life, so
        # substitute the raw post-restart snapshot
        cur_h = cur.get("histograms", {}).get(name) or {}
        cur_counts = {
            (lo, hi): c for lo, hi, c in cur_h.get("buckets") or ()
        }
        b = bh.get(name) or {}
        if (
            h.get("count", 0) < 0
            or h.get("sum", 0.0) < 0
            or any(
                cur_counts.get((lo, hi), 0) < c
                for lo, hi, c in b.get("buckets") or ()
            )
        ):
            d["histograms"][name] = dict(cur_h)
    return d


class TimeSeriesStore(object):
    """Bounded per-executor ring buffers of scrape frames.

    Each :meth:`append` computes the delta vs the executor's previous
    raw snapshot (:func:`_reset_safe_delta`) and stores a *frame*
    ``{"t", "delta", "raw"}`` in a ``deque(maxlen=max_frames)`` — the
    memory bound is ``executors × max_frames × snapshot size``
    regardless of how long the fleet runs.  Queries are windowed
    (seconds back from *now*) and work per-executor or fleet-wide.
    """

    def __init__(self, window=None, max_frames=600, clock=None):
        self.window = DEFAULT_WINDOW if window is None else float(window)
        self.max_frames = int(max_frames)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._frames = {}   # eid -> deque of frames
        self._last_raw = {}  # eid -> last raw snapshot
        self.scrapes = 0

    def executors(self):
        with self._lock:
            return sorted(self._frames)

    def append(self, executor_id, snapshot, t=None):
        """Record one scraped snapshot for ``executor_id``.  Returns
        the stored frame (or None for a falsy snapshot)."""
        if not snapshot:
            return None
        eid = int(executor_id)
        t = self._clock() if t is None else float(t)
        with self._lock:
            dq = self._frames.get(eid)
            if dq is None:
                dq = self._frames[eid] = collections.deque(
                    maxlen=self.max_frames
                )
            frame = {
                "t": t,
                "delta": _reset_safe_delta(
                    snapshot, self._last_raw.get(eid)
                ),
                "raw": snapshot,
            }
            self._last_raw[eid] = snapshot
            dq.append(frame)
            self.scrapes += 1
        return frame

    # -- frame access ---------------------------------------------------

    def frames(self, executor=None, window=None):
        """Frames inside the window, newest last.  ``executor=None``
        returns every executor's (interleaved, time-ordered)."""
        window = self.window if window is None else float(window)
        cutoff = self._clock() - window
        with self._lock:
            if executor is not None:
                out = [
                    f for f in self._frames.get(int(executor), ())
                    if f["t"] >= cutoff
                ]
            else:
                out = [
                    f for dq in self._frames.values() for f in dq
                    if f["t"] >= cutoff
                ]
        out.sort(key=lambda f: f["t"])
        return out

    def latest_raw(self, executor=None):
        """Newest raw snapshot per executor (``{eid: snapshot}``), or
        one executor's."""
        with self._lock:
            if executor is not None:
                return self._last_raw.get(int(executor))
            return dict(self._last_raw)

    # -- windowed queries ----------------------------------------------

    def sum_over(self, name, window=None, executor=None):
        """Total counter increments for ``name`` inside the window."""
        return sum(
            f["delta"].get("counters", {}).get(name, 0)
            for f in self.frames(executor, window)
        )

    def rate(self, name, window=None, executor=None):
        """Counter increments per second over the window (0.0 when the
        window holds fewer than two frames — a rate needs an
        interval)."""
        frames = self.frames(executor, window)
        if len(frames) < 2:
            return 0.0
        span = frames[-1]["t"] - frames[0]["t"]
        if span <= 0:
            return 0.0
        total = sum(
            f["delta"].get("counters", {}).get(name, 0) for f in frames
        )
        return total / span

    def hist_over(self, name, window=None, executor=None):
        """Histogram activity for ``name`` inside the window: the
        bucket-wise merge of every frame's delta (exact — the fixed
        bucket scheme again), shaped like a histogram snapshot."""
        deltas = [
            {"histograms": {name: f["delta"]["histograms"][name]}}
            for f in self.frames(executor, window)
            if name in f["delta"].get("histograms", {})
        ]
        merged = _aggregate.merge_snapshots(deltas)
        return merged["histograms"].get(
            name, {"count": 0, "sum": 0.0, "buckets": []}
        )

    def p99_over(self, name, window=None, executor=None, q=99):
        """Interpolated q-th percentile of ``name`` over the window."""
        return _registry.histogram_percentile(
            self.hist_over(name, window, executor), q
        )

    def mean_over(self, name, window=None, executor=None):
        """Exact windowed mean of histogram ``name`` (sum/count from
        the exact running sums — never bucket-interpolated), or None
        when nothing was observed."""
        h = self.hist_over(name, window, executor)
        if not h.get("count"):
            return None
        return h["sum"] / h["count"]

    def drift(self, name, baseline, window=None, executor=None):
        """Measured-over-planned drift factor: the exact windowed mean
        of histogram ``name`` divided by ``baseline`` — the live
        re-planner's trigger statistic (ISSUE 18: drift >= the
        trigger's factor for ``sustain`` rounds fires a re-plan).
        None when nothing was observed or ``baseline`` is not
        positive."""
        if baseline is None or float(baseline) <= 0.0:
            return None
        mean = self.mean_over(name, window, executor)
        if mean is None:
            return None
        return float(mean) / float(baseline)

    def gauge_last(self, name, executor=None):
        """Latest gauge value (max across executors fleet-wide — same
        rule as :func:`~tensorflowonspark_tpu.telemetry.aggregate.
        merge_snapshots`), or None when never reported."""
        raws = (
            [self.latest_raw(executor)] if executor is not None
            else list(self.latest_raw().values())
        )
        vals = [
            r["gauges"][name] for r in raws
            if r and name in r.get("gauges", {})
        ]
        return max(vals) if vals else None

    def series(self, name, executor, window=None, kind="counter"):
        """``[(t, value)]`` per-frame points for one executor — the
        plotting/debugging primitive.  ``kind``: ``counter`` (per-frame
        delta), ``gauge`` (raw value), ``hist_count`` / ``hist_mean``
        (per-frame delta count / exact mean)."""
        out = []
        for f in self.frames(executor, window):
            if kind == "counter":
                out.append((f["t"], f["delta"].get("counters", {}).get(name, 0)))
            elif kind == "gauge":
                g = f["raw"].get("gauges", {})
                if name in g:
                    out.append((f["t"], g[name]))
            else:
                h = f["delta"].get("histograms", {}).get(name)
                if not h:
                    continue
                if kind == "hist_count":
                    out.append((f["t"], h.get("count", 0)))
                elif kind == "hist_mean":
                    if h.get("count"):
                        out.append((f["t"], h["sum"] / h["count"]))
                else:
                    raise ValueError("unknown series kind %r" % kind)
        return out


# ----------------------------------------------------------------------
# SLO engine
# ----------------------------------------------------------------------


class Alert(object):
    """One typed alert transition (firing or resolved).

    Plain-data by design: ``to_dict()`` rides ``/status`` JSON and the
    bench record unchanged."""

    __slots__ = ("rule", "state", "value", "threshold", "window",
                 "severity", "executor", "t", "message", "seq")

    def __init__(self, rule, state, value, threshold, window,
                 severity="warn", executor=None, t=None, message="",
                 seq=0):
        self.rule = rule
        self.state = state            # "firing" | "resolved"
        self.value = value
        self.threshold = threshold
        self.window = window
        self.severity = severity
        self.executor = executor
        self.t = time.time() if t is None else t
        self.message = message
        # monotonic per-engine transition id, stamped by SloEngine when
        # the transition enters history; cursor key for alerts_since()
        self.seq = seq

    def to_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return "Alert({0} {1}: value={2} vs {3})".format(
            self.rule, self.state, self.value, self.threshold
        )


#: Comparison ops an SLO objective may use; the RULE describes the
#: objective ("p99 < 0.5"), the alert fires on its violation.
_OPS = {
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
}

#: Stats a threshold rule may evaluate (validated at construction so a
#: typo'd rule fails at load time, not inside the standing loop).
_STATS = ("p50", "p90", "p99", "mean", "rate", "count", "gauge")


class SloRule(object):
    """One declarative SLO rule (docs/observability.md has the
    grammar).  Two kinds:

    - **threshold** (default): ``stat`` of ``metric`` over ``window``
      must satisfy ``op threshold`` — e.g.
      ``{"name": "serving-p99", "metric": "serving.request_latency_sec",
      "stat": "p99", "op": "<", "threshold": 0.5, "window": 30}``.
      ``stat`` ∈ p50/p90/p99 (histogram percentile), ``mean`` (exact),
      ``rate`` (counter/sec), ``count`` (counter increments), ``gauge``
      (latest value).
    - **burn_rate**: error-budget burn over a short AND a long window
      must both exceed ``burn_threshold`` — e.g.
      ``{"name": "serving-errors", "kind": "burn_rate",
      "bad": "serving.errors", "total": "serving.completed",
      "objective": 0.999, "short_window": 60, "long_window": 600,
      "burn_threshold": 2.0}`` (burn rate 1.0 = spending the budget
      exactly at the rate that exhausts it at the objective horizon).
      ``good`` may replace ``bad`` (bad = total − good).

    Hysteresis on both edges: ``for_count`` consecutive breaching
    evaluations before firing (default 1), ``clear_after`` consecutive
    clean ones before resolving (default 2).  ``per_executor: true``
    evaluates each executor's own series and names the worst offender.
    """

    def __init__(self, spec):
        spec = dict(spec)
        self.name = str(spec.pop("name"))
        self.kind = str(spec.pop("kind", "threshold"))
        self.severity = str(spec.pop("severity", "warn"))
        self.for_count = max(1, int(spec.pop("for_count", 1)))
        self.clear_after = max(1, int(spec.pop("clear_after", 2)))
        self.per_executor = bool(spec.pop("per_executor", False))
        if self.kind == "threshold":
            self.metric = str(spec.pop("metric"))
            self.stat = str(spec.pop("stat", "p99"))
            if self.stat not in _STATS:
                raise ValueError(
                    "rule {0!r}: unknown stat {1!r} (one of {2})".format(
                        self.name, self.stat, "/".join(_STATS)
                    )
                )
            self.op = str(spec.pop("op", "<"))
            if self.op not in _OPS:
                raise ValueError(
                    "rule {0!r}: unknown op {1!r}".format(self.name, self.op)
                )
            self.threshold = float(spec.pop("threshold"))
            self.window = float(spec.pop("window", 60))
        elif self.kind == "burn_rate":
            self.bad = spec.pop("bad", None)
            self.good = spec.pop("good", None)
            if not self.bad and not self.good:
                raise ValueError(
                    "burn_rate rule {0!r} needs 'bad' or 'good'".format(
                        self.name
                    )
                )
            self.total = str(spec.pop("total"))
            objective = float(spec.pop("objective"))
            if not 0.0 < objective < 1.0:
                raise ValueError(
                    "rule {0!r}: objective must be in (0, 1)".format(
                        self.name
                    )
                )
            self.budget = 1.0 - objective
            self.short_window = float(spec.pop("short_window", 60))
            self.long_window = float(spec.pop("long_window", 600))
            self.burn_threshold = float(spec.pop("burn_threshold", 2.0))
        else:
            raise ValueError(
                "rule {0!r}: unknown kind {1!r}".format(self.name, self.kind)
            )
        if spec:
            raise ValueError(
                "rule {0!r}: unknown keys {1}".format(
                    self.name, sorted(spec)
                )
            )

    # -- evaluation -----------------------------------------------------

    def _threshold_value(self, store, executor):
        if self.stat in ("p50", "p90", "p99"):
            return store.p99_over(
                self.metric, self.window, executor, q=int(self.stat[1:])
            )
        if self.stat == "mean":
            return store.mean_over(self.metric, self.window, executor)
        if self.stat == "rate":
            return store.rate(self.metric, self.window, executor)
        if self.stat == "count":
            return store.sum_over(self.metric, self.window, executor)
        if self.stat == "gauge":
            return store.gauge_last(self.metric, executor)
        raise ValueError(
            "rule {0!r}: unknown stat {1!r}".format(self.name, self.stat)
        )

    def _burn(self, store, window, executor):
        total = store.sum_over(self.total, window, executor)
        if total <= 0:
            return 0.0
        if self.bad:
            bad = store.sum_over(self.bad, window, executor)
        else:
            bad = total - store.sum_over(self.good, window, executor)
        return (bad / total) / self.budget

    def breach(self, store, executor=None):
        """``(breaching, value, threshold, window)`` for one evaluation
        of this rule against the store."""
        if self.kind == "threshold":
            v = self._threshold_value(store, executor)
            if v is None:
                return False, None, self.threshold, self.window
            return (
                not _OPS[self.op](v, self.threshold), v,
                self.threshold, self.window,
            )
        short = self._burn(store, self.short_window, executor)
        long_ = self._burn(store, self.long_window, executor)
        # multiwindow: BOTH must burn — the short window catches the
        # fast burn, the long window keeps a momentary blip from paging
        return (
            short > self.burn_threshold and long_ > self.burn_threshold,
            round(min(short, long_), 4), self.burn_threshold,
            self.long_window,
        )


def load_rules(source):
    """Normalize an SLO config into ``[SloRule]``.

    ``source`` may be: a list of rule dicts, a dict with a ``rules``
    key, a path to a ``.json`` file, or a path to a ``slo.yaml``
    written in the restricted grammar below (parsed without a yaml
    dependency — PyYAML is used when importable)::

        rules:
          - name: serving-p99
            metric: serving.request_latency_sec
            stat: p99
            op: "<"
            threshold: 0.5
            window: 30
          - name: serving-errors
            kind: burn_rate
            bad: serving.errors
            total: serving.completed
            objective: 0.999

    (one ``rules:`` list of flat ``key: value`` mappings — scalars
    only, strings optionally quoted).
    """
    if isinstance(source, (list, tuple)):
        specs = list(source)
    elif isinstance(source, dict):
        specs = list(source.get("rules", []))
    else:
        path = os.fspath(source)
        with open(path) as f:
            text = f.read()
        if path.endswith(".json"):
            data = json.loads(text)
        else:
            data = _parse_restricted_yaml(text)
        return load_rules(data)
    return [r if isinstance(r, SloRule) else SloRule(r) for r in specs]


def _parse_restricted_yaml(text):
    """Parse the restricted ``slo.yaml`` grammar (see
    :func:`load_rules`).  Prefers a real yaml parser when one is
    importable; otherwise :func:`_parse_restricted_yaml_fallback`."""
    try:
        import yaml  # noqa: PLC0415 - optional dependency

        return yaml.safe_load(text)
    except ImportError:
        return _parse_restricted_yaml_fallback(text)


def _parse_restricted_yaml_fallback(text):
    """The no-dependency parser: exactly one top-level key whose value
    is a list of flat scalar mappings (directly unit-tested so the
    grammar holds on PyYAML-less deployments too)."""
    out = {}
    key, items, cur = None, None, None
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].rstrip()
        if not line.strip():
            continue
        if not raw.startswith((" ", "\t")) and line.endswith(":"):
            key = line[:-1].strip()
            items = out[key] = []
            cur = None
            continue
        stripped = line.strip()
        if stripped.startswith("- "):
            if items is None:
                raise ValueError(
                    "slo.yaml: list item before any top-level key"
                )
            cur = {}
            items.append(cur)
            stripped = stripped[2:].strip()
            if not stripped:
                continue
        if ":" not in stripped or cur is None:
            raise ValueError(
                "slo.yaml: cannot parse line {0!r} (restricted "
                "grammar: one top-level list of flat 'key: value' "
                "mappings)".format(raw)
            )
        k, v = stripped.split(":", 1)
        cur[k.strip()] = _yaml_scalar(v.strip())
    return out


def _yaml_scalar(v):
    if v.startswith(("'", '"')) and v.endswith(v[0]) and len(v) >= 2:
        return v[1:-1]
    low = v.lower()
    if low in ("true", "yes"):
        return True
    if low in ("false", "no"):
        return False
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v


class SloEngine(object):
    """Evaluates rules against a :class:`TimeSeriesStore`, tracking
    per-rule firing state with hysteresis; transitions emit
    :class:`Alert` records, registry counters, and tracer marks (see
    module docstring)."""

    MAX_HISTORY = 200

    def __init__(self, store, rules, registry=None, tracer=None):
        self.store = store
        self.rules = load_rules(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError("duplicate SLO rule names: %s" % names)
        from tensorflowonspark_tpu import telemetry as _t

        self._registry = registry or _t.get_registry()
        self._tracer = tracer or _t.get_tracer()
        self._m_fired = self._registry.counter("health.alerts_fired")
        self._m_resolved = self._registry.counter("health.alerts_resolved")
        self._m_active = self._registry.gauge("health.alerts_active")
        self._state = {
            r.name: {"firing": False, "breaches": 0, "clean": 0,
                     "executor": None}
            for r in self.rules
        }
        self.history = collections.deque(maxlen=self.MAX_HISTORY)
        # monotonic transition counter; every Alert appended to history
        # carries the next value so cursor readers (alerts_since) can
        # detect both new transitions AND ones that aged out of the
        # bounded deque between polls.
        self._alert_seq = 0

    def _stamp(self, alert):
        self._alert_seq += 1
        alert.seq = self._alert_seq
        return alert

    def _evaluate_rule(self, rule):
        """Worst-case breach across the rule's scope (fleet, or each
        executor when ``per_executor``)."""
        if not rule.per_executor:
            return rule.breach(self.store) + (None,)
        worst = (False, None, None, None, None)
        for eid in self.store.executors():
            b, v, th, w = rule.breach(self.store, executor=eid)
            if b and (not worst[0] or (v or 0) > (worst[1] or 0)):
                worst = (b, v, th, w, eid)
            elif not worst[0] and worst[1] is None:
                worst = (False, v, th, w, eid)
        return worst

    def evaluate(self):
        """One evaluation round; returns the list of alert
        *transitions* (new firings + resolutions) this round."""
        transitions = []
        for rule in self.rules:
            st = self._state[rule.name]
            breaching, value, threshold, window, executor = (
                self._evaluate_rule(rule)
            )
            if breaching:
                st["breaches"] += 1
                st["clean"] = 0
                st["executor"] = executor
                if not st["firing"] and st["breaches"] >= rule.for_count:
                    st["firing"] = True
                    a = Alert(
                        rule.name, "firing", value, threshold, window,
                        severity=rule.severity, executor=executor,
                        message="{0}: {1} breached (value {2} vs {3} "
                        "over {4:.0f}s)".format(
                            rule.name, rule.kind, value, threshold,
                            window or 0,
                        ),
                    )
                    transitions.append(a)
                    self.history.append(self._stamp(a))
                    self._m_fired.inc()
                    # the mark's severity IS the rule's severity — a
                    # page-severity firing is a flight-recorder dump
                    # trigger (telemetry/blackbox.py)
                    self._tracer.mark(
                        "alert_firing", trace="slo",
                        severity=(
                            rule.severity
                            if rule.severity in ("warn", "page")
                            else "warn"
                        ),
                        rule=rule.name, value=value, threshold=threshold,
                        executor=executor,
                    )
                    logger.warning("SLO alert firing: %s", a.message)
            else:
                st["breaches"] = 0
                if st["firing"]:
                    st["clean"] += 1
                    if st["clean"] >= rule.clear_after:
                        st["firing"] = False
                        st["clean"] = 0
                        a = Alert(
                            rule.name, "resolved", value, threshold,
                            window, severity=rule.severity,
                            executor=st["executor"],
                            message="%s: recovered" % rule.name,
                        )
                        transitions.append(a)
                        self.history.append(self._stamp(a))
                        self._m_resolved.inc()
                        self._tracer.mark(
                            "alert_resolved", trace="slo", rule=rule.name,
                        )
                        logger.info("SLO alert resolved: %s", rule.name)
        self._m_active.set(
            sum(1 for s in self._state.values() if s["firing"])
        )
        return transitions

    def active(self):
        """Currently-firing alerts as plain dicts (``/status`` rides
        this)."""
        by_name = {r.name: r for r in self.rules}
        return [
            {"rule": name, "severity": by_name[name].severity,
             "executor": s["executor"]}
            for name, s in sorted(self._state.items())
            if s["firing"]
        ]

    def alert_history(self, limit=50):
        """The bounded alert HISTORY (ISSUE 11 satellite): every
        fired/resolved transition with its timestamp, newest last — so
        an operator can see what paged during a window that already
        cleared.  Rides ``/status`` (``alert_history``) and
        ``TPUCluster.metrics()["fleet"]["alert_history"]``."""
        out = [a.to_dict() for a in self.history]
        if limit is not None:
            out = out[-int(limit):]
        return out

    @property
    def last_alert_seq(self):
        """Seq of the newest transition ever stamped (0 before the
        first) — NOT the oldest one still in the bounded history."""
        return self._alert_seq

    def alerts_since(self, seq):
        """Cursor read over alert transitions (mirrors the journal's
        shipping cursors): every :class:`Alert` whose ``seq`` is
        strictly greater than ``seq``, oldest first.

        ``alert_history`` is a bounded deque, so a subscriber attaching
        late or polling slowly can miss a fired→resolved edge entirely
        if it diff's the rendered history.  A cursor makes the gap
        *detectable*: if the first returned alert's seq is not
        ``seq + 1`` (or, on an empty result, ``last_alert_seq > seq``),
        transitions aged out before the caller saw them and it should
        resync from :meth:`active` rather than assume continuity.  The
        remediation policy engine polls through this API."""
        seq = int(seq)
        return [a for a in list(self.history) if a.seq > seq]


# ----------------------------------------------------------------------
# straggler / anomaly auto-diagnosis
# ----------------------------------------------------------------------

#: Phase taxonomy (PR 7 spans → their histogram twins) the detector
#: attributes a straggler to.  ``host`` is the residual: step time not
#: explained by any instrumented phase.
PHASE_METRICS = (
    ("feed", "train.feed_wait_sec"),
    ("h2d", "train.h2d_sec"),
    ("dispatch", "train.dispatch_sec"),
    ("wire", "ps.round_trip_sec"),
)


def _median(values):
    vals = sorted(values)
    n = len(vals)
    if not n:
        return None
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


class StragglerDetector(object):
    """Names the slow executor and its dominant phase.

    Outlier rule over the windowed per-executor mean of ``metric``
    (default ``train.step_sec`` + the ``feed`` phase, since a stalled
    feed shows up in ``feed_wait`` rather than step time):

    - **MAD gate** (fleets of ≥4): flag executors whose mean exceeds
      ``fleet median + mad_k × 1.4826 × MAD``;
    - **ratio gate** (always, and the only gate for 2–3 node fleets
      where MAD degenerates): flag executors whose mean exceeds
      ``ratio × median of the OTHER executors`` (leave-one-out, so the
      straggler can't drag the baseline toward itself).

    An executor needs ``min_samples`` observations in the window to be
    judged (quiet nodes are a liveness question, not a straggler one).
    Attribution: the phase with the largest per-step excess over the
    peer median; if no instrumented phase explains at least
    ``phase_floor`` of the step-time excess, the phase is ``host``.
    """

    def __init__(self, store, window=60.0, mad_k=3.5, ratio=2.0,
                 min_samples=3, phase_floor=0.3):
        self.store = store
        self.window = float(window)
        self.mad_k = float(mad_k)
        self.ratio = float(ratio)
        self.min_samples = int(min_samples)
        self.phase_floor = float(phase_floor)

    def _per_executor_means(self, metric):
        out = {}
        for eid in self.store.executors():
            h = self.store.hist_over(metric, self.window, eid)
            if h.get("count", 0) >= self.min_samples:
                out[eid] = h["sum"] / h["count"]
        return out

    def _outliers(self, means):
        if len(means) < 2:
            return {}
        flagged = {}
        values = list(means.values())
        med = _median(values)
        mad = _median([abs(v - med) for v in values]) or 0.0
        mad_gate = med + self.mad_k * 1.4826 * mad
        for eid, v in means.items():
            peers = [m for e, m in means.items() if e != eid]
            peer_med = _median(peers)
            if peer_med is None or peer_med <= 0:
                continue
            if v > self.ratio * peer_med and (
                len(means) < 4 or v > mad_gate
            ):
                flagged[eid] = {
                    "value": v, "peer_median": peer_med,
                    "excess": v - peer_med,
                }
            # an executor *behind in wall-clock* but with a normal mean
            # is a liveness/feed question — not flagged here
        return flagged

    def _dominant_phase(self, eid, means_by_phase, step_excess):
        """The phase whose per-step excess over the peer median is
        largest; ``host`` when no phase explains the step excess."""
        best, best_excess = None, 0.0
        for phase, _metric in PHASE_METRICS:
            means = means_by_phase.get(phase) or {}
            if eid not in means or len(means) < 2:
                continue
            peers = [m for e, m in means.items() if e != eid]
            peer_med = _median(peers) or 0.0
            excess = means[eid] - peer_med
            if excess > best_excess:
                best, best_excess = phase, excess
        if best is None:
            return "host", 0.0
        # feed stalls live OUTSIDE step time, so a feed excess stands
        # on its own; device/host phases must explain the step excess
        if best != "feed" and step_excess > 0 and (
            best_excess < self.phase_floor * step_excess
        ):
            return "host", best_excess
        return best, best_excess

    def diagnose(self):
        """One detection round → ``[straggler dict]`` (empty when the
        fleet is even).  Each dict names the executor, the dominant
        phase, and the measured excess."""
        step_means = self._per_executor_means("train.step_sec")
        feed_means = self._per_executor_means("train.feed_wait_sec")
        # an executor can be step-normal but feed-starved: judge the
        # sum of both as its per-step wall contribution
        combined = {}
        for eid in set(step_means) | set(feed_means):
            combined[eid] = (
                step_means.get(eid, 0.0) + feed_means.get(eid, 0.0)
            )
        flagged = self._outliers(combined)
        if not flagged:
            return []
        means_by_phase = {
            phase: self._per_executor_means(metric)
            for phase, metric in PHASE_METRICS
        }
        out = []
        for eid, info in sorted(flagged.items()):
            step_excess = info["excess"]
            phase, phase_excess = self._dominant_phase(
                eid, means_by_phase, step_excess
            )
            out.append({
                "executor": eid,
                "phase": phase,
                "step_sec": round(info["value"], 6),
                "fleet_median_sec": round(info["peer_median"], 6),
                "excess_sec": round(step_excess, 6),
                "phase_excess_sec": round(phase_excess, 6),
                "window": self.window,
            })
        return out


class CleanRoundsSensor(object):
    """Quality gate over the health plane: ready after N CONSECUTIVE
    clean health rounds (no straggler hints, no firing SLO alerts) —
    not after a timer (ROADMAP 3 residual: "re-admission should be
    quality-gated").

    A *round* is one plane scrape (keyed off ``plane.store.scrapes``,
    which only advances when the scrape loop appends frames), so
    callers may :meth:`poll` as often as they like — polls between
    scrapes fold into the same round, and the streak advances at most
    once per round.  Any dirty round resets the streak to zero.

    Consumers: the fleet router's ``readmit_gate`` (a slow replica
    with enough clean probe rounds still waits for the plane) and
    ``ClusterActuators``' elastic ``release_gate`` (``elastic_grow``
    refuses while the fleet is unhealthy); both journal
    ``readmit_gated`` / ``readmit_cleared`` transitions.
    """

    def __init__(self, plane, rounds=3):
        self.plane = plane
        self.rounds = max(1, int(rounds))
        self.streak = 0
        self._last_round = None

    def dirty(self):
        """Is the CURRENT plane state unhealthy?  (straggler hints or
        firing SLO alerts — the same signals ``/status`` surfaces)"""
        if getattr(self.plane, "hints", None):
            return True
        slo = getattr(self.plane, "slo", None)
        if slo is not None and slo.active():
            return True
        return False

    def poll(self):
        """Score the current health round; returns :meth:`ready`.
        Idempotent within a round; a dirty observation resets the
        streak even mid-round (unhealth must never be smoothed
        away)."""
        round_id = getattr(
            getattr(self.plane, "store", None), "scrapes", None
        )
        if self.dirty():
            self.streak = 0
            self._last_round = round_id
            return False
        if round_id is None or round_id != self._last_round:
            self.streak += 1
            self._last_round = round_id
        return self.ready()

    def ready(self):
        return self.streak >= self.rounds

    def reset(self):
        self.streak = 0
        self._last_round = None


# ----------------------------------------------------------------------
# /status providers (serving engine, hier-PS DCN link, ...)
# ----------------------------------------------------------------------

_STATUS_PROVIDERS = {}
_STATUS_LOCK = threading.Lock()


def register_status_provider(name, fn):
    """Register a zero-arg callable whose small dict rides the
    ``/status`` summary under ``name`` (latest registration wins — a
    new ServingEngine replaces its predecessor's entry).  A raising
    provider is reported as ``{"error": ...}``, never propagated."""
    with _STATUS_LOCK:
        _STATUS_PROVIDERS[str(name)] = fn


def unregister_status_provider(name):
    with _STATUS_LOCK:
        _STATUS_PROVIDERS.pop(str(name), None)


def provider_statuses():
    with _STATUS_LOCK:
        providers = list(_STATUS_PROVIDERS.items())
    out = {}
    for name, fn in providers:
        try:
            out[name] = fn()
        except Exception as e:  # noqa: BLE001 - status is best effort
            out[name] = {"error": str(e)}
    return out


# ----------------------------------------------------------------------
# the standing health plane
# ----------------------------------------------------------------------


class HealthPlane(object):
    """Driver-side scrape → store → SLO → straggler loop.

    Args:
      metrics_fn: zero-arg callable returning the per-executor view —
        ``{eid: {"metrics": snapshot, "heartbeat_age": ..., ...}}``
        (exactly ``ClusterMonitor.metrics()``; :meth:`local` wraps a
        single process's own registry for serving-only deployments).
      interval: seconds between scrapes (default
        :data:`SCRAPE_INTERVAL`).
      window: ring-buffer query horizon (default
        :data:`DEFAULT_WINDOW`).
      slo: SLO rule config (anything :func:`load_rules` accepts), or
        None for no rules.
      straggler: enable the :class:`StragglerDetector` (kwargs via
        ``straggler_opts``).
      on_straggler: ``fn(hint_dict)`` called ONCE per (executor, phase)
        flag — the profiler trigger (``TPUCluster.start_health_plane``
        wires it to the flagged node's ``profile_request`` kv).  The
        dedup clears when the executor recovers (see
        ``straggler_clear_rounds``), so a regression that recurs after
        a recovery fires the hook again.
      on_straggler_cleared: ``fn(executor_id)`` called when a
        previously-flagged executor has been absent from
        ``straggler_clear_rounds`` consecutive diagnosis rounds — the
        recovery mirror of ``on_straggler``
        (``TPUCluster.start_health_plane`` wires it to clear the
        node's ``health_hint`` kv so its ``health.straggler`` gauge
        drops).
      straggler_clear_rounds: consecutive clean diagnosis rounds
        before a straggler hint expires from ``/status`` and the
        (executor, phase) dedup resets.
      liveness_fn: zero-arg callable returning the liveness health
        summary (``reservation.Liveness.health()``); feeds
        ``/healthz``.
      max_snapshot_age: scraped snapshots older than this (the
        ``metrics_age`` field — executor stopped publishing) are
        SKIPPED instead of re-appended, so a dead node's last frame is
        never double-counted into rates.
      merge_own_registry: append this (driver) process's own registry
        snapshot to :meth:`merged_snapshot`.  :meth:`local` turns this
        OFF when the scraped registry IS the plane's registry —
        otherwise every local-mode metric would be exposed doubled.
    """

    def __init__(self, metrics_fn, interval=None, window=None, slo=None,
                 straggler=True, straggler_opts=None, on_straggler=None,
                 on_straggler_cleared=None, straggler_clear_rounds=5,
                 liveness_fn=None, max_snapshot_age=None, registry=None,
                 merge_own_registry=True, journal_fn=None):
        self.metrics_fn = metrics_fn
        #: zero-arg callable backing the ``/journal`` route: the fleet
        #: event record (``TPUCluster.start_health_plane`` wires the
        #: reservation server's EventStore; default = this process's
        #: own journal — the local/serving-only shape)
        self.journal_fn = journal_fn
        self.interval = SCRAPE_INTERVAL if interval is None else float(
            interval
        )
        self.store = TimeSeriesStore(window=window)
        self.slo = (
            SloEngine(self.store, slo, registry=registry)
            if slo else None
        )
        self.detector = (
            StragglerDetector(self.store, **(straggler_opts or {}))
            if straggler else None
        )
        self.on_straggler = on_straggler
        self.on_straggler_cleared = on_straggler_cleared
        self.straggler_clear_rounds = max(1, int(straggler_clear_rounds))
        self.merge_own_registry = bool(merge_own_registry)
        self.liveness_fn = liveness_fn
        self.max_snapshot_age = (
            3 * self.interval if max_snapshot_age is None
            else float(max_snapshot_age)
        )
        from tensorflowonspark_tpu import telemetry as _t

        self._registry = registry or _t.get_registry()
        self._tracer = _t.get_tracer()
        self._m_scrapes = self._registry.counter("health.scrapes")
        self._m_flagged = self._registry.counter(
            "health.stragglers_flagged"
        )
        self._m_cleared = self._registry.counter(
            "health.stragglers_cleared"
        )
        #: executor → newest straggler hint (also pushed to
        #: ``on_straggler`` and visible in ``/status``); expires after
        #: ``straggler_clear_rounds`` clean diagnosis rounds
        self.hints = {}
        self._hinted = set()  # (executor, phase) already actioned
        self._clean_rounds = {}  # executor → consecutive unflagged rounds
        #: push subscribers (``fn(alert)`` per SLO transition, called
        #: from the scrape thread); the remediation engine prefers the
        #: pull-side ``slo.alerts_since`` cursor, but push consumers
        #: (bench recorders, paging bridges) hook here
        self._alert_listeners = []
        self.started_at = time.time()
        self._stop = threading.Event()
        self._thread = None
        self._exposition = None
        # arm the process-wide flight recorder: the executor_dead /
        # page-alert marks this plane emits are dump triggers
        # (telemetry/blackbox.py; None when disabled)
        from tensorflowonspark_tpu.telemetry import blackbox as _blackbox

        _blackbox.install()

    @classmethod
    def local(cls, registry=None, **kwargs):
        """A single-process plane scraping this process's own registry
        as executor 0 — the serving-only / bench deployment shape.
        The plane's own counters live in the scraped registry, which
        is therefore NOT re-appended by :meth:`merged_snapshot`
        (otherwise every metric on ``/metrics`` would read doubled)."""
        from tensorflowonspark_tpu import telemetry as _t

        reg = registry or _t.get_registry()

        def metrics_fn():
            return {0: {"metrics": reg.snapshot(), "metrics_age": 0.0}}

        kwargs.setdefault("merge_own_registry", False)
        return cls(metrics_fn, registry=reg, **kwargs)

    @classmethod
    def for_reservation_server(cls, server, **kwargs):
        """A plane scraping a bare
        :class:`~tensorflowonspark_tpu.cluster.reservation.Server`
        directly (no cluster handle) — lets the rendezvous process
        itself expose ``/metrics``/``/healthz`` when the driver isn't
        running the full :class:`~tensorflowonspark_tpu.cluster.
        cluster.TPUCluster` plane."""

        def metrics_fn():
            per = {}
            for eid_s, rec in server.metrics.snapshot().items():
                per[int(eid_s)] = {
                    "metrics": rec["metrics"], "metrics_age": rec["age"],
                }
            return per

        kwargs.setdefault("liveness_fn", server.liveness.health)
        return cls(metrics_fn, **kwargs)

    # -- one scrape round ----------------------------------------------

    def scrape_once(self):
        """Pull → append → evaluate → diagnose.  Returns the list of
        SLO transitions this round.  Never raises: the health plane
        must observe failures, not cause them."""
        try:
            per = self.metrics_fn() or {}
        except Exception:  # noqa: BLE001 - source mid-teardown
            logger.warning("health scrape failed", exc_info=True)
            return []
        for eid, rec in per.items():
            if not isinstance(rec, dict):
                continue
            snap = rec.get("metrics")
            age = rec.get("metrics_age", 0.0) or 0.0
            if snap is None or age > self.max_snapshot_age:
                continue
            try:
                self.store.append(eid, snap)
            except Exception:  # noqa: BLE001 - one bad snapshot must
                logger.warning(  # not stall the whole scrape
                    "health: unappendable snapshot from executor %s",
                    eid, exc_info=True,
                )
        self._m_scrapes.inc()
        transitions = []
        if self.slo is not None:
            try:
                transitions = self.slo.evaluate()
            except Exception:  # noqa: BLE001 - one bad rule must not
                logger.warning(  # kill the standing loop
                    "SLO evaluation failed", exc_info=True
                )
        if self.detector is not None:
            try:
                self._diagnose()
            except Exception:  # noqa: BLE001 - diagnosis is advisory
                logger.warning("straggler diagnosis failed", exc_info=True)
        for a in transitions:
            for fn in list(self._alert_listeners):
                try:
                    fn(a)
                except Exception:  # noqa: BLE001 - a bad subscriber
                    logger.warning(  # must not stall the scrape loop
                        "alert listener failed", exc_info=True
                    )
        return transitions

    def add_alert_listener(self, fn):
        """Subscribe ``fn(alert)`` to every SLO transition (firing and
        resolved), called inline from the scrape thread — keep it
        cheap and never raise.  For at-most-once edge delivery with
        gap detection use ``plane.slo.alerts_since(seq)`` instead."""
        self._alert_listeners.append(fn)
        return fn

    def _diagnose(self):
        try:
            stragglers = self.detector.diagnose()
        except Exception:  # noqa: BLE001 - diagnosis is advisory
            logger.warning("straggler diagnosis failed", exc_info=True)
            return
        self._expire_hints({h["executor"] for h in stragglers})
        for hint in stragglers:
            eid = hint["executor"]
            self.hints[eid] = hint
            key = (eid, hint["phase"])
            if key in self._hinted:
                continue
            self._hinted.add(key)
            self._m_flagged.inc()
            self._tracer.mark(
                "straggler_flagged", trace="health", severity="warn",
                executor=eid, phase=hint["phase"],
                excess_sec=hint["excess_sec"],
            )
            logger.warning(
                "straggler: executor %d is %.1fx the fleet (%.3fs vs "
                "%.3fs per step), dominant phase %r — firing the "
                "profiler hook",
                eid, hint["step_sec"] / max(hint["fleet_median_sec"], 1e-9),
                hint["step_sec"], hint["fleet_median_sec"], hint["phase"],
            )
            if self.on_straggler is not None:
                try:
                    self.on_straggler(hint)
                except Exception:  # noqa: BLE001 - the hint stands even
                    logger.warning(  # if the profiler trigger fails
                        "straggler hook failed for executor %d", eid,
                        exc_info=True,
                    )

    def _expire_hints(self, flagged):
        """Age out recovered stragglers: an executor absent from
        ``straggler_clear_rounds`` consecutive diagnosis rounds drops
        its hint from ``/status``, resets the (executor, phase) dedup
        (so a recurrence re-fires ``on_straggler``), and notifies
        ``on_straggler_cleared`` (the driver clears the node's
        ``health_hint`` kv so its ``health.straggler`` gauge drops)."""
        for eid in flagged:
            self._clean_rounds.pop(eid, None)
        for eid in [e for e in self.hints if e not in flagged]:
            clean = self._clean_rounds.get(eid, 0) + 1
            if clean < self.straggler_clear_rounds:
                self._clean_rounds[eid] = clean
                continue
            self._clean_rounds.pop(eid, None)
            self.hints.pop(eid, None)
            self._hinted = {k for k in self._hinted if k[0] != eid}
            self._m_cleared.inc()
            self._tracer.mark(
                "straggler_cleared", trace="health", executor=eid,
            )
            logger.info(
                "straggler: executor %d recovered (%d clean rounds) — "
                "clearing the flag", eid, clean,
            )
            if self.on_straggler_cleared is not None:
                try:
                    self.on_straggler_cleared(eid)
                except Exception:  # noqa: BLE001 - recovery is advisory
                    logger.warning(
                        "straggler-cleared hook failed for executor %d",
                        eid, exc_info=True,
                    )

    # -- consumption surfaces ------------------------------------------

    def merged_snapshot(self):
        """Fleet-merged view for ``/metrics``: every executor's newest
        raw snapshot plus this (driver) process's own registry — the
        scrape/SLO/alert counters live here.  When the plane's
        registry is itself one of the scraped sources
        (:meth:`local`), it is NOT re-appended: that would expose
        every metric doubled."""
        snaps = [
            rec for rec in self.store.latest_raw().values() if rec
        ]
        if self.merge_own_registry:
            snaps.append(self._registry.snapshot())
        return _aggregate.merge_snapshots(snaps)

    def healthz(self):
        """Liveness merged with the health plane's own state:
        unhealthy on any dead executor (heartbeat age past the
        deadline or an explicit compute-dead report) or a firing
        page-severity alert."""
        out = {"healthy": True, "reasons": []}
        if self.liveness_fn is not None:
            try:
                lv = self.liveness_fn() or {}
            except Exception as e:  # noqa: BLE001 - source down IS a
                lv = {"healthy": False,  # health signal
                      "dead": {"liveness": str(e)}}
            out["liveness"] = lv
            if not lv.get("healthy", True):
                out["healthy"] = False
                for eid, reason in (lv.get("dead") or {}).items():
                    out["reasons"].append(
                        "executor {0} dead: {1}".format(eid, reason)
                    )
        if self.slo is not None:
            pages = [
                a for a in self.slo.active() if a["severity"] == "page"
            ]
            if pages:
                out["healthy"] = False
                out["reasons"].extend(
                    "SLO page: %s" % a["rule"] for a in pages
                )
        return out

    def status(self):
        """Compact JSON fleet summary (``/status``)."""
        per = {}
        for eid in self.store.executors():
            per[str(eid)] = {
                "step_rate": round(
                    self.store.rate("train.steps", executor=eid), 3
                ),
                "step_p99_sec": round(
                    self.store.p99_over(
                        "train.step_sec", executor=eid
                    ), 6
                ),
            }
        out = {
            "uptime_sec": round(time.time() - self.started_at, 1),
            "scrapes": self.store.scrapes,
            "executors": per,
            "alerts": self.slo.active() if self.slo else [],
            # fired/resolved transitions, newest last (ISSUE 11
            # satellite): what paged even if it already cleared
            "alert_history": (
                self.slo.alert_history() if self.slo else []
            ),
            "stragglers": sorted(
                self.hints.values(), key=lambda h: h["executor"]
            ),
            "healthz": self.healthz(),
            # registered subsystem providers: serving engine, hier-PS
            # DCN link, cluster ledger, ...
            "providers": provider_statuses(),
        }
        return out

    def usage(self):
        """The ``/usage`` payload (ISSUE 14): the FLEET-wide per-tenant
        cost table, recovered from the merged scrape's
        ``usage.<field>.<tenant>`` mirror counters (every executor's
        ledger publishes them into its registry, the heartbeat
        piggyback ships them, the normal counter merge sums them —
        no second wire format), plus this process's own ledger detail
        (top-K heavy hitters with sketch error bounds, tracked row
        count)."""
        from tensorflowonspark_tpu.telemetry import ledger as _ledger_mod

        tenants = _ledger_mod.tenants_from_snapshot(
            self.merged_snapshot()
        )
        local = _ledger_mod.get_ledger().snapshot()
        if not tenants:
            # nothing scraped yet (or a bare plane with no mirror
            # counters): fall back to the local ledger's own table
            tenants = local.get("tenants", {})
        return {
            "tenants": tenants,
            "top": local.get("top", []),
            "requests_tracked": local.get("requests_tracked", 0),
            "rows_evicted": local.get("rows_evicted", 0),
            "tenants_folded": local.get("tenants_folded", 0),
        }

    def journal_events(self, limit=None):
        """The ``/journal`` payload: the fleet event record via
        ``journal_fn`` when wired, else this process's own journal."""
        if self.journal_fn is not None:
            out = self.journal_fn()
            if isinstance(out, dict):
                return out
            return {"events": out}
        from tensorflowonspark_tpu.telemetry import journal as _journal

        return {
            "events": [
                e.to_dict()
                for e in _journal.get_journal().events(limit=limit)
            ],
        }

    # -- lifecycle ------------------------------------------------------

    def _run(self):
        while not self._stop.wait(self.interval):
            self.scrape_once()

    def start(self):
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="health-plane"
        )
        self._thread.start()
        return self

    def serve(self, port=0, host="127.0.0.1"):
        """Start the HTTP exposition surface for this plane; returns
        the :class:`~tensorflowonspark_tpu.telemetry.exposition.
        ExpositionServer` (``.port`` is the bound port)."""
        from tensorflowonspark_tpu.telemetry import exposition

        self._exposition = exposition.ExpositionServer(
            self, port=port, host=host
        ).start()
        return self._exposition

    @property
    def exposition(self):
        return self._exposition

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)
        if self._exposition is not None:
            self._exposition.stop()
            self._exposition = None
