"""Structured span tracing with id propagation + Chrome-trace export.

A *span* is one timed region with a name, a trace id (the request /
step it belongs to), a span id, and the enclosing span's id — enough
to reconstruct the tree.  Two recording styles:

- ``with tracer.span("prefill", trace="req3", prefix_hit=True): ...``
  — context-managed, parent id propagated through a thread-local
  stack;
- ``tracer.add("decode_chunk", t0, dur, trace="req3", chunk=2)`` —
  post-hoc, for hot loops that time once and attribute the SAME
  interval to several traces (the serving engine labels one chunk
  dispatch onto every in-flight request's trace this way).

Export is Chrome-trace JSON (``{"traceEvents": [...]}``) loadable in
``chrome://tracing`` / Perfetto; ``ts``/``dur`` are microseconds since
the tracer's epoch.  On-demand *device* traces (XLA timelines) are the
:mod:`tensorflowonspark_tpu.tensorboard` profiler hook's job — this
module covers the host-side scheduling story those traces lack.

Disabled mode (``TFOS_TELEMETRY=0`` or ``set_enabled(False)``):
``span()`` returns a shared null context manager and ``add`` is a
no-op — nothing allocates, nothing is retained.
"""

import collections
import itertools
import json
import os
import threading
import time

from tensorflowonspark_tpu.telemetry import registry as _registry

#: Bounded span store per tracer: keeps the newest spans, drops the
#: oldest — a serving process must never grow without bound.
MAX_SPANS = int(os.environ.get("TFOS_TRACE_MAX_SPANS", "20000"))


class _NullSpan(object):
    """Shared no-op context manager for disabled tracing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, key, value):
        pass


_NULL_SPAN = _NullSpan()


class _SpanCtx(object):
    """Live span context: records on ``__exit__``."""

    __slots__ = ("_tracer", "name", "trace", "attrs", "_t0", "_id",
                 "_parent")

    def __init__(self, tracer, name, trace, attrs):
        self._tracer = tracer
        self.name = name
        self.trace = trace
        self.attrs = attrs

    def set(self, key, value):
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    def __enter__(self):
        tr = self._tracer
        stack = tr._stack()
        if self.trace is None and stack:
            self.trace = stack[-1][0]
        self._parent = stack[-1][1] if stack else None
        self._id = next(tr._ids)
        stack.append((self.trace, self._id))
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        tr = self._tracer
        stack = tr._stack()
        if stack:
            stack.pop()
        tr._record(
            self.name, self.trace, self._id, self._parent,
            self._t0, dur, self.attrs,
        )
        return False


class Tracer(object):
    """Bounded in-process span store (see module docstring)."""

    def __init__(self, enabled=None, max_spans=None, journal=None):
        self._enabled = (
            _registry._env_enabled() if enabled is None else bool(enabled)
        )
        self._spans = collections.deque(
            maxlen=max_spans if max_spans else MAX_SPANS
        )
        self._ids = itertools.count(1)
        self._local = threading.local()
        #: spans evicted by the bounded store (ISSUE 10 satellite):
        #: truncation must be *visible* — a trace missing its oldest
        #: spans without this counter reads as "nothing happened"
        self.dropped_spans = 0
        self._m_dropped = None
        #: perf_counter at construction — span timestamps are relative
        #: to this epoch (Chrome-trace ``ts`` microseconds)
        self.epoch = time.perf_counter()
        #: wall clock at the same instant: ``epoch_wall + span["t0"]``
        #: maps a span onto the journal/clock-sync wall timeline — what
        #: the forensics analyzer aligns cross-executor traces with
        self.epoch_wall = time.time()
        #: journal every mark() bridges into (ISSUE 11): None = the
        #: process-wide default, resolved lazily; pass an explicit
        #: EventJournal to isolate (tests)
        self._journal = journal
        #: Chrome-trace process label (merge_traces/export metadata);
        #: defaults to "pid<pid>"
        self.process_name = None

    # -- enable/disable -------------------------------------------------

    @property
    def enabled(self):
        return self._enabled

    def set_enabled(self, flag):
        self._enabled = bool(flag)

    # -- recording ------------------------------------------------------

    def _stack(self):
        s = getattr(self._local, "stack", None)
        if s is None:
            s = self._local.stack = []
        return s

    def span(self, name, trace=None, **attrs):
        """Context manager timing a region.  ``trace`` names the
        request/step the span belongs to (inherited from the enclosing
        span when omitted); extra kwargs become span attributes."""
        if not self._enabled:
            return _NULL_SPAN
        return _SpanCtx(self, name, trace, attrs or None)

    def add(self, name, t0, dur, trace=None, **attrs):
        """Record an already-measured interval (``t0`` from
        ``time.perf_counter()``)."""
        if not self._enabled:
            return
        self._record(
            name, trace, next(self._ids), None, t0, dur, attrs or None
        )

    def mark(self, name, trace=None, severity="info", attrs=None,
             **extra):
        """Record an instantaneous event (zero-duration span) — shed /
        watchdog / restart markers the chaos tests assert on.

        ISSUE 11: marks carry an explicit ``severity``
        (info/warn/page) and a structured attrs dict (``attrs`` merges
        with keyword extras), and every mark auto-bridges into the
        tracer's :class:`~tensorflowonspark_tpu.telemetry.journal.
        EventJournal` — the fault sites instrumented since PR 7 become
        typed journal events with no new call-site code.  The span
        record and Chrome export keep their old shape for existing
        consumers (severity rides along as one more field/arg)."""
        if not self._enabled:
            return
        merged = dict(attrs) if attrs else {}
        if extra:
            merged.update(extra)
        self._record(
            name, trace, next(self._ids), None, time.perf_counter(),
            0.0, merged or None, severity=severity,
        )
        j = self._journal
        if j is None:
            from tensorflowonspark_tpu.telemetry import journal as _journal

            j = _journal.get_journal()
        try:
            j.emit(
                name, severity=severity, trace=trace,
                attrs=merged or None,
            )
        except Exception:  # noqa: BLE001 - the mark already landed;
            pass  # journalling must never break the instrumented path

    def _record(self, name, trace, span_id, parent, t0, dur, attrs,
                severity=None):
        if len(self._spans) == self._spans.maxlen:
            # the deque is about to silently evict its oldest span —
            # count it into the registry so truncation shows up in
            # snapshot() / the fleet view (tracing.dropped_spans)
            self.dropped_spans += 1
            if self._m_dropped is None:
                self._m_dropped = _registry.get_registry().counter(
                    "tracing.dropped_spans"
                )
            self._m_dropped.inc()
        rec = {
            "name": name,
            "trace": trace,
            "id": span_id,
            "t0": t0 - self.epoch,
            "dur": dur,
            "tid": threading.get_ident(),
        }
        if parent is not None:
            rec["parent"] = parent
        if attrs:
            rec["attrs"] = attrs
        if severity is not None:
            rec["severity"] = severity
        self._spans.append(rec)

    # -- introspection / export -----------------------------------------

    def spans(self, name=None, trace=None):
        """Snapshot of recorded spans, optionally filtered."""
        out = list(self._spans)
        if name is not None:
            out = [s for s in out if s["name"] == name]
        if trace is not None:
            out = [s for s in out if s.get("trace") == trace]
        return out

    def count(self, name, trace=None):
        """Number of recorded spans matching the filter — the
        assertion primitive for MUST-NOT-FIRE contracts (e.g. the
        hierarchical PS plane's zero-``grad_readback`` invariant,
        tests/test_hier_ps.py) without materializing the span list."""
        return sum(
            1 for s in self._spans
            if s["name"] == name
            and (trace is None or s.get("trace") == trace)
        )

    def clear(self):
        self._spans.clear()

    def export_chrome(self, trace=None):
        """Chrome-trace / Perfetto JSON object.  Spans map to complete
        ('X') events; the trace id rides ``args.trace`` and the span
        tree rides ``args.parent``.  Also carries ``process_name`` /
        ``thread_name`` metadata ('M') events — appended AFTER the
        spans, so old consumers indexing ``traceEvents[0]`` still see
        the first span — keeping a merged multi-executor trace
        (:func:`merge_traces`) row-named.

        ``trace`` filters the export to ONE trace id — the shape the
        cost-attribution plane hands to :func:`merge_traces` to render
        a single request's fleet-wide story (ISSUE 14)."""
        pid = os.getpid()
        pname = self.process_name or "pid{0}".format(pid)
        events = []
        meta = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": pname},
        }]
        tids = []
        spans = list(self._spans)
        if trace is not None:
            spans = [s for s in spans if s.get("trace") == trace]
        for s in spans:
            if s["tid"] not in tids:
                tids.append(s["tid"])
                meta.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": s["tid"],
                    "args": {"name": "thread-{0}".format(s["tid"])},
                })
            args = dict(s.get("attrs") or {})
            if s.get("trace") is not None:
                args["trace"] = s["trace"]
            if s.get("parent") is not None:
                args["parent"] = s["parent"]
            if s.get("severity") is not None:
                args["severity"] = s["severity"]
            events.append({
                "name": s["name"],
                "ph": "X",
                "ts": round(s["t0"] * 1e6, 3),
                "dur": round(s["dur"] * 1e6, 3),
                "pid": pid,
                "tid": s["tid"],
                "args": args,
            })
        return {"traceEvents": events + meta, "displayTimeUnit": "ms"}

    def save(self, path):
        """Write the Chrome-trace JSON; returns ``path``."""
        with open(path, "w") as f:
            json.dump(self.export_chrome(), f)
        return path


def merge_traces(parts):
    """Merge per-executor Chrome traces into ONE Perfetto-loadable
    file, applying the estimated clock offsets (ISSUE 11 satellite).

    ``parts`` is a list of ``(trace, offset_sec, label)`` tuples (or
    dicts with ``trace``/``offset``/``label`` keys): ``trace`` is a
    Chrome-trace object (``{"traceEvents": [...]}``, as
    :meth:`Tracer.export_chrome` produces), ``offset_sec`` is the
    seconds to ADD to that executor's timestamps to land them on the
    reference (driver) clock (``ClockSync.offset`` — see
    cluster/reservation.py), and ``label`` names the merged trace's
    process row (overriding any ``process_name`` metadata).

    Colliding pids across parts are remapped (part index becomes the
    pid) so two executors that happen to share an OS pid never
    interleave rows.  Non-metadata events come back time-sorted —
    causally ordered across executors once the offsets are right."""
    events = []
    meta = []
    for i, part in enumerate(parts):
        if isinstance(part, dict):
            trace = part.get("trace") or {}
            offset = float(part.get("offset", 0.0) or 0.0)
            label = part.get("label")
        else:
            trace, offset = part[0], float(part[1] or 0.0)
            label = part[2] if len(part) > 2 else None
        shift_us = offset * 1e6
        named = False
        for ev in (trace or {}).get("traceEvents", []):
            ev = dict(ev, pid=i)
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    if label is not None:
                        ev["args"] = {"name": label}
                    named = True
                meta.append(ev)
                continue
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] + shift_us, 3)
            events.append(ev)
        if not named and label is not None:
            meta.append({
                "name": "process_name", "ph": "M", "pid": i, "tid": 0,
                "args": {"name": label},
            })
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


_GLOBAL = None
_GLOBAL_LOCK = threading.Lock()


def get_tracer():
    """The process-wide default tracer (same enable story as the
    default registry)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = Tracer()
    return _GLOBAL
