"""Cluster aggregation: node snapshots → one driver-side fleet view.

The wire path (docs/observability.md "Fleet aggregation"):

1. each compute process runs a :class:`NodePublisher` (started by
   ``cluster.node._compute_process_main``) that periodically writes
   the default registry's snapshot into the node manager's kv store
   (``mgr.set("metrics", snap)``);
2. the node :class:`~tensorflowonspark_tpu.cluster.supervisor.Supervisor`'s
   heartbeater reads that kv entry and piggybacks it on HEARTBEAT
   frames, stamped with supervisor-side fields (restarts, generation);
3. the reservation :class:`~tensorflowonspark_tpu.cluster.reservation.Server`
   stores the newest snapshot per executor and answers the ``METRICS``
   wire op with all of them;
4. ``TFCluster.metrics()`` (or a bare
   ``reservation.Client.get_metrics()``) pulls the per-executor
   snapshots and :func:`merge_snapshots` folds them into one fleet
   view — counters summed, histograms merged bucket-wise with
   percentiles recomputed, gauges kept per-executor.

Everything on the wire is the plain-dict snapshot format from
:mod:`~tensorflowonspark_tpu.telemetry.registry` — JSON all the way.
"""

import logging
import os
import threading

from tensorflowonspark_tpu.telemetry import registry as _registry

logger = logging.getLogger(__name__)

#: Seconds between node-side snapshot publications into the manager kv
#: (env-tunable: TFOS_TELEMETRY_PUBLISH_INTERVAL).
PUBLISH_INTERVAL = float(
    os.environ.get("TFOS_TELEMETRY_PUBLISH_INTERVAL", "2.0")
)


def merge_snapshots(snapshots):
    """Fold per-executor registry snapshots into ONE fleet snapshot.

    Counters sum; histograms merge bucket-wise (the fixed geometric
    bucket scheme makes this exact) with ``p50``/``p99`` recomputed
    over the merged counts; gauges take the max (a per-executor gauge
    summed across the fleet would be meaningless — the per-executor
    values stay available in the unmerged view).
    """
    counters = {}
    gauges = {}
    hists = {}  # name -> {"count","sum","buckets": {le: count}, min, max}
    for snap in snapshots:
        if not snap:
            continue
        for name, v in (snap.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + v
        for name, v in (snap.get("gauges") or {}).items():
            gauges[name] = max(gauges.get(name, v), v)
        for name, h in (snap.get("histograms") or {}).items():
            agg = hists.setdefault(
                name,
                {"count": 0, "sum": 0.0, "buckets": {},
                 "min": None, "max": None, "exemplars": {}},
            )
            agg["count"] += h.get("count", 0)
            agg["sum"] += h.get("sum", 0.0)
            for lo, hi, c in h.get("buckets", []):
                key = (lo, hi)
                agg["buckets"][key] = agg["buckets"].get(key, 0) + c
            for lo, hi, ex in h.get("exemplars", []) or []:
                # newest exemplar per bucket wins across executors —
                # the reference stays one concrete recent request
                key = (lo, hi)
                prev = agg["exemplars"].get(key)
                if prev is None or ex.get("ts", 0) > prev.get("ts", 0):
                    agg["exemplars"][key] = ex
            for k, pick in (("min", min), ("max", max)):
                v = h.get(k)
                if v is not None:
                    agg[k] = v if agg[k] is None else pick(agg[k], v)
    merged_h = {}
    for name, agg in hists.items():
        triples = sorted(
            ([lo, hi, c] for (lo, hi), c in agg["buckets"].items()),
            key=lambda t: t[0],
        )
        h = {
            # exact sum (registry.Histogram.snapshot carries the exact
            # running sum): the merged mean is sum/count, never
            # bucket-interpolated
            "count": agg["count"], "sum": agg["sum"],
            "min": agg["min"], "max": agg["max"], "buckets": triples,
        }
        if agg["exemplars"]:
            h["exemplars"] = sorted(
                ([lo, hi, ex] for (lo, hi), ex in agg["exemplars"].items()),
                key=lambda t: t[0],
            )
        h["p50"] = _registry.histogram_percentile(h, 50)
        h["p99"] = _registry.histogram_percentile(h, 99)
        if h["count"]:
            h["mean"] = h["sum"] / h["count"]
        merged_h[name] = h
    return {"counters": counters, "gauges": gauges,
            "histograms": merged_h}


def fleet_view(per_executor):
    """``{executor_id: {"metrics": snapshot, ...liveness fields}}`` →
    ``{"executors": <input>, "fleet": merged snapshot}`` — the shape
    ``TFCluster.metrics()`` returns."""
    return {
        "executors": per_executor,
        "fleet": merge_snapshots(
            rec.get("metrics") for rec in per_executor.values()
        ),
    }


class NodePublisher(object):
    """Background thread shipping the default registry's snapshot into
    the node manager kv every ``interval`` seconds (step 1 of the
    module-docstring pipeline).  Publication is best-effort: a manager
    hiccup is logged once and retried next interval — telemetry must
    never take a node down.

    The same loop is the compute-side pickup of the health plane's
    **auto-profiler trigger** (ISSUE 10): when the driver's straggler
    diagnosis flags this node, it writes a sequenced
    ``profile_request`` into the manager kv; the publisher sees it on
    its next pass, starts the PR 7 ``tensorboard.start_profile``
    capture (graceful no-op on builds without the profiler), and acks
    into ``profile_state`` so the driver/tests can assert the capture
    was triggered on the flagged node only."""

    KV_KEY = "metrics"
    KV_JOURNAL_KEY = "journal_events"
    PROFILE_REQ_KEY = "profile_request"
    PROFILE_STATE_KEY = "profile_state"

    #: Newest journal events kept in the kv (the supervisor ships by
    #: seq cursor, so this only has to cover a few publish intervals).
    JOURNAL_PUBLISH_MAX = 256

    def __init__(self, mgr, interval=None, registry=None, journal=None):
        self.mgr = mgr
        self.interval = PUBLISH_INTERVAL if interval is None else float(
            interval
        )
        self.registry = registry
        self.journal = journal
        self._stop = threading.Event()
        self._warned = False
        self._thread = None
        self._profile_seq = 0
        self._journal_seq = 0

    def _snapshot(self):
        reg = self.registry or _registry.get_registry()
        return reg.snapshot()

    def publish_once(self):
        """One synchronous publication (also called at loop exit so the
        final state of a finished compute process is visible)."""
        try:
            self.mgr.set(self.KV_KEY, self._snapshot())
        except Exception as e:  # noqa: BLE001 - observability best effort
            if not self._warned:
                self._warned = True
                logger.warning(
                    "telemetry publication to the node manager failed "
                    "(%s); will keep retrying quietly", e,
                )
            return False
        self.publish_journal()
        return True

    def publish_journal(self):
        """Mirror this process's newest journal events into the node
        kv (``journal_events``) — the compute half of the fleet
        journal's heartbeat piggyback (ISSUE 11).  The kv holds one
        cumulative window tagged with this pid; the supervisor ships
        events whose seq is beyond its cursor (a restarted process's
        fresh pid resets that cursor), so a publisher/reader race can
        only re-send, never lose — and the server-side EventStore
        dedups re-sends by (pid, seq)."""
        from tensorflowonspark_tpu.telemetry import journal as _journal

        j = self.journal or _journal.get_journal()
        evs = j.tail(self.JOURNAL_PUBLISH_MAX)
        newest = evs[-1].seq if evs else 0
        if newest <= self._journal_seq:
            return False
        try:
            self.mgr.set(self.KV_JOURNAL_KEY, {
                "pid": os.getpid(),
                "events": [e.to_dict() for e in evs],
            })
        except Exception:  # noqa: BLE001 - observability best effort
            return False
        self._journal_seq = newest
        return True

    def check_profile_request(self):
        """Start a profiler capture when the driver requested one via
        the ``profile_request`` kv (sequenced — each request fires
        once, surviving publisher restarts through the persisted
        ``profile_state`` ack).  Returns the ack dict when a capture
        was triggered this call, else None."""
        try:
            req = self.mgr.get(self.PROFILE_REQ_KEY)
            if hasattr(req, "_getvalue"):
                req = req._getvalue()
        except Exception:  # noqa: BLE001 - kv may not exist / mgr down
            return None
        if not isinstance(req, dict) or not req.get("seq"):
            return None
        seq = int(req["seq"])
        if seq <= self._profile_seq:
            return None
        if self._profile_seq == 0:
            # fresh publisher (process restart): consult the persisted
            # ack so an already-served request doesn't re-fire
            try:
                prev = self.mgr.get(self.PROFILE_STATE_KEY)
                if hasattr(prev, "_getvalue"):
                    prev = prev._getvalue()
                if isinstance(prev, dict) and int(
                    prev.get("seq", 0)
                ) >= seq:
                    self._profile_seq = int(prev["seq"])
                    return None
            except Exception:  # noqa: BLE001 - no ack kv yet
                pass
        self._profile_seq = seq
        from tensorflowonspark_tpu import telemetry as _t
        from tensorflowonspark_tpu import tensorboard as _tb

        log_dir = req.get("log_dir") or "tfos_profile"
        sub = os.path.join(str(log_dir), str(os.getpid()))
        sess = _tb.start_profile(sub, req.get("steps"))
        state = {
            "seq": seq,
            "started": sess is not None,
            "log_dir": sub,
            "pid": os.getpid(),
        }
        try:
            self.mgr.set(self.PROFILE_STATE_KEY, state)
        except Exception:  # noqa: BLE001 - ack is observability
            logger.warning(
                "unable to ack profile request %d", seq, exc_info=True
            )
        reg = self.registry or _registry.get_registry()
        reg.counter("health.profile_captures").inc()
        _t.get_tracer().mark(
            "profile_capture", trace="health", seq=seq,
            started=state["started"], log_dir=sub,
        )
        logger.info(
            "health plane profile request %d: capture %s into %s",
            seq, "started" if state["started"] else "unavailable", sub,
        )
        return state

    def _run(self):
        while not self._stop.wait(self.interval):
            self.publish_once()
            self.check_profile_request()
        self.publish_once()

    def start(self):
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="telemetry-publisher"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)


def start_node_publisher(mgr, interval=None):
    """Start a :class:`NodePublisher` when telemetry is enabled;
    returns it (or None when disabled — zero threads, zero cost)."""
    if not _registry.get_registry().enabled:
        return None
    return NodePublisher(mgr, interval=interval).start()
