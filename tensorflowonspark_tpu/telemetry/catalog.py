"""The metric-name CATALOG: one table every metric name answers to.

Every counter/gauge/histogram the stack creates is re-typed as a
string literal at its call site — nothing stops a typo'd
``"serving.admited"`` from silently forking a new time series that no
SLO rule, dashboard, or doc row will ever find.  This module is the
contract registry that closes that hole (ISSUE 15):

- :data:`METRICS` is the exhaustive per-metric table — name, kind
  (counter/gauge/histogram), owning subsystem, one-line meaning.
- :data:`DYNAMIC_PREFIXES` names the families whose full names are
  minted at runtime (the per-tenant ``usage.*`` mirror counters).
- ``docs/observability.md``'s "Built-in metrics" table is GENERATED
  from this table (:func:`render_markdown`, between the
  ``metric-table:begin/end`` markers) and drift-tested in the CI lint
  lane (:func:`check_docs`) — the doc can never silently disagree
  with the catalog.
- The tfoslint rule **TFOS004** (``analysis/lint.py``) checks every
  literal metric name at a ``counter(...)``/``gauge(...)``/
  ``histogram(...)`` call site against this catalog, so a new metric
  must land here (and therefore in the docs) in the same diff that
  creates it.

The reserved serving-input columns live in
:mod:`tensorflowonspark_tpu.serving_engine` (``RESERVED_INPUTS``);
:data:`RESERVED_INPUT_COLUMNS` mirrors the *names* here so the linter
and the docs can read them without importing the jax-heavy serving
stack (equality of the two tuples is asserted in
``tests/test_analysis.py``).

CLI::

    python -m tensorflowonspark_tpu.telemetry.catalog --check docs/observability.md
    python -m tensorflowonspark_tpu.telemetry.catalog --write docs/observability.md
"""

import collections

#: The reserved request-row input columns, one constant each —
#: import-light twins of ``serving_engine.BUDGET_INPUT`` /
#: ``DEADLINE_INPUT`` / ``TENANT_INPUT`` / ``TRACE_INPUT`` for the
#: telemetry layer (which must never pull the jax-heavy serving
#: stack).  ``serving_engine.RESERVED_INPUTS`` re-exports exactly
#: :data:`RESERVED_INPUT_COLUMNS` (asserted in
#: tests/test_analysis.py).
BUDGET_COLUMN = "max_new"          # per-request token budget
DEADLINE_COLUMN = "deadline_sec"   # per-request deadline (seconds)
TENANT_COLUMN = "tenant"           # usage-ledger attribution key
TRACE_COLUMN = "trace_id"          # fleet-minted trace id

RESERVED_INPUT_COLUMNS = (
    BUDGET_COLUMN, DEADLINE_COLUMN, TENANT_COLUMN, TRACE_COLUMN,
)

Metric = collections.namedtuple("Metric", "name kind source desc")

_C, _G, _H = "counter", "gauge", "histogram"


def _m(kind, source, *pairs):
    return [Metric(name, kind, source, desc) for name, desc in pairs]


#: the exhaustive metric table, grouped by subsystem prefix
METRICS = tuple(
    # --- serving engine (serving_engine.py + static predict_rows) ---
    _m(_C, "ServingEngine",
       ("serving.admitted", "requests past admission validation"),
       ("serving.completed", "requests emitted with output tokens"),
       ("serving.errors", "typed per-request error records"),
       ("serving.shed", "requests shed by the admission policy"),
       ("serving.expired", "deadline cancellations"),
       ("serving.degraded", "budgets shrunk by the degrade policy"),
       ("serving.chunks", "decode chunks dispatched"),
       ("serving.watchdog_fires", "wedged chunk syncs abandoned"),
       ("serving.recovered", "requests re-admitted after a watchdog teardown"),
       ("serving.prefix_hit_admits", "admits served from the radix cache"),
       ("serving.swaps", "weight swaps installed"),
       ("serving.swap_commits", "probation windows closed clean"),
       ("serving.swap_rollbacks", "swaps rolled back inside the window"),
       ("serving.drained", "requests returned as typed drained records"))
    + _m(_H, "ServingEngine",
         ("serving.request_latency_sec",
          "submit→emit latency, BOTH schedules (the authoritative "
          "p50/p99 source; carries trace-id exemplars)"),
         ("serving.ttft_sec",
          "submit→first-token latency (the number the prefill/decode "
          "split bounds; trace-id exemplars)"),
         ("serving.queue_wait_sec", "admission-queue wait"))
    + _m(_G, "ServingEngine",
         ("serving.weight_generation", "live weight generation tag"))
    + _m(_C, "hot_swap.CheckpointWatcher",
         ("serving.checkpoints_quarantined",
          "serving exports rejected by the validation pipeline"))
    # --- fleet router (fleet/router.py) ---
    + _m(_C, "fleet.FleetRouter",
         ("fleet.dispatched", "requests handed to a replica"),
         ("fleet.redispatched", "in-flight work re-dispatched off a dead replica"),
         ("fleet.completed", "requests emitted fleet-wide"),
         ("fleet.shed", "fleet-level admission sheds (spill-before-shed)"),
         ("fleet.affinity_hits", "prefix-affinity dispatches that hit their replica"),
         ("fleet.replica_deaths", "replica worker deaths observed"),
         ("fleet.evictions", "slow replicas routed around"),
         ("fleet.readmissions", "probed replicas re-admitted"))
    + _m(_C, "fleet.FleetRouter remediation verbs",
         ("fleet.replicas_spawned", "replicas added by scale_up"),
         ("fleet.replicas_retired", "replicas drained away by scale_down"))
    + _m(_G, "fleet.FleetRouter",
         ("fleet.live_replicas", "replicas currently taking dispatch"))
    # --- radix prefix cache (prefix_cache.py) ---
    + _m(_C, "radix prefix cache",
         ("prefix_cache.hits", "cached-prefix admit hits"),
         ("prefix_cache.misses", "cold admits"),
         ("prefix_cache.tokens_saved", "prompt tokens not re-prefilled"),
         ("prefix_cache.evictions", "cold leaves evicted under the HBM budget"))
    + _m(_G, "radix prefix cache",
         ("prefix_cache.bytes_used", "device bytes held by committed blocks"))
    # --- training loop (parallel/dp.py) ---
    + _m(_C, "SyncTrainer.train_on_feed",
         ("train.steps", "optimizer steps taken"))
    + _m(_H, "SyncTrainer.train_on_feed",
         ("train.step_sec", "per-step wall time"),
         ("train.feed_wait_sec", "feed-starvation wait per step"),
         ("train.h2d_sec", "host→device transfer (straggler phase series)"),
         ("train.dispatch_sec", "step dispatch (straggler phase series)"))
    # --- parameter-server wire (parallel/ps.py) ---
    + _m(_C, "PSClient",
         ("ps.bytes_sent", "exact frame bytes onto the wire"),
         ("ps.bytes_recv", "exact frame bytes off the wire (delta replies)"),
         ("ps.round_trips", "push/pull round trips"))
    + _m(_H, "PSClient / AsyncTrainer drain",
         ("ps.round_trip_sec", "wire round-trip latency"),
         ("ps.grad_readback_sec", "device→host gradient readback"))
    # --- hierarchical PS (parallel/hier_ps.py) ---
    + _m(_C, "HierTrainer + DcnLink",
         ("hier.ici_steps", "on-device psum+apply steps"),
         ("hier.dcn_windows", "compressed delta windows pushed over DCN"),
         ("hier.dcn_dedup", "windows the exactly-once ledger dropped"),
         ("hier.leader_failovers", "pod-leader re-elections"))
    + _m(_G, "HierTrainer",
         ("hier.leader", "this member's leadership flag"))
    + _m(_H, "DcnLink",
         ("hier.dcn_readback_sec", "delta device→host readback"),
         ("hier.dcn_push_sec", "DCN push wall time"))
    # --- data plane (data/feed.py, data/shm_ring.py) ---
    + _m(_C, "DataFeed",
         ("feed.wire_bytes", "feed payload bytes (twin of wire_stats())"),
         ("feed.wire_records", "wire records decoded"),
         ("feed.wire_rows", "rows decoded"))
    + _m(_C, "ShmRing",
         ("ring.push_records", "records pushed into the shm ring"),
         ("ring.push_bytes", "bytes pushed into the shm ring"),
         ("ring.pop_records", "records popped off the shm ring"),
         ("ring.pop_bytes", "bytes popped off the shm ring"))
    # --- cluster lifecycle (cluster/supervisor.py, cluster/cluster.py) ---
    + _m(_C, "supervisor + driver monitor",
         ("cluster.restarts", "compute-process restarts (supervisor-side)"),
         ("cluster.restart_events", "restarts observed by the driver monitor"))
    + _m(_G, "supervisor heartbeat",
         ("cluster.generation", "rendezvous generation on the beat"))
    # --- health plane (telemetry/health.py) ---
    + _m(_C, "HealthPlane / SloEngine / StragglerDetector",
         ("health.scrapes", "scrape→store→evaluate rounds"),
         ("health.alerts_fired", "SLO alert fire transitions"),
         ("health.alerts_resolved", "SLO alert resolve transitions"),
         ("health.stragglers_flagged", "executors flagged as stragglers"),
         ("health.stragglers_cleared", "straggler hints expired clean"),
         ("health.profile_captures", "auto-triggered profile captures"))
    + _m(_G, "HealthPlane / supervisor beat",
         ("health.alerts_active", "currently-firing alerts"),
         ("health.straggler", "per-node straggler hint flag (beat-side)"))
    # --- telemetry substrate itself ---
    + _m(_C, "Tracer bounded store",
         ("tracing.dropped_spans", "spans evicted by the bounded ring"))
    + _m(_C, "EventJournal",
         ("journal.events", "typed events appended"),
         ("journal.dropped_events", "events evicted from a severity ring"))
    + _m(_C, "blackbox.FlightRecorder",
         ("blackbox.dumps", "dump bundles frozen to disk"),
         ("blackbox.dumps_suppressed", "triggers rate-limited away"))
    # --- lock-order sanitizer (analysis/locksan.py, ISSUE 15) ---
    + _m(_C, "analysis.locksan",
         ("locksan.locks", "instrumented locks created"),
         ("locksan.cycles", "potential-deadlock cycles reported"))
    # --- remediation engine (remediation/engine.py, ISSUE 16) ---
    + _m(_C, "remediation.RemediationEngine",
         ("remediation.decisions", "policy intents that reached the audit log"),
         ("remediation.actions_executed", "actuator verbs actually invoked"),
         ("remediation.actions_suppressed",
          "intents stopped by a cooldown or rate limit"),
         ("remediation.actions_deferred",
          "intents parked by the deploy-conflict rule"))
    + _m(_G, "remediation.RemediationEngine",
         ("remediation.budget_remaining",
          "global action budget left before hands-off"))
    # --- cost-model planner (planner/, ISSUE 18) ---
    + _m(_C, "planner.cost.calibrate",
         ("planner.calibrations", "calibration probe passes run"))
    + _m(_H, "planner.cost.calibrate",
         ("planner.calibration_sec", "micro-bench probe pass wall time"))
    + _m(_C, "planner.plan",
         ("planner.candidates", "lattice points priced by the cost model"),
         ("planner.pruned", "lattice points rejected by a legality validator"))
    + _m(_H, "planner.plan",
         ("planner.plan_sec", "enumerate+price+choose wall time"))
    + _m(_C, "planner.LivePlanner",
         ("planner.replans", "live re-plans applied through an actuator"),
         ("planner.replan_suppressed",
          "sustained triggers suppressed by a cooldown"))
    # --- live re-planner sensors (serving_engine.py, ISSUE 18) ---
    + _m(_H, "ServingEngine admission",
         ("serving.prompt_tokens",
          "admitted prompt length (the prompt-mix drift sensor)"))
    + _m(_G, "ServingEngine paged pool",
         ("serving.pool_pages", "physical page-pool size"),
         ("serving.pool_pages_used",
          "pages currently held (occupancy = used / size)"))
)

#: families whose full names are minted at runtime — a literal name
#: under one of these prefixes is catalog-clean without its own row
DYNAMIC_PREFIXES = {
    "usage.":
        "per-tenant usage-ledger mirror counters "
        "(``usage.<field>.<tenant>``, bounded tenant set — "
        "telemetry/ledger.py)",
}

#: full-name set for O(1) membership checks (the linter's view)
NAMES = frozenset(m.name for m in METRICS)

_BEGIN = "<!-- metric-table:begin (generated by telemetry/catalog.py — edit the catalog, not this table) -->"
_END = "<!-- metric-table:end -->"


def known(name):
    """True when ``name`` is catalog-clean: an exact row or a
    registered dynamic family."""
    return name in NAMES or any(
        name.startswith(p) for p in DYNAMIC_PREFIXES
    )


def duplicates():
    """Catalog self-check: names declared twice (tested empty)."""
    seen, dups = set(), []
    for m in METRICS:
        if m.name in seen:
            dups.append(m.name)
        seen.add(m.name)
    return dups


def render_markdown():
    """The generated "Built-in metrics" doc table (one row per
    metric, plus one per dynamic family), marker lines included."""
    lines = [_BEGIN, "| metric | kind | source | meaning |", "|---|---|---|---|"]
    for m in METRICS:
        lines.append("| `%s` | %s | %s | %s |" % (m.name, m.kind, m.source, m.desc))
    for prefix in sorted(DYNAMIC_PREFIXES):
        lines.append(
            "| `%s*` | counter | dynamic family | %s |"
            % (prefix, DYNAMIC_PREFIXES[prefix])
        )
    lines.append(_END)
    return "\n".join(lines)


def _split_doc(text, path):
    try:
        head, rest = text.split(_BEGIN, 1)
        table, tail = rest.split(_END, 1)
    except ValueError:
        raise SystemExit(
            "%s: metric-table markers missing (%r ... %r) — "
            "regenerate with --write" % (path, _BEGIN, _END)
        )
    return head, table, tail


def check_docs(path):
    """Drift test: the doc's generated region must byte-match the
    catalog rendering.  Returns [] when clean, else human-readable
    drift lines."""
    with open(path) as f:
        text = f.read()
    _head, table, _tail = _split_doc(text, path)
    want = render_markdown()
    got = _BEGIN + table + _END
    if got.strip() == want.strip():
        return []
    want_l = set(want.strip().splitlines())
    got_l = set(got.strip().splitlines())
    drift = ["catalog row missing from doc: %s" % l
             for l in sorted(want_l - got_l)]
    drift += ["doc row not in catalog: %s" % l
              for l in sorted(got_l - want_l)]
    return drift or ["metric table differs (ordering)"]


def write_docs(path):
    """Regenerate the doc's metric table in place."""
    with open(path) as f:
        text = f.read()
    head, _table, tail = _split_doc(text, path)
    with open(path, "w") as f:
        f.write(head + render_markdown() + tail)


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tensorflowonspark_tpu.telemetry.catalog",
        description="metric-catalog docs generation / drift check",
    )
    ap.add_argument("--check", metavar="DOC", help="fail on doc drift")
    ap.add_argument("--write", metavar="DOC", help="regenerate the doc table")
    args = ap.parse_args(argv)
    dups = duplicates()
    if dups:
        print("catalog declares duplicate metrics: %s" % ", ".join(dups))
        return 1
    if args.write:
        write_docs(args.write)
        print("%s: metric table regenerated (%d metrics)"
              % (args.write, len(METRICS)))
    if args.check:
        drift = check_docs(args.check)
        if drift:
            print("%s: metric table DRIFTED from telemetry/catalog.py:"
                  % args.check)
            for line in drift:
                print("  " + line)
            print("fix: python -m tensorflowonspark_tpu.telemetry."
                  "catalog --write %s" % args.check)
            return 1
        print("%s: metric table matches the catalog (%d metrics)"
              % (args.check, len(METRICS)))
    if not args.check and not args.write:
        print(render_markdown())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
