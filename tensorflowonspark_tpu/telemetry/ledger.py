"""Per-request usage ledger: cost attribution by request and tenant.

The consumption-attribution half of the observability stack (ISSUE
14).  The telemetry plane so far answers "how is the fleet doing";
nothing answers "WHO spent it".  This module keeps one **row per
request** — queue-wait seconds, decode chip-seconds (each decode
chunk's wall time apportioned by live slot share, so the per-request
rows sum back to the measured decode wall time), KV **page-seconds**
(the paged-pool occupancy integral: pages held × chunk duration),
prefix tokens saved, wire bytes, tokens in/out — and folds the rows
into **per-tenant aggregates** under the reserved ``"tenant"`` input
key (:data:`DEFAULT_TENANT` when a request carries none).

Bounding (a serving process must never grow without bound):

- the per-request row store is a bounded LRU of CLOSED rows (open
  rows are never evicted; totals survive eviction because aggregates
  fold incrementally, not from the rows);
- the per-tenant table holds at most ``max_tenants`` entries — the
  coldest tenant folds into :data:`OVERFLOW_TENANT` when a new one
  needs the slot — and a **space-saving sketch**
  (:class:`SpaceSaving`, Metwally et al.'s top-K heavy-hitter
  algorithm) keeps frequency estimates with bounded error for every
  tenant ever seen, so ``top(k)`` ranks heavy hitters even past the
  table bound.

Fleet aggregation rides the EXISTING heartbeat piggyback: the ledger
mirrors its per-tenant totals into the default metrics registry as
``usage.<field>.<tenant>`` counters (cardinality bounded by the tenant
table), which ship on heartbeat frames, merge in
``TPUCluster.metrics()`` fleet aggregation (counters sum — the correct
cross-process semantics), and appear on ``/metrics``.  The ``/usage``
HTTP route (telemetry/exposition.py) renders the per-tenant view as
JSON or as OpenMetrics counters with a ``tenant`` label
(:func:`usage_openmetrics` — round-trips the strict parser).

Zero-cost-when-disabled: every mutator consults the default
registry's enabled flag (the same ``TFOS_TELEMETRY=0`` /
``set_enabled(False)`` kill switch) and returns immediately when off.

See docs/observability.md "Cost attribution & usage ledger".
"""

import collections
import re
import threading

from tensorflowonspark_tpu.telemetry import registry as _registry
from tensorflowonspark_tpu.telemetry.catalog import TENANT_COLUMN

#: Tenant assigned to requests that carry no ``"tenant"`` input.
DEFAULT_TENANT = "default"

#: Reserved tenant bucket absorbing evicted per-tenant aggregates when
#: the bounded tenant table overflows (never evicted itself).
OVERFLOW_TENANT = "__other__"

#: Resource fields carried per row and per tenant.  ``requests`` is
#: bumped once per CLOSED request; everything else accrues as charged.
#: ``chip_sec`` is DECODE chip-seconds (per-chunk wall apportioned by
#: live slot share — the rows sum back to measured decode wall);
#: ``prefill_chip_sec`` is the request's prefill program wall, split
#: out so a disaggregated engine's two programs attribute separately
#: (unified engines charge their admit dispatch wall here too).
FIELDS = (
    "requests", "tokens_in", "tokens_out", "queue_wait_sec",
    "chip_sec", "prefill_chip_sec", "page_sec", "prefix_tokens_saved",
    "wire_bytes",
)

#: Registry-mirror metric prefix: per-tenant totals publish as
#: ``usage.<field>.<tenant>`` counters so they ride the heartbeat
#: piggyback into the fleet merge unchanged (counters sum).
MIRROR_PREFIX = "usage."

_TENANT_SAFE = re.compile(r"[^A-Za-z0-9_\-]")


def safe_tenant(tenant):
    """Tenant key → registry-safe token (no dots — the mirror name
    ``usage.<field>.<tenant>`` must split back unambiguously)."""
    out = _TENANT_SAFE.sub("_", str(tenant))
    return out or "_"


class SpaceSaving(object):
    """Bounded top-K heavy-hitter sketch (the *space-saving* algorithm:
    Metwally, Agrawal & El Abbadi, "Efficient computation of frequent
    and top-k elements in data streams").

    Keeps at most ``capacity`` ``(count, err)`` entries.  A new key
    arriving at capacity replaces the minimum entry and inherits its
    count as the overestimation error, which preserves the guarantees
    the algorithm is known for: every tracked count overestimates the
    true count by at most its ``err``, and any key whose true weight
    exceeds ``total / capacity`` is guaranteed to be tracked.
    """

    __slots__ = ("capacity", "total", "_counts", "_errs")

    def __init__(self, capacity=64):
        self.capacity = max(1, int(capacity))
        self.total = 0.0
        self._counts = {}
        self._errs = {}

    def add(self, key, weight=1.0):
        w = float(weight)
        if w <= 0.0:
            return
        self.total += w
        if key in self._counts:
            self._counts[key] += w
            return
        if len(self._counts) < self.capacity:
            self._counts[key] = w
            self._errs[key] = 0.0
            return
        victim = min(self._counts, key=self._counts.get)
        floor = self._counts.pop(victim)
        self._errs.pop(victim)
        self._counts[key] = floor + w
        self._errs[key] = floor

    def estimate(self, key):
        """``(count, err)`` — the true weight lies in
        ``[count - err, count]``; ``(0.0, 0.0)`` for untracked keys."""
        return self._counts.get(key, 0.0), self._errs.get(key, 0.0)

    def top(self, n=None):
        """``[(key, count, err)]`` heaviest first."""
        items = sorted(
            self._counts.items(), key=lambda kv: -kv[1]
        )
        if n is not None:
            items = items[:int(n)]
        return [(k, c, self._errs[k]) for k, c in items]

    def __len__(self):
        return len(self._counts)


def _zero_row():
    return {f: 0 if f in ("requests", "tokens_in", "tokens_out",
                          "prefix_tokens_saved", "wire_bytes") else 0.0
            for f in FIELDS}


class UsageLedger(object):
    """Per-request resource rows + bounded per-tenant aggregates (see
    module docstring).

    Thread-safe under one ledger-level lock: mutations are dict
    arithmetic on a handful of fields, far off any device dispatch
    path (charges happen once per decode CHUNK, not per token).

    Args:
      max_rows: bound on retained per-request rows (closed rows evict
        LRU; open rows never evict).
      max_tenants: bound on the exact per-tenant table (the coldest
        tenant folds into :data:`OVERFLOW_TENANT` past it).
      sketch_capacity: :class:`SpaceSaving` entry bound (defaults to
        ``2 * max_tenants``).
      registry: metrics registry for the per-tenant mirror counters
        (default: the process registry — which also supplies the
        enabled flag).
    """

    def __init__(self, max_rows=4096, max_tenants=32,
                 sketch_capacity=None, registry=None):
        self.max_rows = max(1, int(max_rows))
        self.max_tenants = max(1, int(max_tenants))
        self._registry = registry
        self._lock = threading.Lock()
        self._rows = collections.OrderedDict()  # rid -> row dict
        self._tenants = {}                      # tenant -> totals dict
        self.sketch = SpaceSaving(
            sketch_capacity or 2 * self.max_tenants
        )
        self.rows_evicted = 0
        #: conservation remainder: the resource fields of every row
        #: that left the bounded table (LRU eviction, or a closed rid
        #: re-opened fresh) fold in here, so ``sum(rows()) + evicted_
        #: totals`` stays exact at any traffic volume — the soak
        #: harness's ledger-exactness probe depends on this
        self.evicted_totals = _zero_row()
        self.tenants_folded = 0
        self._mirror = {}  # (field, tenant) -> registry Counter
        #: tri-state override: None follows the registry's enabled
        #: flag (the TFOS_TELEMETRY story); True/False pins the
        #: ledger independently (the bench isolates the ledger's own
        #: increment this way)
        self.enabled_override = None

    # -- enable story ---------------------------------------------------

    def _reg(self):
        if self._registry is None:
            # resolve the process registry once — the enabled flag is
            # read off the cached object (set_enabled flips the flag,
            # not the object)
            self._registry = _registry.get_registry()
        return self._registry

    @property
    def enabled(self):
        if self.enabled_override is not None:
            return self.enabled_override
        return self._reg().enabled

    # -- row lifecycle --------------------------------------------------

    def _tenant_totals(self, tenant):
        t = self._tenants.get(tenant)
        if t is None:
            if (len(self._tenants) >= self.max_tenants
                    and tenant != OVERFLOW_TENANT):
                self._fold_coldest()
            t = self._tenants[tenant] = _zero_row()
        return t

    def _fold_coldest(self):
        """Fold the lightest tenant (by token weight) into the
        overflow bucket to free a table slot."""
        victims = [k for k in self._tenants if k != OVERFLOW_TENANT]
        if not victims:
            return
        victim = min(
            victims,
            key=lambda k: (self._tenants[k]["tokens_in"]
                           + self._tenants[k]["tokens_out"]),
        )
        vt = self._tenants.pop(victim)
        other = self._tenant_totals(OVERFLOW_TENANT)
        for f in FIELDS:
            other[f] += vt[f]
            self._mirror_inc(f, OVERFLOW_TENANT, vt[f])
        self.tenants_folded += 1

    def _mirror_inc(self, field, tenant, delta):
        if not delta:
            return
        key = (field, tenant)
        c = self._mirror.get(key)
        if c is None:
            c = self._mirror[key] = self._reg().counter(
                MIRROR_PREFIX + field + "." + safe_tenant(tenant)
            )
        c.inc(delta)

    def _apply(self, row, field, delta):
        """Add ``delta`` to a row field AND the row's tenant totals
        (plus the registry mirror) — the one write path, so rows,
        tenant aggregates, and the fleet mirror can never drift."""
        if not delta:
            return
        row[field] += delta
        t = self._tenant_totals(row[TENANT_COLUMN])
        t[field] += delta
        self._mirror_inc(field, row[TENANT_COLUMN], delta)
        if field in ("tokens_in", "tokens_out"):
            # heavy-hitter sketch weighs tenants by token volume
            self.sketch.add(row[TENANT_COLUMN], delta)

    def _retag(self, row, tenant):
        """Name a row's tenant.  Only a row with NOTHING accrued yet
        retags (every open path names the tenant before any charge);
        once usage has landed on a tenant it stays there — moving it
        would rewind the monotonic mirror counters, which the health
        plane would read as a process restart."""
        if row[TENANT_COLUMN] == tenant:
            return
        if any(row[f] for f in FIELDS):
            return
        row[TENANT_COLUMN] = tenant

    def _get_or_create(self, rid, fresh_if_closed=False):
        row = self._rows.get(rid)
        if row is not None and not (fresh_if_closed and row["closed"]):
            self._rows.move_to_end(rid)
            return row
        row = dict(_zero_row(), rid=str(rid), tenant=DEFAULT_TENANT,
                   closed=False, latency_sec=0.0, redispatches=0)
        if rid in self._rows:
            # a closed rid re-opening fresh: its prior incarnation's
            # charges leave the table — fold them into the remainder
            # so the conservation law (rows + evicted_totals) holds
            self._fold_evicted(self._rows.pop(rid))
        self._rows[rid] = row
        self._evict_rows()
        return row

    def _fold_evicted(self, row):
        for f in FIELDS:
            self.evicted_totals[f] += row.get(f, 0)

    def _evict_rows(self):
        while len(self._rows) > self.max_rows:
            victim = next(
                (k for k, r in self._rows.items() if r["closed"]), None
            )
            if victim is None:
                return  # everything open: never drop a live request
            self._fold_evicted(self._rows.pop(victim))
            self.rows_evicted += 1

    def open(self, rid, tenant=None, tokens_in=None, wire_bytes=0,
             prefix_tokens_saved=0, queue_wait_sec=0.0):
        """Open (or re-open) the request row ``rid``.

        Set-if-unset semantics for ``tenant``/``tokens_in`` (the fleet
        router opens first with the user-facing prompt; a replica
        engine re-opening a re-dispatched request — whose engine-level
        prompt includes committed tokens — must not inflate them);
        additive for the wear fields.  A CLOSED row re-opens fresh
        (the rid namespace recycles across jobs)."""
        if not self.enabled:
            return
        with self._lock:
            row = self._get_or_create(rid, fresh_if_closed=True)
            if tenant is not None:
                self._retag(row, str(tenant))
            if tokens_in is not None and row["tokens_in"] == 0:
                self._apply(row, "tokens_in", int(tokens_in))
            self._apply(row, "wire_bytes", int(wire_bytes))
            self._apply(row, "prefix_tokens_saved",
                        int(prefix_tokens_saved))
            self._apply(row, "queue_wait_sec", float(queue_wait_sec))

    def charge(self, rid, chip_sec=0.0, page_sec=0.0):
        """Accrue decode cost onto an open row (per decode chunk: the
        chunk's wall time over the live slot count, and pages-held ×
        chunk duration)."""
        if not self.enabled:
            return
        with self._lock:
            row = self._get_or_create(rid)
            self._apply(row, "chip_sec", float(chip_sec))
            self._apply(row, "page_sec", float(page_sec))

    def redispatch(self, rid):
        """Count a fleet re-dispatch against the row (replica death —
        the row keeps accruing on the surviving replica)."""
        if not self.enabled:
            return
        with self._lock:
            self._get_or_create(rid)["redispatches"] += 1

    def close(self, rid, tokens_out=None, latency_sec=None,
              chip_sec=0.0, page_sec=0.0):
        """Close (or re-close) ``rid``.  ``tokens_out`` uses
        ASSIGNMENT semantics with delta correction: a replica engine
        closes with its continuation count, the fleet router re-closes
        with the merged committed+continuation total, and the tenant
        aggregate lands on the final value exactly once.
        ``chip_sec``/``page_sec`` additively flush decode cost the
        caller accrued locally (the engine batches per-chunk charges
        and settles them here — one lock crossing per request)."""
        if not self.enabled:
            return
        with self._lock:
            row = self._get_or_create(rid)
            self._apply(row, "chip_sec", float(chip_sec))
            self._apply(row, "page_sec", float(page_sec))
            if tokens_out is not None:
                self._apply(row, "tokens_out",
                            int(tokens_out) - row["tokens_out"])
            if latency_sec is not None:
                row["latency_sec"] = float(latency_sec)
            if not row["closed"]:
                row["closed"] = True
                self._apply(row, "requests", 1)

    def settle(self, rid, tenant=None, tokens_in=None, wire_bytes=0,
               prefix_tokens_saved=0, queue_wait_sec=0.0, chip_sec=0.0,
               prefill_chip_sec=0.0, page_sec=0.0, tokens_out=None,
               latency_sec=None, close=True):
        """Open-accrue-close in ONE lock crossing — the serving
        engine's shape: it accumulates a request's admission fields
        and per-chunk decode cost on its own (lock-free) request
        record and settles the ledger once at the terminal point, so
        the cost plane never taxes the decode cadence.  Semantics
        match :meth:`open` (set-if-unset tenant/tokens_in, additive
        wear fields) + :meth:`close` (assignment-with-delta
        ``tokens_out``); ``close=False`` leaves the row open (the
        replica-death wreckage flush — the surviving replica
        continues the row)."""
        if not self.enabled:
            return
        with self._lock:
            # fresh-if-closed: a settle is always a NEW or CONTINUING
            # request — engine-local rids recycle across jobs, and a
            # previous job's closed row must never absorb this one
            # (re-close corrections go through :meth:`close`)
            row = self._get_or_create(rid, fresh_if_closed=True)
            if tenant is not None:
                self._retag(row, str(tenant))
            if tokens_in is not None and row["tokens_in"] == 0:
                self._apply(row, "tokens_in", int(tokens_in))
            self._apply(row, "wire_bytes", int(wire_bytes))
            self._apply(row, "prefix_tokens_saved",
                        int(prefix_tokens_saved))
            self._apply(row, "queue_wait_sec", float(queue_wait_sec))
            self._apply(row, "chip_sec", float(chip_sec))
            self._apply(row, "prefill_chip_sec", float(prefill_chip_sec))
            self._apply(row, "page_sec", float(page_sec))
            if tokens_out is not None:
                self._apply(row, "tokens_out",
                            int(tokens_out) - row["tokens_out"])
            if latency_sec is not None:
                row["latency_sec"] = float(latency_sec)
            if close and not row["closed"]:
                row["closed"] = True
                self._apply(row, "requests", 1)

    def record(self, rid, tenant=None, tokens_in=0, tokens_out=0,
               latency_sec=None, wire_bytes=0):
        """One-shot open+close (the static schedule's row shape: no
        chunk accounting, just tokens/latency/tenant)."""
        self.settle(rid, tenant=tenant, tokens_in=tokens_in,
                    wire_bytes=wire_bytes, tokens_out=tokens_out,
                    latency_sec=latency_sec)

    # -- introspection --------------------------------------------------

    def row(self, rid):
        with self._lock:
            r = self._rows.get(rid)
            return dict(r) if r is not None else None

    def rows(self, tenant=None, limit=None):
        """Newest-last per-request rows (optionally one tenant's)."""
        with self._lock:
            out = [dict(r) for r in self._rows.values()
                   if tenant is None or r[TENANT_COLUMN] == tenant]
        if limit is not None:
            out = out[-int(limit):]
        return out

    def tenants(self):
        """``{tenant: totals}`` — a copy of the aggregate table."""
        with self._lock:
            return {t: dict(v) for t, v in self._tenants.items()}

    def top(self, n=10):
        """Heavy hitters by token weight: ``[(tenant, est, err)]``
        from the space-saving sketch (survives table overflow)."""
        with self._lock:
            return self.sketch.top(n)

    def snapshot(self):
        """Plain-dict export mirroring ``registry.snapshot()``'s
        spirit: JSON-serializable, delta-able
        (:func:`snapshot_delta`), mergeable (:func:`merge_usage`)."""
        with self._lock:
            return {
                "tenants": {t: dict(v) for t, v in self._tenants.items()},
                "requests_tracked": len(self._rows),
                "rows_evicted": self.rows_evicted,
                "evicted_totals": dict(self.evicted_totals),
                "tenants_folded": self.tenants_folded,
                "top": [
                    [k, round(c, 6), round(e, 6)]
                    for k, c, e in self.sketch.top(10)
                ],
            }

    def reset(self):
        """Drop every row and aggregate (tests / bench windows).  The
        registry mirror counters are NOT rewound (counters are
        monotonic by contract — reset the registry itself for a clean
        window)."""
        with self._lock:
            self._rows.clear()
            self._tenants.clear()
            self.sketch = SpaceSaving(self.sketch.capacity)
            self.rows_evicted = 0
            self.evicted_totals = _zero_row()
            self.tenants_folded = 0
            self._mirror.clear()


def snapshot_delta(cur, base):
    """``cur - base`` over two :meth:`UsageLedger.snapshot` dicts —
    the per-job / per-bench-window accounting primitive (the
    registry's ``snapshot_delta`` rule, applied to tenant tables)."""
    base = base or {}
    bt = base.get("tenants", {})
    tenants = {}
    for t, v in (cur.get("tenants") or {}).items():
        b = bt.get(t, {})
        d = {f: v.get(f, 0) - b.get(f, 0) for f in FIELDS}
        if any(d.values()):
            tenants[t] = d
    return {
        "tenants": tenants,
        "requests_tracked": cur.get("requests_tracked", 0),
        "rows_evicted": (cur.get("rows_evicted", 0)
                         - base.get("rows_evicted", 0)),
        "tenants_folded": (cur.get("tenants_folded", 0)
                           - base.get("tenants_folded", 0)),
        "top": cur.get("top", []),
    }


def merge_usage(snapshots):
    """Fold per-executor ledger snapshots into one fleet view
    (tenant fields sum — the ``merge_snapshots`` counter rule)."""
    tenants = {}
    evicted = folded = tracked = 0
    for snap in snapshots:
        if not snap:
            continue
        for t, v in (snap.get("tenants") or {}).items():
            agg = tenants.setdefault(t, _zero_row())
            for f in FIELDS:
                agg[f] += v.get(f, 0)
        tracked += snap.get("requests_tracked", 0)
        evicted += snap.get("rows_evicted", 0)
        folded += snap.get("tenants_folded", 0)
    top = sorted(
        ((t, v["tokens_in"] + v["tokens_out"]) for t, v in tenants.items()),
        key=lambda kv: -kv[1],
    )
    return {
        "tenants": tenants,
        "requests_tracked": tracked,
        "rows_evicted": evicted,
        "tenants_folded": folded,
        "top": [[t, w, 0.0] for t, w in top[:10]],
    }


def tenants_from_snapshot(snapshot):
    """Recover the per-tenant table from a REGISTRY snapshot's mirror
    counters (``usage.<field>.<tenant>``) — how the ``/usage`` route
    renders the FLEET-wide view off the health plane's merged scrape
    (every executor's mirror counters summed by the normal counter
    merge) without a second wire format."""
    tenants = {}
    for name, v in (snapshot or {}).get("counters", {}).items():
        if not name.startswith(MIRROR_PREFIX):
            continue
        parts = name[len(MIRROR_PREFIX):].split(".", 1)
        if len(parts) != 2 or parts[0] not in FIELDS:
            continue
        field, tenant = parts
        t = tenants.setdefault(tenant, _zero_row())
        t[field] = v
    return tenants


def chip_sec_per_token(rows, min_tokens=1):
    """Cost-efficiency ratios from cost rows (ISSUE 18): ``{key:
    chip_sec / tokens_out}`` over any row table shaped like the
    router's per-replica ``health_status()["costs"]`` or a tenant
    table from :func:`tenants_from_snapshot`.  Rows with fewer than
    ``min_tokens`` emitted are skipped — a cold row's ratio is all
    prefill, not a cost signal.  The remediation plane's
    :class:`~tensorflowonspark_tpu.remediation.policy.CostPolicy`
    judges the fleet on exactly these ratios."""
    out = {}
    for key, row in (rows or {}).items():
        toks = int(row.get("tokens_out", 0))
        if toks < max(1, int(min_tokens)):
            continue
        out[key] = float(row.get("chip_sec", 0.0)) / toks
    return out


def usage_openmetrics(tenants):
    """Per-tenant totals → OpenMetrics text with a bounded ``tenant``
    label — the ``/usage`` route body, round-tripping the strict
    :func:`~tensorflowonspark_tpu.telemetry.exposition.
    parse_openmetrics` (cardinality is bounded by the ledger's tenant
    table, never by the request stream)."""
    from tensorflowonspark_tpu.telemetry import exposition as _expo

    lines = []
    for field in FIELDS:
        om = "usage_" + field
        lines.append("# TYPE {0} counter".format(om))
        for tenant in sorted(tenants):
            lines.append('{0}_total{{tenant="{1}"}} {2}'.format(
                om, safe_tenant(tenant), _expo._fmt(tenants[tenant][field])
            ))
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


_GLOBAL = None
_GLOBAL_LOCK = threading.Lock()


def get_ledger():
    """The process-wide usage ledger every serving surface charges
    into (same enable story as the default registry)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = UsageLedger()
    return _GLOBAL
