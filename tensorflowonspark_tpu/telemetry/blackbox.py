"""Per-process flight recorder: always-on ring, fault-triggered dumps.

An aircraft black box records continuously and is read after the
crash.  Same contract here (ISSUE 11 tentpole): every instrumented
process keeps its recent spans, journal events, and metric state live
in bounded rings (the tracer, journal, and registry it already runs),
and the :class:`FlightRecorder` freezes them into a **dump bundle**
the instant a fault event lands — while the evidence is still in
memory, before a restart or teardown erases it.

Triggers ride the journal's listener bus (telemetry/journal.py): the
recorder subscribes once, and any event whose ``kind`` is in
:data:`DUMP_TRIGGERS` — or whose severity is ``page`` — produces a
dump.  Because every fault site already marks the tracer and marks
bridge into the journal, the trigger set covers, with zero new
call-site code:

- ``watchdog_fire`` — a wedged serving dispatch (serving_engine.py);
- ``swap_rollback`` — a weight generation rolled back (canary or
  probation-window failure);
- ``restart`` / ``executor_restart`` — a supervisor rebirth of a dead
  compute process (supervisor.py / cluster.py);
- ``executor_dead`` — the driver monitor declaring a node permanently
  dead (page severity);
- ``leader_failover`` — the hierarchical gradient plane re-electing a
  dead DCN leader (parallel/hier_ps.py);
- any ``page``-severity SLO alert (``alert_firing`` from the
  SloEngine).

A dump bundle is one JSON file: the trigger event, the journal rings,
the tracer's span ring (plus its wall-clock epoch, so the forensics
analyzer can align spans across executors with the heartbeat-estimated
clock offsets), the registry snapshot and the delta since the
recorder started, and process identity.  Dumps are rate-limited per
trigger kind and capped per process — a crash loop must not fill the
disk.

Driver-side collection: a recorder attached to a node kv
(:meth:`FlightRecorder.attach_kv`) publishes its dump index under
``blackbox_dumps``; ``TPUCluster.collect_dumps()`` reads every node's
index through the existing manager connections — no new wire protocol.

``install()`` is the one-call idempotent setup
(``_compute_process_main``, the node supervisor, ``ServingEngine``,
and ``HealthPlane`` all call it); ``TFOS_BLACKBOX=0`` disables the
whole module.
"""

import json
import logging
import os
import tempfile
import threading
import time

from tensorflowonspark_tpu.telemetry import journal as _journal
from tensorflowonspark_tpu.telemetry import registry as _registry
from tensorflowonspark_tpu.telemetry import tracing as _tracing

logger = logging.getLogger(__name__)

#: Env kill-switch for the recorder alone (the journal/tracer keep
#: running): TFOS_BLACKBOX=0.
BLACKBOX_ENV = "TFOS_BLACKBOX"

#: Where dumps land (env-tunable: TFOS_BLACKBOX_DIR); default
#: ``<tmp>/tfos_blackbox``.
DUMP_DIR_ENV = "TFOS_BLACKBOX_DIR"

#: Event kinds that trigger a dump regardless of severity (any
#: ``page``-severity event triggers too).
DUMP_TRIGGERS = frozenset({
    "watchdog_fire",
    "swap_rollback",
    "restart",
    "executor_restart",
    "restart_budget_exhausted",
    "executor_dead",
    "leader_failover",
})

#: Bundle format tag (the forensics analyzer's dispatch key).
BUNDLE_FORMAT = "tfos-blackbox-1"

#: Per-process dump cap and per-kind rate limit (seconds) — crash
#: loops must not fill the disk (env-tunable).
MAX_DUMPS = int(os.environ.get("TFOS_BLACKBOX_MAX_DUMPS", "16"))
MIN_INTERVAL = float(os.environ.get("TFOS_BLACKBOX_MIN_INTERVAL", "5.0"))


def _env_enabled():
    return os.environ.get(BLACKBOX_ENV, "1").lower() not in (
        "0", "false", "off", "no",
    )


class FlightRecorder(object):
    """Always-on recorder over one process's journal/tracer/registry.

    Args:
      journal, tracer, registry: the rings to freeze (defaults: the
        process-wide singletons).
      dump_dir: where bundles land (created on first dump).
      triggers: event kinds that dump (default :data:`DUMP_TRIGGERS`;
        ``page`` severity always triggers).
      max_dumps / min_interval: the disk-protection bounds (the cap is
        per recorder ≈ per process; the interval per trigger kind).
      clock: wall-clock source (injectable for tests).
    """

    def __init__(self, journal=None, tracer=None, registry=None,
                 dump_dir=None, triggers=None, max_dumps=None,
                 min_interval=None, clock=None):
        self.journal = journal or _journal.get_journal()
        self.tracer = tracer or _tracing.get_tracer()
        self.registry = registry or _registry.get_registry()
        self.dump_dir = os.fspath(
            dump_dir
            or os.environ.get(DUMP_DIR_ENV)
            or os.path.join(tempfile.gettempdir(), "tfos_blackbox")
        )
        self.triggers = (
            DUMP_TRIGGERS if triggers is None else frozenset(triggers)
        )
        self.max_dumps = MAX_DUMPS if max_dumps is None else int(max_dumps)
        self.min_interval = (
            MIN_INTERVAL if min_interval is None else float(min_interval)
        )
        self._clock = clock or time.time
        self._lock = threading.Lock()
        self._last_dump = {}   # kind -> wall time of its last dump
        self._seq = 0
        self._started = False
        self._mgr = None
        self._baseline = None
        self._m_dumps = self.registry.counter("blackbox.dumps")
        self._m_suppressed = self.registry.counter(
            "blackbox.dumps_suppressed"
        )
        #: dump records this recorder produced:
        #: ``{"path", "reason", "time", "trigger"}``
        self.dumps = []

    # -- lifecycle ------------------------------------------------------

    def start(self):
        """Subscribe the dump trigger to the journal (idempotent) and
        snapshot the metrics baseline the bundle deltas against."""
        with self._lock:
            if self._started:
                return self
            self._started = True
        self._baseline = self.registry.snapshot()
        self.journal.add_listener(self._on_event)
        return self

    def stop(self):
        with self._lock:
            self._started = False
        self.journal.remove_listener(self._on_event)

    def attach_kv(self, mgr):
        """Publish this recorder's dump index into a node manager kv
        (``blackbox_dumps``) after every dump, so the driver can
        collect bundles through its existing manager connections
        (``TPUCluster.collect_dumps``)."""
        self._mgr = mgr
        self._publish_index()
        return self

    # -- triggering -----------------------------------------------------

    def _on_event(self, ev):
        if ev.kind not in self.triggers and ev.severity != "page":
            return
        self.dump(ev.kind, trigger=ev)

    def dump(self, reason, trigger=None):
        """Freeze the rings into one bundle file; returns its path, or
        None when suppressed (cap / rate limit / disabled journal)."""
        now = self._clock()
        with self._lock:
            if len(self.dumps) >= self.max_dumps:
                self._m_suppressed.inc()
                return None
            last = self._last_dump.get(reason)
            if last is not None and now - last < self.min_interval:
                self._m_suppressed.inc()
                return None
            self._last_dump[reason] = now
            self._seq += 1
            seq = self._seq
        bundle = self.bundle(reason, trigger=trigger, now=now)
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(
                self.dump_dir,
                "blackbox-{0}-{1:03d}-{2}.json".format(
                    os.getpid(), seq, _safe(reason)
                ),
            )
            with open(path, "w") as f:
                json.dump(bundle, f)
        except (OSError, TypeError, ValueError):
            logger.warning(
                "flight recorder could not write a dump for %r",
                reason, exc_info=True,
            )
            return None
        rec = {
            "path": path, "reason": reason, "time": now,
            "executor": self.journal.executor,
        }
        with self._lock:
            self.dumps.append(rec)
        self._m_dumps.inc()
        logger.warning(
            "flight recorder: dumped %r bundle to %s", reason, path
        )
        self._publish_index()
        return path

    def bundle(self, reason, trigger=None, now=None):
        """The in-memory dump bundle (what :meth:`dump` serializes)."""
        now = self._clock() if now is None else now
        delta = None
        snap = self.registry.snapshot()
        if self._baseline is not None:
            try:
                delta = _registry.snapshot_delta(snap, self._baseline)
            except Exception:  # noqa: BLE001 - delta is advisory
                delta = None
        return {
            "format": BUNDLE_FORMAT,
            "reason": reason,
            "time": now,
            "pid": os.getpid(),
            "executor": self.journal.executor,
            "trigger": trigger.to_dict() if trigger is not None else None,
            # the alignment anchor: span t0/dur are relative to the
            # tracer's perf_counter epoch; epoch_wall places them on
            # the wall clock the journal events and the heartbeat
            # clock-offset estimates live on
            "clock": {"epoch_wall": self.tracer.epoch_wall},
            "events": [e.to_dict() for e in self.journal.events()],
            "spans": self.tracer.spans(),
            "metrics": snap,
            "metrics_delta": delta,
        }

    def _publish_index(self):
        if self._mgr is None:
            return
        try:
            with self._lock:
                index = list(self.dumps)
            self._mgr.set("blackbox_dumps", index)
        except Exception:  # noqa: BLE001 - kv is best effort
            logger.warning(
                "flight recorder could not publish its dump index",
                exc_info=True,
            )


def _safe(name):
    return "".join(
        c if c.isalnum() or c in "-_" else "_" for c in str(name)
    )[:48]


def load_dump(path):
    """Read a dump bundle back; raises ValueError on a non-bundle."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("format") != BUNDLE_FORMAT:
        raise ValueError(
            "{0} is not a {1} bundle".format(path, BUNDLE_FORMAT)
        )
    return data


_GLOBAL = None
_GLOBAL_LOCK = threading.Lock()


def install(**kwargs):
    """Start (or return) the process-wide recorder.  Returns None when
    disabled (``TFOS_BLACKBOX=0`` or telemetry off) — callers treat
    the recorder as strictly optional."""
    global _GLOBAL
    if not _env_enabled() or not _registry.get_registry().enabled:
        return None
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = FlightRecorder(**kwargs).start()
    return _GLOBAL


def get_recorder():
    """The installed process-wide recorder, or None."""
    return _GLOBAL
