"""Batch Example → columnar numpy: the native feed fast path.

Role parity with the reference JVM layer's record→tensor conversion
(``batch2tensors``, TFModel.scala:51-114, and the tensorflow-hadoop
jar's record decode): a batch of serialized ``tf.train.Example`` protos
is parsed in C++ (native/example_codec.cc) straight into contiguous
columnar arrays — one pass per requested column, no per-value Python
objects — ready for ``jax.device_put``.  Pure-Python fallback via
:mod:`tensorflowonspark_tpu.data.example` keeps the package working
without a compiler.

Fixed-width numeric columns only (the training fast path); string /
ragged features go through the row decoder.

Narrow-dtype wire plane (docs/data_plane.md): ``tf.train.Example``
only stores float32/int64, so the proto layer PROMOTES — a uint8 pixel
costs 8 bytes as an int64 feature value.  :class:`WireSpec` and the
narrow-dtype support in :func:`decode_batch` undo that at ingest:
columns declared narrow (uint8/int8/int16/int32/uint16/float16) are
value-checked and stored in their wire dtype immediately after the
proto decode, so every later hop — ``ColumnarBlock`` pack, the shm
ring, ``DataFeed.next_arrays``, the host→HBM DMA — ships the narrow
bytes.  Widening back to the compute dtype happens ON DEVICE
(:mod:`tensorflowonspark_tpu.data.preprocess`).
"""

import ctypes
import logging

import numpy as np

from tensorflowonspark_tpu.data import _native

logger = logging.getLogger(__name__)

#: wire dtypes decode_batch can narrow an int64-kind feature to (value
#: checked — out-of-range raises, never silently wraps)
NARROW_INT_DTYPES = ("uint8", "int8", "uint16", "int16", "uint32", "int32")
#: wire dtypes a float32-kind feature can narrow to (precision-lossy by
#: declaration — the caller chose the storage dtype)
NARROW_FLOAT_DTYPES = ("float16",)


def narrow_cast(arr, dtype):
    """Cast ``arr`` to a narrower integer ``dtype`` with a VALUE check:
    a label of 300 declared uint8 must raise, not silently wrap to 44
    (corrupted training data).  Float narrowing (float16) is allowed
    without the check — precision loss is the declared storage
    contract, wrap-around is not."""
    dtype = np.dtype(dtype)
    if arr.dtype == dtype:
        return arr
    if dtype.kind in "iu":
        info = np.iinfo(dtype)
        if arr.size and (arr.min() < info.min or arr.max() > info.max):
            raise ValueError(
                "values outside {0} range [{1}, {2}] (min={3}, max={4})"
                ": refusing the silent wrap-around".format(
                    dtype.name, info.min, info.max,
                    arr.min(), arr.max(),
                )
            )
    return arr.astype(dtype)


class WireSpec(object):
    """Per-column wire (storage) dtypes for the narrow-dtype plane.

    ``WireSpec({"image": "uint8", "label": "int32"})`` declares the
    dtype each column ships in end-to-end (feeder → ring → consumer);
    columns not named pass through unchanged.  Use :meth:`narrow` at
    ingest (after a promoting decode) and
    :func:`~tensorflowonspark_tpu.data.preprocess.make_preprocess` on
    device to widen back to the compute dtype.
    """

    def __init__(self, dtypes):
        self.dtypes = {k: np.dtype(v) for k, v in dict(dtypes).items()}

    def narrow(self, columns):
        """Cast named columns of a dict/tuple column set to their wire
        dtypes (value-checked via :func:`narrow_cast`).  Tuple column
        sets are addressed by integer keys in the spec."""
        if isinstance(columns, dict):
            return {
                k: narrow_cast(np.asarray(v), self.dtypes[k])
                if k in self.dtypes else v
                for k, v in columns.items()
            }
        return tuple(
            narrow_cast(np.asarray(v), self.dtypes[i])
            if i in self.dtypes else v
            for i, v in enumerate(columns)
        )

    def narrow_rows(self, rows):
        """Narrow dict rows one by one (the feeder-side map for row
        streams that are not yet columnar)."""
        out = []
        for row in rows:
            out.append({
                k: narrow_cast(np.asarray(v), self.dtypes[k])
                if k in self.dtypes else v
                for k, v in row.items()
            })
        return out

    @staticmethod
    def wire_bytes(columns):
        """Total wire bytes of a dict/tuple column set (what one batch
        costs on the tunnel) — the accounting half of the narrowing
        claim (``feed.wire_stats()`` aggregates the same number on the
        consumer side)."""
        vals = columns.values() if isinstance(columns, dict) else columns
        return int(sum(np.asarray(v).nbytes for v in vals))

_LIB_NAME = "libexample_codec.so"

_ERRORS = {
    -1: "feature missing from a record",
    -2: "feature has a different kind than requested",
    -3: "feature width differs from the requested width",
    -4: "malformed Example proto",
}


def _configure(lib):
    pp = ctypes.POINTER(ctypes.c_char_p)
    for fname, ctype in (
        ("ex_extract_float", ctypes.POINTER(ctypes.c_float)),
        ("ex_extract_int64", ctypes.POINTER(ctypes.c_int64)),
    ):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int
        fn.argtypes = [
            pp,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.c_char_p,
            ctype,
            ctypes.c_int64,
        ]


def _load_native():
    return _native.load_library(_LIB_NAME, _configure)


def _extract_native(lib, records, name, width, dtype, recs=None, lens=None):
    n = len(records)
    if recs is None:
        recs = (ctypes.c_char_p * n)(*records)
        lens = (ctypes.c_uint64 * n)(*[len(r) for r in records])
    out = np.empty((n, width), dtype)
    if dtype == np.float32:
        rc = lib.ex_extract_float(
            recs, lens, n, name.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), width,
        )
    else:
        rc = lib.ex_extract_int64(
            recs, lens, n, name.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), width,
        )
    if rc != 0:
        raise ValueError(
            "column {0!r}: {1}".format(name, _ERRORS.get(rc, "error %d" % rc))
        )
    return out


def _extract_python(records, name, width, dtype):
    from tensorflowonspark_tpu.data import example as ex

    out = np.empty((len(records), width), dtype)
    kind_wanted = ex.KIND_FLOAT if dtype == np.float32 else ex.KIND_INT64
    for i, rec in enumerate(records):
        feats = ex.decode_example(rec)
        if name not in feats:
            raise ValueError("column {0!r}: {1}".format(name, _ERRORS[-1]))
        kind, values = feats[name]
        if values and kind != kind_wanted:
            raise ValueError("column {0!r}: {1}".format(name, _ERRORS[-2]))
        if len(values) != width:
            raise ValueError("column {0!r}: {1}".format(name, _ERRORS[-3]))
        out[i] = values
    return out


def decode_batch(records, columns):
    """Decode serialized Examples into columnar arrays.

    Args:
      records: list of ``bytes`` (serialized ``tf.train.Example``).
      columns: ``{name: (dtype, width)}``; every record must carry
        exactly ``width`` values (missing/ragged features raise —
        silent zero-fill would corrupt training data).  ``dtype`` is
        ``"float32"`` / ``"int64"`` (the proto's native kinds) or a
        NARROW wire dtype: int64-kind features narrow to any of
        ``NARROW_INT_DTYPES`` (value-checked — an out-of-range value
        raises instead of wrapping) and float32-kind features to
        ``NARROW_FLOAT_DTYPES``.  Narrowing happens immediately after
        the proto decode, so everything downstream (ColumnarBlock, shm
        ring, device_put) ships the narrow bytes (docs/data_plane.md).

    Returns:
      ``{name: np.ndarray[n, width]}`` (width-1 columns keep the
      trailing axis; squeeze at the call site if needed).
    """
    records = [bytes(r) for r in records]
    lib = _load_native()
    recs = lens = None
    if lib is not None and records:
        # build the ctypes views once, shared across all columns
        recs = (ctypes.c_char_p * len(records))(*records)
        lens = (ctypes.c_uint64 * len(records))(*[len(r) for r in records])
    out = {}
    for name, (dtype, width) in columns.items():
        wire_dtype = np.dtype(dtype)
        if wire_dtype.name in NARROW_INT_DTYPES:
            extract_dtype = np.int64
        elif wire_dtype.name in NARROW_FLOAT_DTYPES:
            extract_dtype = np.float32
        elif wire_dtype.type in (np.float32, np.int64):
            extract_dtype = wire_dtype.type
        else:
            raise ValueError(
                "column {0!r}: columnar decode supports float32/int64 "
                "and the narrow wire dtypes {1} (got {2})".format(
                    name,
                    NARROW_INT_DTYPES + NARROW_FLOAT_DTYPES,
                    wire_dtype,
                )
            )
        if lib is not None:
            arr = _extract_native(
                lib, records, name, width, extract_dtype,
                recs=recs, lens=lens,
            )
        else:
            arr = _extract_python(records, name, width, extract_dtype)
        try:
            out[name] = narrow_cast(arr, wire_dtype)
        except ValueError as e:
            raise ValueError("column {0!r}: {1}".format(name, e))
    return out


def load_tfrecords_columnar(path, columns):
    """TFRecord file/dir → columnar arrays in one pass (the
    InputMode.TENSORFLOW training-data fast path; see
    examples/mnist/mnist_tf.py for the row-based equivalent)."""
    from tensorflowonspark_tpu.data import tfrecord as tfr
    from tensorflowonspark_tpu.data.interchange import _record_files

    records = []
    for f in _record_files(path):
        records.extend(tfr.read_records(f))
    return decode_batch(records, columns)
