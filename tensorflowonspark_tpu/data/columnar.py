"""Batch Example → columnar numpy: the native feed fast path.

Role parity with the reference JVM layer's record→tensor conversion
(``batch2tensors``, TFModel.scala:51-114, and the tensorflow-hadoop
jar's record decode): a batch of serialized ``tf.train.Example`` protos
is parsed in C++ (native/example_codec.cc) straight into contiguous
columnar arrays — one pass per requested column, no per-value Python
objects — ready for ``jax.device_put``.  Pure-Python fallback via
:mod:`tensorflowonspark_tpu.data.example` keeps the package working
without a compiler.

Fixed-width numeric columns only (the training fast path); string /
ragged features go through the row decoder.
"""

import ctypes
import logging

import numpy as np

from tensorflowonspark_tpu.data import _native

logger = logging.getLogger(__name__)

_LIB_NAME = "libexample_codec.so"

_ERRORS = {
    -1: "feature missing from a record",
    -2: "feature has a different kind than requested",
    -3: "feature width differs from the requested width",
    -4: "malformed Example proto",
}


def _configure(lib):
    pp = ctypes.POINTER(ctypes.c_char_p)
    for fname, ctype in (
        ("ex_extract_float", ctypes.POINTER(ctypes.c_float)),
        ("ex_extract_int64", ctypes.POINTER(ctypes.c_int64)),
    ):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int
        fn.argtypes = [
            pp,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.c_char_p,
            ctype,
            ctypes.c_int64,
        ]


def _load_native():
    return _native.load_library(_LIB_NAME, _configure)


def _extract_native(lib, records, name, width, dtype, recs=None, lens=None):
    n = len(records)
    if recs is None:
        recs = (ctypes.c_char_p * n)(*records)
        lens = (ctypes.c_uint64 * n)(*[len(r) for r in records])
    out = np.empty((n, width), dtype)
    if dtype == np.float32:
        rc = lib.ex_extract_float(
            recs, lens, n, name.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), width,
        )
    else:
        rc = lib.ex_extract_int64(
            recs, lens, n, name.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), width,
        )
    if rc != 0:
        raise ValueError(
            "column {0!r}: {1}".format(name, _ERRORS.get(rc, "error %d" % rc))
        )
    return out


def _extract_python(records, name, width, dtype):
    from tensorflowonspark_tpu.data import example as ex

    out = np.empty((len(records), width), dtype)
    kind_wanted = ex.KIND_FLOAT if dtype == np.float32 else ex.KIND_INT64
    for i, rec in enumerate(records):
        feats = ex.decode_example(rec)
        if name not in feats:
            raise ValueError("column {0!r}: {1}".format(name, _ERRORS[-1]))
        kind, values = feats[name]
        if values and kind != kind_wanted:
            raise ValueError("column {0!r}: {1}".format(name, _ERRORS[-2]))
        if len(values) != width:
            raise ValueError("column {0!r}: {1}".format(name, _ERRORS[-3]))
        out[i] = values
    return out


def decode_batch(records, columns):
    """Decode serialized Examples into columnar arrays.

    Args:
      records: list of ``bytes`` (serialized ``tf.train.Example``).
      columns: ``{name: (dtype, width)}`` with dtype ``"float32"`` or
        ``"int64"``; every record must carry exactly ``width`` values
        (missing/ragged features raise — silent zero-fill would corrupt
        training data).

    Returns:
      ``{name: np.ndarray[n, width]}`` (width-1 columns keep the
      trailing axis; squeeze at the call site if needed).
    """
    records = [bytes(r) for r in records]
    lib = _load_native()
    recs = lens = None
    if lib is not None and records:
        # build the ctypes views once, shared across all columns
        recs = (ctypes.c_char_p * len(records))(*records)
        lens = (ctypes.c_uint64 * len(records))(*[len(r) for r in records])
    out = {}
    for name, (dtype, width) in columns.items():
        dtype = np.dtype(dtype).type
        if dtype not in (np.float32, np.int64):
            raise ValueError(
                "column {0!r}: only float32/int64 columnar decode is "
                "supported (got {1})".format(name, dtype)
            )
        if lib is not None:
            out[name] = _extract_native(
                lib, records, name, width, dtype, recs=recs, lens=lens
            )
        else:
            out[name] = _extract_python(records, name, width, dtype)
    return out


def load_tfrecords_columnar(path, columns):
    """TFRecord file/dir → columnar arrays in one pass (the
    InputMode.TENSORFLOW training-data fast path; see
    examples/mnist/mnist_tf.py for the row-based equivalent)."""
    from tensorflowonspark_tpu.data import tfrecord as tfr
    from tensorflowonspark_tpu.data.interchange import _record_files

    records = []
    for f in _record_files(path):
        records.extend(tfr.read_records(f))
    return decode_batch(records, columns)
