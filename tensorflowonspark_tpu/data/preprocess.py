"""On-device input preprocessing: the compute half of the narrow-dtype
data plane (docs/data_plane.md).

The wire half (:class:`~tensorflowonspark_tpu.data.columnar.WireSpec`,
the columnar feed, the shm ring) keeps image/int-like columns in their
STORAGE dtype — uint8 pixels stay uint8 from the Spark row to the HBM
DMA, cutting tunnel bytes up to 4x vs the old promote-to-float32-at-
ingest.  Something still has to widen them before the matmuls; doing it
on the host re-inflates the transfer, so this module builds a small
jit-traceable graph (cast / scale / offset / mean-sub / std-div,
optional center-crop and random flip) that runs fused IN FRONT of the
train or predict step — the cast happens in HBM ("TensorFlow: A system
for large-scale machine learning" attributes much of its input-pipeline
headroom to exactly this move).

Wired through:

- ``prefetch_to_device(..., preprocess=...)`` (data/feed.py) — applied
  on the device-resident batch after the async ``device_put``;
- ``SyncTrainer(device_preprocess=...)`` (parallel/dp.py) — traced into
  the jitted train step (and the fused multi-step scan body);
- ``serving.load_predictor(..., preprocess=...)`` /
  ``serving.with_preprocess`` — a jitted stage in front of the
  predictor, also resolvable from the serving export's metadata
  (``save_for_serving(..., extra_metadata={"preprocess": {...}})``);
- ``TFEstimator/TFModel`` ``setPreprocess`` params (pipeline.py).

Numerics contract: ``make_preprocess(dtype, scale, mean, std)`` on a
uint8 batch matches the host-side ``x.astype(np.float32) * scale``
path to float32 tolerance (parity-tested in tests/test_preprocess.py).
"""

import logging

import numpy as np

logger = logging.getLogger(__name__)

#: dtypes the default column selection treats as "narrow wire" inputs
#: that need widening (labels/indices are typically int32/int64 and are
#: left alone)
NARROW_DTYPES = ("uint8", "int8", "uint16", "int16")


def _is_narrow(a):
    try:
        return np.dtype(getattr(a, "dtype", None)).name in NARROW_DTYPES
    except TypeError:
        return False


def make_preprocess(
    columns=None,
    dtype="float32",
    scale=None,
    offset=None,
    mean=None,
    std=None,
    crop=None,
    flip=False,
):
    """Build a jit-traceable batch preprocess ``fn(batch[, rng])``.

    ``batch`` may be a single array, a tuple of columns, or a dict of
    named columns; the transform applies to the selected columns and
    passes the rest through untouched.

    Args:
      columns: which entries to transform — a list of names (dict
        batches) or indices (tuple batches).  Default ``None`` selects
        every column with a NARROW wire dtype (uint8/int8/uint16/int16)
        — the columns the wire plane deliberately did not widen; int32+
        label/index columns pass through.
      dtype: compute dtype the selected columns are cast to.
      scale / offset: ``x * scale + offset`` after the cast (e.g.
        ``scale=1/255`` for uint8 pixels).
      mean / std: ``(x - mean) / std`` after scale/offset (arrays
        broadcast, e.g. per-channel ImageNet stats).
      crop: ``(h, w)`` center crop of axes 1 and 2 (NHWC batches).
      flip: random horizontal flip (axis 2) per row — requires the
        ``rng`` argument at call time; with ``rng=None`` the flip is
        skipped (the deterministic eval/serving path).

    Returns ``fn(batch, rng=None) -> batch`` built from jax.numpy ops —
    trace it under ``jax.jit`` (the wiring points above do) so the
    widening runs on device.
    """
    import jax
    import jax.numpy as jnp

    out_dtype = jnp.dtype(dtype)

    def _one(x, rng):
        x = jnp.asarray(x)
        x = x.astype(out_dtype)
        if scale is not None:
            x = x * jnp.asarray(scale, out_dtype)
        if offset is not None:
            x = x + jnp.asarray(offset, out_dtype)
        if mean is not None:
            x = x - jnp.asarray(mean, out_dtype)
        if std is not None:
            x = x / jnp.asarray(std, out_dtype)
        if crop is not None:
            ch, cw = crop
            if x.ndim < 3:
                raise ValueError(
                    "crop needs [N, H, W, ...] batches; got shape %s"
                    % (x.shape,)
                )
            h0 = (x.shape[1] - ch) // 2
            w0 = (x.shape[2] - cw) // 2
            if h0 < 0 or w0 < 0:
                raise ValueError(
                    "crop %s larger than input %s" % (crop, x.shape)
                )
            x = x[:, h0:h0 + ch, w0:w0 + cw]
        if flip and rng is not None:
            if x.ndim < 3:
                raise ValueError(
                    "flip needs [N, H, W, ...] batches; got shape %s"
                    % (x.shape,)
                )
            coin = jax.random.bernoulli(rng, 0.5, (x.shape[0],))
            shape = (x.shape[0],) + (1,) * (x.ndim - 1)
            x = jnp.where(coin.reshape(shape), jnp.flip(x, axis=2), x)
        return x

    def _selected(key, value):
        if columns is not None:
            return key in columns
        return _is_narrow(value)

    def preprocess(batch, rng=None):
        if isinstance(batch, dict):
            return {
                k: _one(v, rng) if _selected(k, v) else v
                for k, v in batch.items()
            }
        if isinstance(batch, (tuple, list)):
            return tuple(
                _one(v, rng) if _selected(i, v) else v
                for i, v in enumerate(batch)
            )
        return _one(batch, rng)

    if flip:
        return preprocess

    # deterministic graph: expose a single-arg signature so rng-probing
    # wiring (SyncTrainer's takes_rng) never forks its step-rng chain
    # for a preprocess that cannot consume one
    def deterministic(batch):
        return preprocess(batch, None)

    return deterministic


def resolve_preprocess(spec):
    """Normalize a preprocess argument: a callable passes through, a
    dict becomes ``make_preprocess(**spec)`` (the form serving-export
    metadata and pipeline params carry — JSON-serializable), ``None``
    stays ``None``."""
    if spec is None or callable(spec):
        return spec
    if isinstance(spec, dict):
        return make_preprocess(**spec)
    raise TypeError(
        "preprocess must be a callable or a make_preprocess kwargs "
        "dict, got {0!r}".format(type(spec))
    )


def takes_rng(fn):
    """True when ``fn`` accepts a second (rng) argument — the contract
    probe the train-step wiring uses to decide whether to split its
    step rng for augmentation."""
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    params = [
        p for p in sig.parameters.values()
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    if any(
        p.kind == p.VAR_POSITIONAL for p in sig.parameters.values()
    ):
        return True
    return len(params) >= 2 or "rng" in sig.parameters
