"""``tf.train.Example`` wire-format codec — no tensorflow dependency.

The reference encoded/decoded Examples with TensorFlow's generated
protos (dfutil.py:84-131,171-212; DFUtil.scala:119-184).  This module
implements the protobuf wire format for the tiny Example schema by
hand, so the interchange layer stands alone:

    Example   { Features features = 1; }
    Features  { map<string, Feature> feature = 1; }
    Feature   { oneof { BytesList=1; FloatList=2; Int64List=3; } }
    BytesList { repeated bytes value = 1; }
    FloatList { repeated float value = 1 [packed]; }
    Int64List { repeated int64 value = 1 [packed]; }

Output is byte-compatible with TF's encoder (validated against
tf.train.Example in tests when tensorflow is importable).  Packed and
unpacked repeated scalars are both accepted on decode.
"""

import struct

import numpy as np

_BYTES, _FLOAT, _INT64 = 1, 2, 3


# ----------------------------------------------------------------------
# varint / wire primitives
# ----------------------------------------------------------------------


def _write_varint(buf, value):
    if value < 0:
        value &= (1 << 64) - 1  # two's complement, 10 bytes
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _read_varint(data, pos):
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("malformed varint")


def _signed64(value):
    if value >= 1 << 63:
        value -= 1 << 64
    return value


def _tag(field, wire):
    return (field << 3) | wire


def _write_len_delimited(buf, field, payload):
    _write_varint(buf, _tag(field, 2))
    _write_varint(buf, len(payload))
    buf.extend(payload)


# ----------------------------------------------------------------------
# encode
# ----------------------------------------------------------------------


def _encode_feature(kind, values):
    inner = bytearray()
    if kind == _BYTES:
        for v in values:
            _write_len_delimited(inner, 1, bytes(v))
    elif kind == _FLOAT:
        packed = np.asarray(values, dtype="<f4").tobytes()
        # TF omits an empty packed field entirely (byte-compatibility)
        if packed:
            _write_len_delimited(inner, 1, packed)
    elif kind == _INT64:
        packed = bytearray()
        for v in values:
            _write_varint(packed, int(v))
        if packed:
            _write_len_delimited(inner, 1, packed)
    else:
        raise ValueError("unknown feature kind {0}".format(kind))
    feat = bytearray()
    _write_len_delimited(feat, kind, inner)
    return feat


def encode_example(features):
    """Encode ``{name: (kind, values)}`` or ``{name: values}`` (kind
    inferred from the python/numpy types) into Example bytes."""
    feats = bytearray()
    # deterministic order → reproducible bytes (dict order suffices for
    # round-trips; sorting makes files diffable)
    for name in sorted(features):
        spec = features[name]
        if isinstance(spec, tuple) and len(spec) == 2 and spec[0] in (
            _BYTES, _FLOAT, _INT64,
        ):
            kind, values = spec
        else:
            kind, values = infer_kind(spec)
        entry = bytearray()
        _write_len_delimited(entry, 1, name.encode("utf-8"))
        _write_len_delimited(entry, 2, _encode_feature(kind, values))
        _write_len_delimited(feats, 1, entry)
    out = bytearray()
    _write_len_delimited(out, 1, feats)
    return bytes(out)


def infer_kind(values):
    """Map python/numpy values to a (kind, list) pair, following the
    reference's dtype table (dfutil.py:84-131): floats→FloatList,
    ints/bools→Int64List, str/bytes/bytearray→BytesList."""
    arr = values
    if isinstance(arr, (bytes, bytearray)):
        return _BYTES, [bytes(arr)]
    if isinstance(arr, str):
        return _BYTES, [arr.encode("utf-8")]
    if isinstance(arr, np.ndarray):
        if arr.dtype.kind == "f":
            return _FLOAT, arr.ravel().tolist()
        if arr.dtype.kind in ("i", "u", "b"):
            return _INT64, arr.ravel().astype(np.int64).tolist()
        if arr.dtype.kind in ("S", "O", "U"):
            return _BYTES, [
                v.encode("utf-8") if isinstance(v, str) else bytes(v)
                for v in arr.ravel().tolist()
            ]
        raise TypeError("unsupported array dtype {0}".format(arr.dtype))
    if not isinstance(arr, (list, tuple)):
        arr = [arr]
    if not arr:
        return _INT64, []
    first = arr[0]
    if isinstance(first, bool):
        return _INT64, [int(v) for v in arr]
    if isinstance(first, (int, np.integer)):
        return _INT64, [int(v) for v in arr]
    if isinstance(first, (float, np.floating)):
        return _FLOAT, [float(v) for v in arr]
    if isinstance(first, str):
        return _BYTES, [v.encode("utf-8") for v in arr]
    if isinstance(first, (bytes, bytearray)):
        return _BYTES, [bytes(v) for v in arr]
    raise TypeError("unsupported feature value type {0}".format(type(first)))


# ----------------------------------------------------------------------
# decode
# ----------------------------------------------------------------------


def _decode_list(kind, data):
    values = []
    pos, end = 0, len(data)
    while pos < end:
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if field != 1:
            pos = _skip(data, pos, wire)
            continue
        if kind == _BYTES:
            n, pos = _read_varint(data, pos)
            values.append(bytes(data[pos:pos + n]))
            pos += n
        elif kind == _FLOAT:
            if wire == 2:  # packed
                n, pos = _read_varint(data, pos)
                values.extend(
                    np.frombuffer(data, dtype="<f4", count=n // 4,
                                  offset=pos).tolist()
                )
                pos += n
            else:  # unpacked 32-bit
                values.append(struct.unpack_from("<f", data, pos)[0])
                pos += 4
        else:  # INT64
            if wire == 2:  # packed
                n, pos = _read_varint(data, pos)
                stop = pos + n
                while pos < stop:
                    v, pos = _read_varint(data, pos)
                    values.append(_signed64(v))
            else:
                v, pos = _read_varint(data, pos)
                values.append(_signed64(v))
    return values


def _skip(data, pos, wire):
    if wire == 0:
        _, pos = _read_varint(data, pos)
    elif wire == 1:
        pos += 8
    elif wire == 2:
        n, pos = _read_varint(data, pos)
        pos += n
    elif wire == 5:
        pos += 4
    else:
        raise ValueError("unsupported wire type {0}".format(wire))
    return pos


def _decode_feature(data):
    pos, end = 0, len(data)
    while pos < end:
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if field in (_BYTES, _FLOAT, _INT64) and wire == 2:
            n, pos = _read_varint(data, pos)
            return field, _decode_list(field, data[pos:pos + n])
        pos = _skip(data, pos, wire)
    return _INT64, []  # empty feature


def decode_example(data):
    """Decode Example bytes → ``{name: (kind, values)}``."""
    data = memoryview(bytes(data))
    out = {}
    pos, end = 0, len(data)
    while pos < end:
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == 2:  # features
            n, pos = _read_varint(data, pos)
            fend = pos + n
            while pos < fend:
                etag, pos = _read_varint(data, pos)
                if etag >> 3 != 1 or etag & 7 != 2:
                    pos = _skip(data, pos, etag & 7)
                    continue
                elen, pos = _read_varint(data, pos)
                eend = pos + elen
                name, feat = None, None
                while pos < eend:
                    ftag, pos = _read_varint(data, pos)
                    fn, fw = ftag >> 3, ftag & 7
                    if fn == 1 and fw == 2:
                        sn, pos = _read_varint(data, pos)
                        name = bytes(data[pos:pos + sn]).decode("utf-8")
                        pos += sn
                    elif fn == 2 and fw == 2:
                        vn, pos = _read_varint(data, pos)
                        feat = bytes(data[pos:pos + vn])
                        pos += vn
                    else:
                        pos = _skip(data, pos, fw)
                if name is not None:
                    out[name] = _decode_feature(feat or b"")
        else:
            pos = _skip(data, pos, wire)
    return out


KIND_BYTES, KIND_FLOAT, KIND_INT64 = _BYTES, _FLOAT, _INT64
