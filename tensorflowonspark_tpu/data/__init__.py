from tensorflowonspark_tpu.data.feed import DataFeed  # noqa: F401
