"""In-memory dataset pipeline: the tf.data role for self-read input.

The reference delegated all InputMode.TENSORFLOW input handling to
``tf.data`` (shuffle/repeat/batch/map/prefetch — e.g.
examples/mnist/keras/mnist_tf_ds.py:42-47, resnet input pipelines).
This module is the JAX-native equivalent for datasets that fit in host
memory (CIFAR/MNIST-class acceptance workloads): columnar numpy arrays
with a lazy transformation chain, ending in device-resident batches via
:func:`~tensorflowonspark_tpu.data.feed.prefetch_to_device`.

    ds = (Dataset.from_tfrecords(path, {"image": ("float32", 784),
                                        "label": ("int64", 1)})
            .shard(ctx.num_workers, ctx.task_index)
            .shuffle(seed=0)
            .repeat(3)
            .batch(64)
            .map(normalize))
    for device_batch in ds.prefetch(sharding=trainer.batch_sharding()):
        state, metrics = trainer.step_on_device(state, device_batch, rng)

Each transformation returns a new Dataset (chains are cheap — arrays
are shared, not copied).  Shuffling reshuffles every epoch with a
per-epoch derived seed, like ``tf.data``'s
``shuffle(reshuffle_each_iteration=True)``.
"""

import logging

import numpy as np

logger = logging.getLogger(__name__)


class Dataset(object):
    """Columnar in-memory dataset with a lazy op chain."""

    def __init__(self, columns, ops=()):
        """``columns``: dict of equal-length numpy arrays."""
        lengths = {k: len(v) for k, v in columns.items()}
        if len(set(lengths.values())) > 1:
            raise ValueError(
                "columns must have equal lengths, got {0}".format(lengths)
            )
        self._columns = {k: np.asarray(v) for k, v in columns.items()}
        self._ops = tuple(ops)

    # -- sources -------------------------------------------------------

    @classmethod
    def from_arrays(cls, **columns):
        return cls(columns)

    @classmethod
    def from_tfrecords(cls, path, columns, shard=None):
        """Load a TFRecord file/dir through the native columnar decoder.

        Args:
          columns: ``{name: (dtype, width)}``; width-1 columns are
            squeezed to rank-1 like tf.data scalar features.
          shard: optional ``(num_shards, index)`` applied to the *file
            list* BEFORE decoding (each worker reads 1/N of the I/O —
            the reference's file-sharding pattern,
            examples/mnist/keras/mnist_tf_ds.py:42-47).  File shards
            may be uneven; prefer row-level :meth:`shard` when the data
            is small enough that decoding it all is cheap and uniform
            shard sizes matter more.
        """
        from tensorflowonspark_tpu.data import columnar, tfrecord as tfr
        from tensorflowonspark_tpu.data.interchange import _record_files

        files = _record_files(path)
        if shard is not None:
            num, idx = shard
            if not 0 <= idx < num:
                raise ValueError("shard index must be in [0, num_shards)")
            files = files[idx::num]
        records = []
        for f in files:
            records.extend(tfr.read_records(f))
        data = columnar.decode_batch(records, columns)
        out = {}
        for name, arr in data.items():
            out[name] = arr[:, 0] if arr.shape[1] == 1 else arr
        return cls(out)

    # -- transformations (lazy) ----------------------------------------

    def _with(self, op):
        return Dataset(self._columns, self._ops + (op,))

    def shard(self, num_shards, index):
        """Keep every ``num_shards``-th row starting at ``index`` (the
        per-worker split, tf.data ``shard`` role)."""
        if not 0 <= index < num_shards:
            raise ValueError("index must be in [0, num_shards)")
        cols = {k: v[index::num_shards] for k, v in self._columns.items()}
        return Dataset(cols, self._ops)

    def shuffle(self, seed=0):
        return self._with(("shuffle", seed))

    def repeat(self, epochs=1):
        """Iterate the data ``epochs`` times (``None`` = forever)."""
        return self._with(("repeat", epochs))

    def batch(self, batch_size, drop_remainder=True):
        """Emit ``{name: array[batch, ...]}`` batches.  Dropping the
        remainder keeps shapes static for XLA (the default; the
        reference's uneven-tail problems came from not doing this)."""
        return self._with(("batch", (batch_size, drop_remainder)))

    def map(self, fn):
        """Apply ``fn(batch_dict) -> batch_dict`` to each batch (after
        ``batch``) or ``fn(row_dict_of_scalars)`` is NOT supported —
        map operates on batches, where vectorized numpy work belongs."""
        return self._with(("map", fn))

    # -- execution -----------------------------------------------------

    @property
    def num_rows(self):
        return len(next(iter(self._columns.values()))) if self._columns else 0

    def __iter__(self):
        shuffle_seed = None
        epochs = 1
        batch_spec = None
        maps = []
        for op, arg in self._ops:
            if op == "shuffle":
                shuffle_seed = arg
            elif op == "repeat":
                epochs = arg
            elif op == "batch":
                batch_spec = arg
            elif op == "map":
                maps.append(arg)
        if batch_spec is None:
            raise ValueError("call .batch(n) before iterating")
        batch_size, drop_remainder = batch_spec
        n = self.num_rows
        if n == 0 or (drop_remainder and n < batch_size):
            # zero batches per epoch: with repeat(None) the epoch loop
            # would spin forever yielding nothing
            raise ValueError(
                "dataset has {0} rows — fewer than one batch of {1}; {2}".format(
                    n,
                    batch_size,
                    "add data"
                    if n == 0
                    else "reduce batch_size or disable drop_remainder",
                )
            )
        epoch = 0
        while epochs is None or epoch < epochs:
            if shuffle_seed is not None:
                perm = np.random.RandomState(
                    (shuffle_seed + epoch) & 0x7FFFFFFF
                ).permutation(n)
            else:
                perm = None
            end = (n // batch_size) * batch_size if drop_remainder else n
            for lo in range(0, end, batch_size):
                idx = (
                    perm[lo : lo + batch_size]
                    if perm is not None
                    else slice(lo, lo + batch_size)
                )
                batch = {k: v[idx] for k, v in self._columns.items()}
                for fn in maps:
                    batch = fn(batch)
                yield batch
            epoch += 1

    def prefetch(self, size=2, sharding=None):
        """Iterate with device placement pipelined ``size`` batches deep
        (see :func:`~tensorflowonspark_tpu.data.feed.prefetch_to_device`)."""
        from tensorflowonspark_tpu.data.feed import prefetch_to_device

        return prefetch_to_device(iter(self), size=size, sharding=sharding)

    def steps_per_epoch(self, batch_size=None):
        """Full batches per epoch (uses the chained batch size when
        ``batch_size`` is None)."""
        if batch_size is None:
            for op, arg in self._ops:
                if op == "batch":
                    batch_size = arg[0]
        if not batch_size:
            raise ValueError("no batch size chained or given")
        return self.num_rows // batch_size
