"""DataFeed: the compute-process side of the executor data plane.

Re-designed from the reference's ``TFNode.DataFeed`` (reference:
tensorflowonspark/TFNode.py:221-329).  Semantics preserved:

- ``next_batch(batch_size)`` blocks on the input queue and returns up to
  ``batch_size`` items; a ``None`` sentinel means end-of-feed
  (reference: TFNode.py:243-288), an ``EndPartition`` marker truncates
  the batch at a partition boundary (reference: TFNode.py:268-274).
- With ``input_mapping``, batches come back as a dict of named columns
  (reference: TFNode.py:276-288) — the natural layout for feeding a JAX
  step function.
- ``batch_results`` pushes inference results to the output queue
  (reference: TFNode.py:294-305).
- ``terminate`` sets the node state to ``'terminating'`` and drains the
  input queue so blocked feeders are released
  (reference: TFNode.py:307-329).

TPU-native additions (no reference analogue — SURVEY.md §7 step 3):

- ``batches(...)`` generator with numpy stacking, padding of the final
  short batch, and optional device placement,
- ``prefetch_to_device`` double-buffering so host→HBM transfer of batch
  N+1 overlaps compute on batch N (the InputMode.SPARK → HBM path).
"""

import logging
import queue as queue_mod

import numpy as np

from tensorflowonspark_tpu.cluster.marker import (
    Block,
    ColumnarBlock,
    EndPartition,
    decode_columnar_record,
    pack_columnar,
)


def _decode_ring_record(rec):
    """Decode one ring record to a PENDING element — a row list or a
    :class:`ColumnarBlock` (the two shapes ``_set_pending`` consumers
    index into).  Records are either the zero-pickle columnar wire
    format (magic-prefixed; decoded as zero-copy views over ``rec``) or
    a pickled Block/row-list fallback — a pickled ``Block`` must be
    unwrapped to its rows here (the queue path unwraps in the fetch
    loop; a raw Block is not subscriptable).  A zero-length record (the
    ring supports them) is an empty row list — ``pickle.loads(b"")``
    would raise EOFError."""
    if not rec:
        return []
    block = decode_columnar_record(rec)
    if block is not None:
        return block
    import pickle

    obj = pickle.loads(rec)
    return obj.items if isinstance(obj, Block) else obj

logger = logging.getLogger(__name__)


class DataFeed(object):
    """Consumes feed items from the executor queue manager inside the
    compute process (reference: TFNode.py:221)."""

    def __init__(
        self,
        mgr,
        train_mode=True,
        qname_in="input",
        qname_out="output",
        input_mapping=None,
    ):
        self.mgr = mgr
        self.train_mode = train_mode
        self.qname_in = qname_in
        self.qname_out = qname_out
        self.done_feeding = False
        # Sorted column order matches the driver's df.select(sorted(cols))
        # convention (reference: TFNode.py:239-241, pipeline.py:411-413).
        self.input_tensors = (
            sorted(input_mapping.keys()) if input_mapping is not None else None
        )
        #: rows unwrapped from a Block but not yet consumed by a batch
        self._pending = []
        self._pending_pos = 0
        #: queue proxies are cached: creating one is a full manager
        #: round trip (~100ms) and next_batch used to pay it per call
        self._qin = None
        self._qout = None
        #: shm feed ring (TFOS_SHM_FEED): attached lazily from the
        #: manager kv; None = queue-only feeding
        self._ring = None
        self._ring_checked = False
        self._ring_producer_warned = False  # one log line per death
        #: which source produced the last item ("ring" | "queue") —
        #: next_batch blocks on the hot source, polls the other
        self._hot_source = "ring"
        #: wire accounting (docs/data_plane.md): bytes/records/rows
        #: received over the feed plane.  Ring records count their
        #: exact wire length; queue blocks count their column/row
        #: payload bytes (pickle framing excluded — the payload is
        #: what dtype narrowing shrinks, and the number is comparable
        #: across transports).
        self.wire_bytes = 0
        self.wire_records = 0
        self.wire_rows = 0
        # fleet telemetry twins of the wire accounting (null
        # singletons when TFOS_TELEMETRY=0): the same numbers
        # wire_stats() reports, published into the process registry so
        # the driver's fleet view carries feed-plane throughput
        from tensorflowonspark_tpu import telemetry

        reg = telemetry.get_registry()
        self._m_bytes = reg.counter("feed.wire_bytes")
        self._m_records = reg.counter("feed.wire_records")
        self._m_rows = reg.counter("feed.wire_rows")

    _RING_SENTINEL = object()  # internal: ring produced a block

    def _account(self, nbytes, nrows):
        self.wire_bytes += int(nbytes)
        self.wire_records += 1
        self.wire_rows += int(nrows)
        self._m_bytes.inc(int(nbytes))
        self._m_records.inc()
        self._m_rows.inc(int(nrows))

    def _account_item(self, item):
        """Wire accounting for a queue-delivered element (Block /
        ColumnarBlock / bare row): payload bytes + row count."""
        if isinstance(item, ColumnarBlock):
            self._account(_columns_nbytes(item.columns), item.count)
        elif isinstance(item, Block):
            self._account(
                sum(_row_nbytes(r) for r in item.items), len(item.items)
            )
        else:
            self._account(_row_nbytes(item), 1)

    def wire_stats(self):
        """Cumulative feed-plane wire accounting: ``wire_bytes`` (ring
        records at exact wire length, queue blocks at payload bytes),
        ``records``, ``rows``, and derived ``bytes_per_row`` — the
        number the narrow-dtype plane shrinks (docs/data_plane.md;
        asserted >= 3x smaller for uint8-vs-float32 image columns in
        tests/test_dataplane.py)."""
        return {
            "wire_bytes": self.wire_bytes,
            "records": self.wire_records,
            "rows": self.wire_rows,
            "bytes_per_row": (
                self.wire_bytes / self.wire_rows if self.wire_rows else 0.0
            ),
        }

    def _fetch(self):
        """Block until the next feed element arrives; returns it.

        Ring elements are installed as pending directly and signalled
        with ``_RING_SENTINEL``; queue elements (rows, Blocks, markers,
        the ``None`` end-of-feed sentinel) are returned raw with
        ``task_done`` left to the caller's handling here.
        """
        if self._qin is None:
            self._qin = self.mgr.get_queue(self.qname_in)
        queue_in = self._qin
        if not self._ring_checked:
            self._attach_ring()
        while True:
            if self._ring is not None:
                # shm fast path: rows usually arrive through the ring,
                # but control sentinels (None / EndPartition) and
                # fallback Blocks (oversized rows, inference feeds) come
                # via the queue.  Poll both, blocking on whichever
                # produced LAST (the hot source) so either path runs at
                # full rate; switching sources costs one 50ms miss.  (A
                # fixed non-blocking queue poll throttled to 10/s capped
                # queue-fed rows at ~2.5k rows/s — the ADVICE.md r1
                # finding; blocking on the wrong source starved the
                # other.)
                if self._hot_source == "queue":
                    try:
                        return queue_in.get(block=True, timeout=0.05)
                    except queue_mod.Empty:
                        rec = self._ring_pop(0)
                        if rec is None:
                            continue
                        self._hot_source = "ring"
                        self._install_ring_record(rec)
                        return self._RING_SENTINEL
                else:
                    rec = self._ring_pop(0.05)
                    if rec is not None:
                        self._install_ring_record(rec)
                        return self._RING_SENTINEL
                    try:
                        item = queue_in.get(block=False)
                        self._hot_source = "queue"
                        return item
                    except queue_mod.Empty:
                        continue
            else:
                # Bounded block, retried: an UNbounded proxied get()
                # parks a thread inside the manager server holding the
                # queue's read lock; if this process then dies, that
                # zombie thread survives it and silently swallows the
                # next item (it only discovers the dead socket when it
                # tries to reply).  A 1s bound makes any zombie expire
                # within a second of the death — the supervisor's
                # queue-reset grace period relies on this constant.
                try:
                    return queue_in.get(block=True, timeout=1.0)
                except queue_mod.Empty:
                    continue

    def _install_ring_record(self, rec):
        """Decode one ring record, install it as pending, and account
        its EXACT wire length (the ring frame is the tunnel payload)."""
        self._set_pending(_decode_ring_record(rec))
        self._account(len(rec), self._pending_left())

    def _ring_pop(self, timeout):
        """Ring pop with producer-liveness handling: a dead feeder
        (its pid is announced in the ring header, see
        :class:`~tensorflowonspark_tpu.data.shm_ring.ShmRing`) turns
        the would-be-infinite ring wait into a logged miss — the feed
        drops to the queue path, where control sentinels and the
        cluster's heartbeat/ledger recovery (PR 1) own the failure.
        A NEW feeder for a later partition re-announces itself, which
        re-arms the ring."""
        from tensorflowonspark_tpu.data import shm_ring

        try:
            return self._ring.pop(timeout=timeout)
        except shm_ring.ProducerDiedError as e:
            if not self._ring_producer_warned:
                self._ring_producer_warned = True
                logger.warning("%s; falling back to the queue path", e)
            return None

    def _set_pending(self, obj):
        """Install a ring/queue block as the pending element (a row list
        or a :class:`ColumnarBlock`)."""
        self._pending = obj
        self._pending_pos = 0

    def _pending_left(self):
        n = (
            self._pending.count
            if isinstance(self._pending, ColumnarBlock)
            else len(self._pending)
        )
        return n - self._pending_pos

    def _pending_rows(self):
        """Row-objects view of the pending element (converts a columnar
        block ONCE — the row-mode compat path)."""
        if isinstance(self._pending, ColumnarBlock):
            self._pending = self._pending.rows()
        return self._pending

    def next_batch(self, batch_size):
        """Gets a batch of items from the input queue.

        Blocks until items are available (or the ``None`` end-of-feed
        sentinel is seen).  Returns a list of items, or — when
        ``input_mapping`` was provided — a dict of named column lists
        (reference: TFNode.py:243-288).  Training loops should prefer
        :meth:`next_arrays`, which consumes columnar blocks with zero
        per-row Python.
        """
        queue_in = None
        tensors = [] if self.input_tensors is None else {
            tensor: [] for tensor in self.input_tensors
        }
        count = 0

        def _consume(item):
            if self.input_tensors is None:
                tensors.append(item)
            else:
                for i, tensor in enumerate(self.input_tensors):
                    tensors[tensor].append(item[i])

        while count < batch_size:
            if self._pending_left() > 0:
                rows = self._pending_rows()
                _consume(rows[self._pending_pos])
                self._pending_pos += 1
                count += 1
                continue
            if self.done_feeding:
                # calls after end-of-feed return what's left instead of
                # blocking on a drained queue (reference: TFNode.py:258
                # loops `while not done_feeding`)
                break
            item = self._fetch()
            if item is self._RING_SENTINEL:
                continue  # pending installed by _fetch
            queue_in = self._qin
            if item is None:
                # End-of-feed: mark done and stop (reference: TFNode.py:265-268)
                queue_in.task_done()
                self.done_feeding = True
                break
            elif isinstance(item, (Block, ColumnarBlock)):
                self._set_pending(
                    item.items if isinstance(item, Block) else item
                )
                self._account_item(item)
                queue_in.task_done()
            elif isinstance(item, EndPartition):
                # Truncate the batch at a partition boundary
                # (reference: TFNode.py:268-274)
                queue_in.task_done()
                if count > 0:
                    break
            else:
                _consume(item)
                self._account_item(item)
                count += 1
                queue_in.task_done()
        logger.debug("next_batch() returning %d items", count)
        return tensors

    def next_arrays(self, batch_size):
        """Columnar fast path: a batch as stacked numpy columns.

        Consumes :class:`ColumnarBlock` elements by SLICING — no
        per-row Python objects anywhere (the Spark→HBM staging layout;
        row Blocks interleaved in the stream are stacked as a fallback).

        Returns ``(columns, count)`` where ``columns`` is a tuple of
        arrays (tuple/field rows), a dict of arrays (dict rows or
        ``input_mapping``), or a single array (scalar rows); ``count``
        is the number of rows (< ``batch_size`` at a partition
        boundary; 0 with ``columns=None`` at end-of-feed).
        """
        pieces = []  # per-fragment column sets
        count = 0
        scalar = False
        while count < batch_size:
            left = self._pending_left()
            if left == 0 and self.done_feeding:
                break  # post-end-of-feed calls must not block
            if left > 0:
                if isinstance(self._pending, ColumnarBlock):
                    take = min(batch_size - count, left)
                    pos = self._pending_pos
                    cols = self._pending.columns
                    sl = (
                        {
                            k: v[pos : pos + take]
                            for k, v in cols.items()
                        }
                        if isinstance(cols, dict)
                        else tuple(c[pos : pos + take] for c in cols)
                    )
                    scalar = scalar or self._pending._scalar
                    pieces.append(sl)
                    self._pending_pos += take
                    count += take
                else:
                    # row fallback: stack the pending rows into columns
                    take = min(batch_size - count, left)
                    rows = self._pending[
                        self._pending_pos : self._pending_pos + take
                    ]
                    blk = pack_columnar(rows)
                    if blk is None:
                        raise TypeError(
                            "next_arrays() requires fixed-shape numeric "
                            "rows; use next_batch() for object rows"
                        )
                    scalar = scalar or blk._scalar
                    pieces.append(blk.columns)
                    self._pending_pos += take
                    count += take
                continue
            item = self._fetch()
            if item is self._RING_SENTINEL:
                continue
            queue_in = self._qin
            if item is None:
                queue_in.task_done()
                self.done_feeding = True
                break
            elif isinstance(item, ColumnarBlock):
                self._set_pending(item)
                self._account_item(item)
                queue_in.task_done()
            elif isinstance(item, Block):
                self._set_pending(item.items)
                self._account_item(item)
                queue_in.task_done()
            elif isinstance(item, EndPartition):
                queue_in.task_done()
                if count > 0:
                    break
            else:
                self._set_pending([item])
                self._account_item(item)
                queue_in.task_done()
        if count == 0:
            return None, 0
        cols = _concat_pieces(pieces)
        if self.input_tensors is not None:
            if isinstance(cols, dict):
                # dict rows: select + order by the mapping's sorted keys
                # (mirrors next_batch's sorted-column contract)
                cols = {k: cols[k] for k in self.input_tensors}
            else:
                seq = (cols,) if not isinstance(cols, tuple) else cols
                cols = dict(zip(self.input_tensors, seq))
        elif scalar and isinstance(cols, tuple) and len(cols) == 1:
            cols = cols[0]
        logger.debug("next_arrays() returning %d rows", count)
        return cols, count

    def _attach_ring(self):
        """Attach the node's shm feed ring if the runtime advertised one
        (TFOS_SHM_FEED; see cluster/node.py and data/shm_ring.py)."""
        self._ring_checked = True
        try:
            info = self.mgr.get("shm_ring")._getvalue()
        except Exception:  # noqa: BLE001 - kv read is best effort
            info = None
        if info:
            from tensorflowonspark_tpu.data import shm_ring

            ring = shm_ring.ShmRing(info["name"])
            # wire-format negotiation: the segment header tags the
            # record encoding its producer writes; a tag this build
            # doesn't know means frames would MIS-decode — stay on the
            # queue path (correct, just slower) instead
            tag = ring.format_tag()
            if tag not in shm_ring.KNOWN_FORMATS:
                logger.warning(
                    "shm ring %s carries unknown wire-format tag %d "
                    "(this build knows %s); staying on the queue path",
                    info["name"], tag, shm_ring.KNOWN_FORMATS,
                )
                ring.close(unlink=False)
                return
            self._ring = ring
            logger.info(
                "consuming from shm feed ring %s (wire format %d)",
                info["name"], tag,
            )

    def should_stop(self):
        """True once the feeder posted the end-of-feed sentinel
        (reference: TFNode.py:290-292)."""
        return self.done_feeding

    def commit_partitions(self):
        """Promote every *delivered* feed partition to *committed* in
        this node's :class:`~tensorflowonspark_tpu.cluster.manager.PartitionLedger`.

        Call immediately AFTER a checkpoint save has been made durable
        (``Checkpointer.save(..., wait=True)`` or
        ``wait_until_finished()``): a committed partition is one the
        elastic restart path will never requeue, so committing before
        durability would turn a crash into silent data loss.  The
        ``train_on_feed(checkpointer=...)`` resume hook sequences this
        correctly.  Returns the number of partitions promoted (0 when
        feeding isn't elastic — the ledger is simply empty)."""
        try:
            return int(self.mgr.ledger("commit")._getvalue())
        except Exception:  # noqa: BLE001 - pre-ledger manager (rolling
            logger.warning(  # upgrade): requeue stays conservative
                "partition-ledger commit failed; partitions stay "
                "requeue-eligible", exc_info=True,
            )
            return 0

    def batch_results(self, results):
        """Push a batch of inference results to the output queue
        (reference: TFNode.py:294-305).  Ships the whole batch as one
        Block — one manager RPC (the feed-side optimization, mirrored)."""
        if self._qout is None:
            self._qout = self.mgr.get_queue(self.qname_out)
        self._qout.put(Block(results), block=True)

    def terminate(self):
        """Terminate data feeding early: set node state to 'terminating'
        and drain the input queue so blocked feeders are released
        (reference: TFNode.py:307-329)."""
        logger.info("terminate() invoked")
        self.mgr.set("state", "terminating")

        from tensorflowonspark_tpu.cluster import manager

        if not self._ring_checked:
            self._attach_ring()
        if self._ring is not None:
            # release feeders blocked on a full ring: keep discarding
            # until the ring stays empty (an in-flight feeder refills it
            # as space frees) — the queue-path drain's shm twin
            import time as _time

            hard_end = _time.monotonic() + 30
            idle_end = _time.monotonic() + 2
            ring_count = 0
            while _time.monotonic() < min(hard_end, idle_end):
                if self._ring_pop(0.05) is None:
                    continue
                ring_count += 1
                idle_end = _time.monotonic() + 2
            logger.info("terminate() drained %d ring blocks", ring_count)
            # release this consumer's mapping — a feed outliving its
            # cluster run must not pin the (unlinked) segment in memory
            self._ring.close(unlink=False)
            self._ring = None
        if self._qin is None:
            self._qin = self.mgr.get_queue(self.qname_in)
        count = manager.drain(self._qin, timeout=5)
        logger.info("terminate() drained %d items from input queue", count)

    # ------------------------------------------------------------------
    # TPU-native batch pipeline (SURVEY.md §7 step 3)
    # ------------------------------------------------------------------

    def batches(self, batch_size, stack=True, pad_to_batch=False):
        """Generator of batches until end-of-feed.

        The JAX analogue of the reference examples' ``rdd_generator`` →
        ``tf.data.Dataset.from_generator`` idiom (reference:
        examples/mnist/keras/mnist_spark.py:33-47), folded into the
        framework so user code shrinks.

        Args:
          batch_size: items per batch.
          stack: stack each column into a single ``np.ndarray``.
          pad_to_batch: zero-pad the final short batch to ``batch_size``
            (static shapes keep XLA from recompiling the jitted step);
            yields ``(batch, n_valid)`` tuples when set.
        """
        while not self.should_stop():
            batch = self.next_batch(batch_size)
            n = _batch_len(batch)
            if n == 0:
                continue
            if stack:
                batch = _stack_batch(batch)
            if pad_to_batch:
                if n < batch_size:
                    batch = _pad_batch(batch, batch_size)
                yield batch, n
            else:
                yield batch


def _columns_nbytes(cols):
    vals = cols.values() if isinstance(cols, dict) else cols
    return sum(getattr(np.asarray(v), "nbytes", 0) for v in vals)


def _row_nbytes(row):
    """Cheap payload-byte estimate of one row object (arrays exact,
    bytes/str by length, everything else 8 — scalars and refs)."""
    vals = (
        row.values() if isinstance(row, dict)
        else row if isinstance(row, (tuple, list))
        else (row,)
    )
    total = 0
    try:
        for v in vals:
            n = getattr(v, "nbytes", None)
            if n is None:
                n = len(v) if isinstance(v, (bytes, str)) else 8
            total += n
    except TypeError:
        return 0
    return total


def _concat_pieces(pieces):
    """Join per-fragment column sets (single fragment: no copy)."""
    first = pieces[0]
    if len(pieces) == 1:
        return first
    if isinstance(first, dict):
        return {
            k: np.concatenate([p[k] for p in pieces]) for k in first
        }
    return tuple(
        np.concatenate([p[i] for p in pieces]) for i in range(len(first))
    )


def _batch_len(batch):
    if isinstance(batch, dict):
        return len(next(iter(batch.values()))) if batch else 0
    return len(batch)


def _stack_batch(batch):
    """Rows → columnar numpy arrays (host-side, ready for device_put).

    Fast path: homogeneous row lists stack in ONE ``np.asarray`` —
    the old ``np.stack([np.asarray(r) for r in batch])`` materialized
    every row twice (per-row array + the stacked copy).  Ragged or
    object rows fall back to the per-row path (whose ``np.stack``
    raises the same shape error it always did)."""
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    try:
        arr = np.asarray(batch)
    except ValueError:
        arr = None  # ragged rows: modern numpy refuses the single pass
    if arr is not None and arr.dtype != object:
        return arr
    rows = [np.asarray(r) for r in batch]
    return np.stack(rows)


def _pad_batch(batch, batch_size):
    def pad(a):
        n = batch_size - a.shape[0]
        if n <= 0:
            return a
        widths = [(0, n)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths)

    if isinstance(batch, dict):
        return {k: pad(v) for k, v in batch.items()}
    return pad(batch)


def prefetch_to_device(
    iterator, size=2, sharding=None, preprocess=None, host_prefetch=False
):
    """Double-buffered host→device transfer.

    Keeps ``size`` batches in flight: batch N+1's ``jax.device_put`` (an
    async HBM DMA on TPU) overlaps the compute consuming batch N —
    the zero-copy staging the reference's JoinableQueue feed path lacks
    (SURVEY.md §7 'Hard parts: feed-path throughput').

    Args:
      iterator: yields pytrees of numpy arrays (or ``(batch, n)`` tuples
        from ``batches(pad_to_batch=True)`` — the batch is device-put,
        the valid-row count ``n`` STAYS a host int: shipping it to HBM
        made every consumer that reads the count pay a device→host sync
        per batch).
      size: number of in-flight device batches (>= 1).
      sharding: optional ``jax.sharding.Sharding`` for multi-chip
        placement of each batch (data-parallel feeding).
      preprocess: optional on-device preprocess — a callable or a
        :func:`~tensorflowonspark_tpu.data.preprocess.make_preprocess`
        kwargs dict — jitted and applied AFTER the ``device_put``, so
        narrow wire dtypes (uint8 pixels) cross the host→HBM link
        narrow and widen in HBM (docs/data_plane.md).  Deterministic
        only here (no rng); use ``SyncTrainer(device_preprocess=...)``
        for rng-bearing augmentation fused into the train step.
      host_prefetch: run the ITERATOR (host-side decode/stacking) plus
        the ``device_put`` dispatch on a background thread with a
        bounded ``size``-deep buffer, so host decode of batch N+1
        overlaps compute on batch N — the last stage of the
        decode→ring→device pipeline.  Order is preserved; iterator
        exceptions re-raise in the consumer.
    """
    import collections

    import jax

    if size < 1:
        raise ValueError(
            "prefetch_to_device size must be >= 1, got {0}".format(size)
        )

    pre = None
    if preprocess is not None:
        from tensorflowonspark_tpu.data import preprocess as pp_mod

        pre = jax.jit(pp_mod.resolve_preprocess(preprocess))

    def put_tree(tree):
        if sharding is not None:
            tree = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding), tree
            )
        else:
            tree = jax.tree_util.tree_map(jax.device_put, tree)
        return pre(tree) if pre is not None else tree

    def put(item):
        # (batch, n) from pad_to_batch: only the batch goes to device;
        # the host-side row count must never become a device scalar
        if (
            isinstance(item, tuple)
            and len(item) == 2
            and isinstance(item[1], (int, np.integer))
        ):
            return (put_tree(item[0]), int(item[1]))
        return put_tree(item)

    if host_prefetch:
        return _host_prefetch_gen(iterator, put, size)

    def _sync_gen():
        q = collections.deque()
        for item in iterator:
            q.append(put(item))
            if len(q) >= size:
                yield q.popleft()
        while q:
            yield q.popleft()

    return _sync_gen()


def _host_prefetch_gen(iterator, put, size):
    """Background-thread variant of prefetch_to_device: the worker
    drains the iterator and dispatches ``device_put`` into a bounded
    queue; the consumer generator yields in order.  The worker is a
    daemon and honors a stop flag, so abandoning the generator (or the
    consumer erroring out) cannot deadlock on a full buffer."""
    import queue as _q
    import threading

    out_q = _q.Queue(maxsize=size)
    stop = threading.Event()

    def worker():
        try:
            for item in iterator:
                msg = ("ok", put(item))
                while not stop.is_set():
                    try:
                        out_q.put(msg, timeout=0.1)
                        break
                    except _q.Full:
                        continue
                if stop.is_set():
                    return
            msg = ("end", None)
        except BaseException as e:  # noqa: BLE001 - forwarded to consumer
            msg = ("err", e)
        while not stop.is_set():
            try:
                out_q.put(msg, timeout=0.1)
                return
            except _q.Full:
                continue

    t = threading.Thread(
        target=worker, daemon=True, name="prefetch-host"
    )
    t.start()

    def gen():
        try:
            while True:
                kind, payload = out_q.get()
                if kind == "end":
                    return
                if kind == "err":
                    raise payload
                yield payload
        finally:
            stop.set()

    return gen()
