"""Shared-memory ring: zero-RPC staging between feeder and compute.

The manager-queue data plane costs one proxy RPC (connect, pickle,
third-process hop) per Block; this ring (native/shm_ring.cc, a
lock-free SPSC byte ring in a ``multiprocessing.shared_memory``
segment) moves a record with two memcpys and no intermediary — the
"C++ ring buffer" half of SURVEY.md §7's feed-throughput prescription
(the "async device_put" half is
:func:`tensorflowonspark_tpu.data.feed.prefetch_to_device`).

Used as the opt-in train-feed fast path (``TFOS_SHM_FEED=1``): the node
runtime creates a ring per worker, advertises its name through the
manager kv, feeders push pickled row-Blocks, and ``DataFeed`` drains
the ring before consulting the queue (control sentinels — ``None`` /
EndPartition — always travel via the queue).

No pure-Python fallback: callers check :func:`available` and stay on
the queue path when the native lib is missing.
"""

import atexit
import ctypes
import gc
import logging
import os
import struct
import time
import weakref
from multiprocessing import shared_memory

from tensorflowonspark_tpu.data import _native

logger = logging.getLogger(__name__)

_LIB_NAME = "libshm_ring.so"

DEFAULT_CAPACITY = 64 * 1024 * 1024

#: byte offset of the producer-pid slot inside the 64-byte native
#: header: magic(8) + capacity(8) + head(8) + tail(8) = 32, then the
#: header's reserved pad region — the native code never reads it, so
#: the python layer owns it.  A zero pid means "no producer announced"
#: (SharedMemory segments are created zero-filled).
_PRODUCER_PID_OFFSET = 32

#: seconds between producer-liveness probes while a pop waits on an
#: empty ring (one os.kill(pid, 0) per interval — negligible)
_LIVENESS_INTERVAL = 0.2

#: record wire-format tags (the native header's format_tag field):
#: producers tag the segment with the encoding their records use;
#: consumers verify at attach and refuse tags they don't understand
#: instead of mis-decoding frames.  0 = legacy (pickled blocks only);
#: 1 = dtype-tagged columnar wire records (cluster/marker.py
#: COLUMNAR_MAGIC format — self-describing per-column dtypes, the
#: narrow-dtype plane) with pickle fallback.
FORMAT_LEGACY = 0
FORMAT_COLUMNAR_V1 = 1
#: tags this build knows how to decode
KNOWN_FORMATS = (FORMAT_LEGACY, FORMAT_COLUMNAR_V1)


class ProducerDiedError(RuntimeError):
    """The ring's announced producer process died with the ring empty:
    no more records are coming and a blocking consumer would otherwise
    wait out its full feed timeout (or forever, in a retry loop).
    Names the segment and the dead pid."""

#: live rings; at interpreter exit their ctypes buffer pins are dropped
#: BEFORE SharedMemory.__del__ runs, so its close() doesn't raise
#: BufferError into stderr
_INSTANCES = weakref.WeakSet()


@atexit.register
def _release_pins():
    for ring in list(_INSTANCES):
        ring._cbase = None
    gc.collect()


def _configure(lib):
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.shmring_init.restype = ctypes.c_int64
    lib.shmring_init.argtypes = [u8p, ctypes.c_uint64]
    lib.shmring_push.restype = ctypes.c_int
    lib.shmring_push.argtypes = [u8p, ctypes.c_char_p, ctypes.c_uint64]
    lib.shmring_pushv.restype = ctypes.c_int
    lib.shmring_pushv.argtypes = [
        u8p,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
    ]
    lib.shmring_pop.restype = ctypes.c_int64
    lib.shmring_pop.argtypes = [
        u8p, u8p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.shmring_size.restype = ctypes.c_int64
    lib.shmring_size.argtypes = [u8p]
    lib.shmring_set_format.restype = ctypes.c_int
    lib.shmring_set_format.argtypes = [u8p, ctypes.c_uint32]
    lib.shmring_format.restype = ctypes.c_int64
    lib.shmring_format.argtypes = [u8p]


def _load():
    return _native.load_library(_LIB_NAME, _configure)


def available():
    return _load() is not None


class ShmRing(object):
    """SPSC byte ring over a named shared-memory segment.

    Args:
      name: segment name (``create=True`` makes it, else attaches).
      capacity: total segment bytes when creating.
    """

    def __init__(self, name, capacity=DEFAULT_CAPACITY, create=False):
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError("native shm ring unavailable (no compiler?)")
        if create:
            self.shm = shared_memory.SharedMemory(
                name=name, create=True, size=capacity
            )
        else:
            self.shm = shared_memory.SharedMemory(name=name)
        self.name = name
        self._owner = create
        #: usable data-region bytes (segment minus the 64B header)
        self.capacity = self.shm.size - 64
        self._out = ctypes.create_string_buffer(8)  # length-probe target
        # one ctypes view for the segment's lifetime: from_buffer pins
        # the exported buffer, so it must be dropped before close()
        self._cbase = (ctypes.c_uint8 * self.shm.size).from_buffer(
            self.shm.buf
        )
        if create:
            rc = self._lib.shmring_init(self._cbase, self.shm.size)
            if rc < 0:
                self.close()
                raise ValueError("segment too small: {0}".format(capacity))
        _INSTANCES.add(self)
        self._next_liveness = 0.0  # next producer probe (monotonic)
        # fleet telemetry (record granularity — records are whole
        # blocks, so this is NOT per-row overhead; null no-ops when
        # TFOS_TELEMETRY=0)
        from tensorflowonspark_tpu import telemetry

        reg = telemetry.get_registry()
        self._m_push = reg.counter("ring.push_records")
        self._m_push_bytes = reg.counter("ring.push_bytes")
        self._m_pop = reg.counter("ring.pop_records")
        self._m_pop_bytes = reg.counter("ring.pop_bytes")

    def _base(self):
        return self._cbase

    # -- wire-format negotiation ---------------------------------------

    def set_format(self, tag):
        """Tag the segment with the record wire format its producer
        writes (``FORMAT_*``); the creating side calls this once."""
        rc = self._lib.shmring_set_format(self._base(), int(tag))
        if rc == -3:
            raise RuntimeError("corrupt ring segment")

    def format_tag(self):
        """The segment's record wire-format tag (``FORMAT_LEGACY`` on
        segments from builds predating the tag — the header region is
        zero-filled at creation)."""
        tag = int(self._lib.shmring_format(self._base()))
        if tag == -3:
            raise RuntimeError("corrupt ring segment")
        return tag

    # -- producer liveness ---------------------------------------------

    def announce_producer(self, pid=None):
        """Record the producer's pid in the ring header (the native
        header's reserved pad bytes — the C++ side never reads them).
        The pushing process calls this once after attaching; a new
        producer for a later stream simply overwrites it (SPSC — one
        live producer at a time)."""
        struct.pack_into(
            "<Q", self.shm.buf, _PRODUCER_PID_OFFSET,
            int(os.getpid() if pid is None else pid),
        )

    def producer_pid(self):
        """The announced producer pid, or 0 when none announced."""
        return struct.unpack_from(
            "<Q", self.shm.buf, _PRODUCER_PID_OFFSET
        )[0]

    @staticmethod
    def _pid_alive(pid):
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True  # exists, owned by someone else
        return True

    def _producer_dead(self):
        """The announced producer's pid when that process is dead,
        else None.  Called from pop's empty-wait path, rate-limited to
        one probe per ``_LIVENESS_INTERVAL`` ACROSS calls (feed loops
        issue many short-timeout pops; per-call probe state would
        never reach the interval and miss the death)."""
        now = time.monotonic()
        if now < self._next_liveness:
            return None
        self._next_liveness = now + _LIVENESS_INTERVAL
        pid = self.producer_pid()
        if pid and not self._pid_alive(pid):
            return pid
        return None

    def push(self, record, timeout=None, error_check=None):
        """Append one byte record; blocks (spin+sleep) while full.

        ``error_check``: optional callable invoked during waits so
        feeders can keep surfacing compute errors.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        base = self._base()
        while True:
            rc = self._lib.shmring_push(base, record, len(record))
            if rc == 0:
                self._m_push.inc()
                self._m_push_bytes.inc(len(record))
                return
            if rc == -2:
                raise ValueError(
                    "record of {0} bytes exceeds {1}".format(
                        len(record),
                        "the 4GiB u32 frame limit"
                        if len(record) > (1 << 32) - 5
                        else "ring capacity",
                    )
                )
            if rc == -3:
                raise RuntimeError("corrupt ring segment")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("ring full for {0}s".format(timeout))
            if error_check is not None:
                error_check()
            time.sleep(0.001)

    def pushv(self, parts, timeout=None, error_check=None):
        """Scatter-gather push: one record from multiple buffer-protocol
        segments (header + raw numpy column buffers), copied into the
        ring WITHOUT first concatenating into an intermediate bytes —
        the zero-pickle columnar path's single feeder-side copy.
        """
        views = [memoryview(p).cast("B") for p in parts]
        n = len(views)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        # keep ctypes casts alive for the duration of the call
        holders = []
        for i, v in enumerate(views):
            c = (ctypes.c_uint8 * len(v)).from_buffer_copy(v) if v.readonly \
                else (ctypes.c_uint8 * len(v)).from_buffer(v)
            holders.append(c)
            ptrs[i] = ctypes.cast(c, ctypes.c_void_p)
            lens[i] = len(v)
        total = sum(len(v) for v in views)
        deadline = None if timeout is None else time.monotonic() + timeout
        base = self._base()
        try:
            while True:
                rc = self._lib.shmring_pushv(
                    base,
                    ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_void_p)),
                    lens,
                    n,
                )
                if rc == 0:
                    self._m_push.inc()
                    self._m_push_bytes.inc(total)
                    return
                if rc == -2:
                    raise ValueError(
                        "record of {0} bytes exceeds {1}".format(
                            total,
                            "the 4GiB u32 frame limit"
                            if total > (1 << 32) - 5
                            else "ring capacity",
                        )
                    )
                if rc == -3:
                    raise RuntimeError("corrupt ring segment")
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("ring full for {0}s".format(timeout))
                if error_check is not None:
                    error_check()
                time.sleep(0.001)
        finally:
            del holders

    def pop(self, timeout=0):
        """Pop one record into an exactly-sized buffer; returns ``None``
        when empty past ``timeout``.

        Two C calls per record — a zero-capacity probe for the length,
        then the copy straight into a fresh ``bytearray`` — so the data
        crosses ring→consumer with exactly ONE memcpy and no shared
        scratch (a scratch would need a second copy before handing the
        record out, since the next pop overwrites it)."""
        deadline = time.monotonic() + timeout
        base = self._base()
        need = ctypes.c_uint64(0)
        dead_pid = None
        while True:
            n = self._lib.shmring_pop(
                base,
                ctypes.cast(self._out, ctypes.POINTER(ctypes.c_uint8)),
                0,
                ctypes.byref(need),
            )
            if n == 0:
                self._m_pop.inc()
                return b""  # zero-length record
            if n == -2:
                buf = bytearray(int(need.value))
                carr = (ctypes.c_uint8 * len(buf)).from_buffer(buf)
                n2 = self._lib.shmring_pop(
                    base,
                    ctypes.cast(carr, ctypes.POINTER(ctypes.c_uint8)),
                    len(buf),
                    ctypes.byref(need),
                )
                del carr
                if n2 < 0:  # cannot happen for SPSC (sole consumer)
                    raise RuntimeError(
                        "ring record vanished between probe and pop"
                    )
                self._m_pop.inc()
                self._m_pop_bytes.inc(len(buf))
                return buf
            if n == -3:
                raise RuntimeError("corrupt ring segment")
            if dead_pid is not None:
                # the producer was dead on the PREVIOUS iteration and
                # this re-probe still found the ring empty: nothing
                # raced in between its last push and its death
                raise ProducerDiedError(
                    "shm ring {0!r}: producer pid {1} died with the "
                    "ring empty — no more records are coming".format(
                        self.name, dead_pid
                    )
                )
            dead_pid = self._producer_dead()
            if dead_pid is not None:
                continue  # one confirming empty re-probe, then raise
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.0005)

    def size(self):
        return int(self._lib.shmring_size(self._base()))

    def close(self, unlink=None):
        # dropping the last reference releases the from_buffer export
        # synchronously (refcount); a cycle-trapped array needs a
        # collection pass first, so retry once behind gc.collect()
        self._cbase = None
        for attempt in range(2):
            try:
                self.shm.close()
                break
            except BufferError:
                if attempt == 0:
                    gc.collect()
                    continue
                # a stray export (e.g. an in-flight ctypes call) still
                # pins the mapping; it unmaps at process exit
                logger.debug(
                    "segment %s still pinned; deferring unmap", self.name
                )
            except FileNotFoundError:
                break
        if unlink if unlink is not None else self._owner:
            try:
                self.shm.unlink()
            except FileNotFoundError:
                pass

    def __del__(self):
        # a dropped ring must not reach SharedMemory.__del__ with the
        # ctypes pin alive (member finalization order is arbitrary, so
        # shm.close() could raise BufferError into stderr) nor leak the
        # owner's segment registration; close() is idempotent
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter may be tearing down
            pass
