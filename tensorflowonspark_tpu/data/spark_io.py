"""Gated pyspark DataFrame adapter over the row interchange layer.

The reference's ``dfutil.py`` operated directly on Spark DataFrames
(reference: tensorflowonspark/dfutil.py:29-81); here the core codec is
engine-agnostic (:mod:`tensorflowonspark_tpu.data.interchange` on dict
rows) and this module is the thin Spark veneer — imported only when a
DataFrame actually shows up, so the framework never requires pyspark.
"""

import logging

logger = logging.getLogger(__name__)


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as e:  # pragma: no cover - pyspark not in test env
        raise ImportError(
            "pyspark is required for DataFrame interop; install it or "
            "pass plain dict rows instead"
        ) from e


def dataframe_to_rows(df):
    """DataFrame → list of dict rows (driver-side collect; the engine
    re-partitions for executor fan-out)."""
    _require_pyspark()
    return [row.asDict() for row in df.collect()]


def _spark_type(typ):
    from pyspark.sql import types as T

    base_map = {
        "binary": T.BinaryType(),
        "boolean": T.BooleanType(),
        "double": T.DoubleType(),
        "float": T.FloatType(),
        "int": T.IntegerType(),
        "long": T.LongType(),
        "string": T.StringType(),
        "short": T.ShortType(),
    }
    if typ.startswith("array<"):
        return T.ArrayType(base_map[typ[len("array<"):-1]])
    return base_map[typ]


def to_spark_schema(schema):
    """Interchange schema (``[(name, type)]`` or struct string) →
    ``pyspark.sql.types.StructType`` (the SimpleTypeParser role,
    reference: SimpleTypeParser.scala:36-63)."""
    _require_pyspark()
    from pyspark.sql import types as T

    from tensorflowonspark_tpu.data import interchange

    if isinstance(schema, str):
        schema = interchange.parse_schema(schema)
    return T.StructType(
        [T.StructField(name, _spark_type(typ), True) for name, typ in schema]
    )


def rows_to_dataframe(spark, rows, schema=None):
    """Dict rows → DataFrame.  ``schema`` (interchange schema list or
    struct string) carries the column *types*, so empty row sets and
    None-valued columns don't break Spark's inference."""
    _require_pyspark()
    if schema:
        spark_schema = to_spark_schema(schema)
        cols = spark_schema.fieldNames()
        data = [tuple(r.get(c) for c in cols) for r in rows]
        return spark.createDataFrame(data, schema=spark_schema)
    if not rows:
        raise ValueError(
            "cannot infer a DataFrame schema from zero rows; pass schema="
        )
    return spark.createDataFrame(rows)


def save_df_as_tfrecords(df, path, num_shards=1):
    """DataFrame → TFRecord shards via the native codec
    (reference: dfutil.py:29-41 saveAsTFRecords).  A DataFrame loaded
    by :func:`load_tfrecords_df` reuses its known schema instead of
    re-inferring types per row."""
    from tensorflowonspark_tpu.data import interchange

    return interchange.save_as_tfrecords(
        dataframe_to_rows(df),
        path,
        schema=loaded_schema(df),
        num_shards=num_shards,
    )


def load_tfrecords_df(spark, path, schema=None, binary_features=()):
    """TFRecords → DataFrame (reference: dfutil.py:44-81 loadTFRecords).
    The result is marked for :func:`is_loaded_df` provenance checks."""
    from tensorflowonspark_tpu.data import interchange

    rows, schema = interchange.load_tfrecords(
        path, schema=schema, binary_features=binary_features
    )
    df = rows_to_dataframe(spark, rows, schema)
    mark_loaded_df(df, schema)
    return df


def mark_loaded_df(df, schema):
    """Record that ``df`` originated from TFRecords (its interchange
    schema is known exactly — no re-inference needed on save)."""
    df._tfos_loaded_schema = schema
    return df


def is_loaded_df(df):
    """True when ``df`` was produced by :func:`load_tfrecords_df`
    (reference: dfutil.py:15-26 ``isLoadedDF`` provenance registry;
    here the mark rides the DataFrame object itself — the reference's
    global dict keyed by id() could alias recycled ids)."""
    return getattr(df, "_tfos_loaded_schema", None) is not None


def loaded_schema(df):
    """The interchange schema a loaded DataFrame carries, or ``None``."""
    return getattr(df, "_tfos_loaded_schema", None)
