"""TFRecord file I/O over the native C++ codec (ctypes).

Role parity with the reference's tensorflow-hadoop jar
(`TFRecordFileInputFormat/OutputFormat`, used at dfutil.py:39,63 and
DFUtil.scala:38,192): the record-level storage codec everything else
sits on.  The C++ library (native/tfrecord_codec.cc) does the framing
and slice-by-8 CRC32C; a pure-Python fallback keeps the package
importable where no compiler exists (CRC via a generated table — same
numbers, ~100x slower).

The shared lib is built lazily with ``make`` on first use and cached
next to the sources.

Remote URIs (``gs://``, ``hdfs://``, ``s3://``, ``memory://``, …) are
routed through ``fsspec`` with the pure-Python framing — the role the
reference's Hadoop jar played for HDFS (reference: dfutil.py:39,63);
the native codec keeps the local fast path.
"""

import ctypes
import logging
import os
import struct

from tensorflowonspark_tpu.data import _native
from tensorflowonspark_tpu.utils import fs as fs_utils

logger = logging.getLogger(__name__)

_LIB_NAME = "libtfrecord_codec.so"


def _load_native():
    """Load (building if needed) the codec library; None on failure."""
    return _native.load_library(_LIB_NAME, _configure)


def _configure(lib):
    lib.tfr_crc32c.restype = ctypes.c_uint32
    lib.tfr_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.tfr_masked_crc.restype = ctypes.c_uint32
    lib.tfr_masked_crc.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.tfr_writer_open.restype = ctypes.c_void_p
    lib.tfr_writer_open.argtypes = [ctypes.c_char_p]
    lib.tfr_writer_write.restype = ctypes.c_int
    lib.tfr_writer_write.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
    ]
    lib.tfr_writer_flush.argtypes = [ctypes.c_void_p]
    lib.tfr_writer_close.argtypes = [ctypes.c_void_p]
    lib.tfr_reader_open.restype = ctypes.c_void_p
    lib.tfr_reader_open.argtypes = [ctypes.c_char_p]
    lib.tfr_reader_next.restype = ctypes.c_int64
    lib.tfr_reader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
    ]
    lib.tfr_reader_error.restype = ctypes.c_char_p
    lib.tfr_reader_error.argtypes = [ctypes.c_void_p]
    lib.tfr_reader_close.argtypes = [ctypes.c_void_p]


def native_available():
    return _load_native() is not None


# ----------------------------------------------------------------------
# Pure-python fallback CRC32C (identical numbers, for no-compiler envs)
# ----------------------------------------------------------------------

_PY_TABLE = None


def _py_table():
    global _PY_TABLE
    if _PY_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (poly if crc & 1 else 0)
            table.append(crc)
        _PY_TABLE = table
    return _PY_TABLE


def crc32c(data):
    lib = _load_native()
    if lib is not None:
        return lib.tfr_crc32c(bytes(data), len(data))
    table = _py_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc(data):
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


class CorruptRecordError(IOError):
    pass


class TFRecordWriter(object):
    """Append-only TFRecord writer (context manager)."""

    def __init__(self, path):
        self.path = os.fspath(path)
        self._lib = None if fs_utils.is_remote(self.path) else _load_native()
        if self._lib is not None:
            local = fs_utils.local_path(self.path)
            self._h = self._lib.tfr_writer_open(local.encode())
            if not self._h:
                raise IOError("cannot open {0} for writing".format(path))
            self._f = None
        else:
            self._h = None
            self._f = fs_utils.open_file(self.path, "wb")

    def write(self, record):
        record = bytes(record)
        if self._h is not None:
            if self._lib.tfr_writer_write(self._h, record, len(record)):
                raise IOError("write failed on {0}".format(self.path))
        else:
            length = struct.pack("<Q", len(record))
            self._f.write(length)
            self._f.write(struct.pack("<I", masked_crc(length)))
            self._f.write(record)
            self._f.write(struct.pack("<I", masked_crc(record)))

    def flush(self):
        if self._h is not None:
            self._lib.tfr_writer_flush(self._h)
        else:
            self._f.flush()

    def close(self):
        if self._h is not None:
            self._lib.tfr_writer_close(self._h)
            self._h = None
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class TFRecordReader(object):
    """Iterates records of one TFRecord file (context manager)."""

    def __init__(self, path):
        self.path = os.fspath(path)
        self._lib = None if fs_utils.is_remote(self.path) else _load_native()
        if self._lib is not None:
            local = fs_utils.local_path(self.path)
            if not os.path.exists(local):
                # match builtin open()'s error class — callers catch
                # FileNotFoundError to fall back to synthetic data
                raise FileNotFoundError(local)
            self._h = self._lib.tfr_reader_open(local.encode())
            if not self._h:
                raise IOError("cannot open {0}".format(path))
            self._f = None
        else:
            self._h = None
            self._f = fs_utils.open_file(self.path, "rb")

    def __iter__(self):
        return self

    def __next__(self):
        if self._h is not None:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = self._lib.tfr_reader_next(self._h, ctypes.byref(out))
            if n == -1:
                raise StopIteration
            if n == -2:
                raise CorruptRecordError(
                    "{0}: {1}".format(
                        self.path,
                        self._lib.tfr_reader_error(self._h).decode(),
                    )
                )
            return ctypes.string_at(out, n)
        return self._py_next()

    def _py_next(self):
        header = self._f.read(8)
        if not header:
            raise StopIteration
        if len(header) != 8:
            raise CorruptRecordError("truncated length")
        (length,) = struct.unpack("<Q", header)
        len_crc_bytes = self._f.read(4)
        if len(len_crc_bytes) != 4:
            raise CorruptRecordError("truncated length crc")
        (len_crc,) = struct.unpack("<I", len_crc_bytes)
        if len_crc != masked_crc(header):
            raise CorruptRecordError("length crc mismatch")
        data = self._f.read(length)
        if len(data) != length:
            raise CorruptRecordError("truncated data")
        data_crc_bytes = self._f.read(4)
        if len(data_crc_bytes) != 4:
            raise CorruptRecordError("truncated data crc")
        (data_crc,) = struct.unpack("<I", data_crc_bytes)
        if data_crc != masked_crc(data):
            raise CorruptRecordError("data crc mismatch")
        return data

    def close(self):
        if self._h is not None:
            self._lib.tfr_reader_close(self._h)
            self._h = None
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path, records):
    """Write an iterable of byte records to one TFRecord file."""
    count = 0
    with TFRecordWriter(path) as w:
        for r in records:
            w.write(r)
            count += 1
    return count


def read_records(path):
    """Yield all byte records of one TFRecord file."""
    with TFRecordReader(path) as r:
        for rec in r:
            yield rec
