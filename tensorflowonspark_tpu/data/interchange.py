"""Row/column ↔ TFRecord interchange (the dfutil/DFUtil equivalent).

Re-designed from the reference's ``dfutil.py`` (Python) and
``DFUtil.scala``/``SimpleTypeParser.scala`` (JVM): save rows as
TFRecord shards of ``tf.train.Example``, load them back with schema
inference from the first record (reference: dfutil.py:44-81,134-168)
incl. the ``binary_features`` hint disambiguating bytes vs string
(reference: dfutil.py:134-168), and a ``struct<name:type,...>`` schema
string grammar (reference: SimpleTypeParser.scala:36-63).

Rows are plain dicts (the engine-agnostic representation the data
plane feeds anyway); the Spark adapter in
:mod:`tensorflowonspark_tpu.data.spark_io` maps DataFrames onto this.
"""

import logging
import re

import numpy as np

from tensorflowonspark_tpu.data import example as ex
from tensorflowonspark_tpu.data import tfrecord as tfr
from tensorflowonspark_tpu.utils import fs as fs_utils

logger = logging.getLogger(__name__)

#: scalar schema types (the SimpleTypeParser base-type set,
#: SimpleTypeParser.scala:42-55, plus the narrow-dtype plane's
#: ``byte``/``ubyte`` extension — docs/data_plane.md: an image column
#: declared ``ubyte`` ships uint8 end-to-end instead of promoting to
#: the proto's int64/float32)
SCALAR_TYPES = (
    "binary", "boolean", "double", "float", "int", "long", "string",
    "short", "byte", "ubyte",
)

#: schema base type → the numpy WIRE dtype a numeric column of that
#: type ships in (the storage dtype, not the proto kind: the proto
#: layer promotes everything to int64/float32, and the narrow plane
#: undoes that at ingest — see :func:`schema_wire_spec`)
WIRE_DTYPE_OF_BASE = {
    "boolean": "uint8",
    "ubyte": "uint8",
    "byte": "int8",
    "short": "int16",
    "int": "int32",
    "long": "int64",
    "float": "float32",
    "double": "float64",
}


# ----------------------------------------------------------------------
# schema strings:  struct<name:type,...>  with  array<base>
# ----------------------------------------------------------------------

_STRUCT_RE = re.compile(r"^\s*struct\s*<(.*)>\s*$", re.S)


def parse_schema(text):
    """Parse ``struct<a:int,b:array<float>,c:string>`` → ordered
    ``[(name, type)]`` (type is ``"base"`` or ``"array<base>"``)."""
    m = _STRUCT_RE.match(text)
    if not m:
        raise ValueError("schema must look like struct<name:type,...>: "
                         "{0!r}".format(text))
    body = m.group(1)
    fields = []
    depth, start = 0, 0
    parts = []
    for i, ch in enumerate(body):
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(body[start:i])
            start = i + 1
    parts.append(body[start:])
    for part in parts:
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError("field {0!r} missing ':'".format(part))
        name, typ = part.split(":", 1)
        fields.append((name.strip(), _check_type(typ.strip())))
    if not fields:
        raise ValueError("empty struct schema")
    return fields


def _check_type(typ):
    inner = typ
    m = re.match(r"^array\s*<(.*)>$", typ)
    if m:
        inner = m.group(1).strip()
        if inner not in SCALAR_TYPES:
            raise ValueError("unsupported array element type "
                             "{0!r}".format(inner))
        return "array<{0}>".format(inner)
    if typ not in SCALAR_TYPES:
        raise ValueError("unsupported type {0!r}".format(typ))
    return typ


def format_schema(fields):
    return "struct<{0}>".format(
        ",".join("{0}:{1}".format(n, t) for n, t in fields)
    )


# ----------------------------------------------------------------------
# schema inference from decoded examples
# ----------------------------------------------------------------------


def infer_schema(feature_dict, binary_features=()):
    """Infer ``[(name, type)]`` from one decoded example (reference:
    dfutil.py:134-168 — first record wins; single-element lists become
    scalars, longer lists arrays; bytes are string unless listed in
    ``binary_features``)."""
    fields = []
    for name in sorted(feature_dict):
        kind, values = feature_dict[name]
        if kind == ex.KIND_INT64:
            base = "long"
        elif kind == ex.KIND_FLOAT:
            base = "float"
        else:
            base = "binary" if name in binary_features else "string"
        if len(values) > 1:
            fields.append((name, "array<{0}>".format(base)))
        else:
            fields.append((name, base))
    return fields


# ----------------------------------------------------------------------
# rows <-> examples
# ----------------------------------------------------------------------

_KIND_OF_BASE = {
    "binary": ex.KIND_BYTES,
    "string": ex.KIND_BYTES,
    "boolean": ex.KIND_INT64,
    "byte": ex.KIND_INT64,
    "ubyte": ex.KIND_INT64,
    "short": ex.KIND_INT64,
    "int": ex.KIND_INT64,
    "long": ex.KIND_INT64,
    "float": ex.KIND_FLOAT,
    "double": ex.KIND_FLOAT,
}


def schema_wire_spec(schema):
    """Derive the narrow-dtype plane's per-column wire dtypes from a
    schema (docs/data_plane.md).

    ``schema`` is a ``struct<...>`` string or ``[(name, type)]``; the
    result is a :class:`~tensorflowonspark_tpu.data.columnar.WireSpec`
    over every numeric column — ``ubyte`` image columns come out
    uint8, ``short`` int16, etc. — ready for ``WireSpec.narrow`` /
    ``narrow_rows`` at the feeder, so a schema-declared storage dtype
    is honored end-to-end instead of riding the proto's int64/float32
    promotion.  String/binary columns are not wire-narrowable and are
    left out (they pass through feeds untouched)."""
    from tensorflowonspark_tpu.data import columnar

    if isinstance(schema, str):
        schema = parse_schema(schema)
    dtypes = {}
    for name, typ in schema:
        base, _ = _base_of(typ)
        if base in WIRE_DTYPE_OF_BASE:
            dtypes[name] = WIRE_DTYPE_OF_BASE[base]
    return columnar.WireSpec(dtypes)


def _base_of(typ):
    m = re.match(r"^array<(.*)>$", typ)
    return (m.group(1), True) if m else (typ, False)


def row_to_example(row, schema=None):
    """Encode one dict row.  With a schema, fields are coerced to their
    declared kinds; without, kinds are inferred per value."""
    if schema is None:
        return ex.encode_example(row)
    feats = {}
    for name, typ in schema:
        if name not in row:
            raise KeyError("row missing field {0!r}".format(name))
        base, is_array = _base_of(typ)
        kind = _KIND_OF_BASE[base]
        value = row[name]
        if not is_array and not isinstance(value, (list, tuple, np.ndarray)):
            value = [value]
        if kind == ex.KIND_BYTES:
            value = [
                v.encode("utf-8") if isinstance(v, str) else bytes(v)
                for v in value
            ]
        elif kind == ex.KIND_INT64:
            value = [int(v) for v in value]
        else:
            value = [float(v) for v in value]
        feats[name] = (kind, value)
    return ex.encode_example(feats)


def example_to_row(record, schema):
    """Decode example bytes into a dict per the schema (reference:
    dfutil.py:171-212 fromTFExample)."""
    decoded = ex.decode_example(record)
    row = {}
    for name, typ in schema:
        base, is_array = _base_of(typ)
        if name not in decoded:
            row[name] = [] if is_array else None
            continue
        kind, values = decoded[name]
        if base == "string":
            values = [
                v.decode("utf-8") if isinstance(v, bytes) else v
                for v in values
            ]
        elif base == "boolean":
            values = [bool(v) for v in values]
        elif base in ("int", "short", "byte", "ubyte"):
            values = [int(v) for v in values]
        elif base == "double":
            values = [float(v) for v in values]
        row[name] = values if is_array else (values[0] if values else None)
    return row


# ----------------------------------------------------------------------
# files
# ----------------------------------------------------------------------


def save_as_tfrecords(rows, path, schema=None, num_shards=1):
    """Write rows to ``path`` (a directory of ``part-rNNNNN`` shards —
    the Hadoop OutputFormat layout the reference produced via Spark,
    dfutil.py:29-41; remote ``scheme://`` URIs go through fsspec like
    the reference's jar went through HDFS).  Returns the number of
    records written."""
    fs_utils.makedirs(path)
    writers = [
        tfr.TFRecordWriter(
            fs_utils.join(path, "part-r-{0:05d}".format(i))
        )
        for i in range(num_shards)
    ]
    count = 0
    try:
        for row in rows:
            writers[count % num_shards].write(row_to_example(row, schema))
            count += 1
    finally:
        for w in writers:
            w.close()
    logger.info("wrote %d records to %s (%d shards)", count, path, num_shards)
    return count


def _record_files(path):
    if fs_utils.isdir(path):
        files = [
            f
            for f in fs_utils.list_files(path)
            if not fs_utils.basename(f).startswith(("_", "."))
        ]
        if not files:
            raise FileNotFoundError("no record files under {0}".format(path))
        return files
    return [path]


def load_tfrecords(path, schema=None, binary_features=()):
    """Load a TFRecord file/dir → ``(rows, schema)``.  ``schema`` may
    be a ``struct<...>`` string or ``[(name, type)]``; inferred from
    the first record when absent (reference: dfutil.py:44-81)."""
    if isinstance(schema, str):
        schema = parse_schema(schema)
    files = _record_files(path)
    rows = []
    for f in files:
        for record in tfr.read_records(f):
            if schema is None:
                schema = infer_schema(
                    ex.decode_example(record), binary_features
                )
            rows.append(example_to_row(record, schema))
    return rows, schema
