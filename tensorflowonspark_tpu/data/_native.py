"""Shared lazy loader for the C++ libraries under ``native/``.

One implementation of the build-on-first-use + ctypes-load dance for
all native components (tfrecord codec, example codec), so fixes land
once.  Cross-process safety: concurrent first-users (spawned compute
processes) serialize the ``make`` through an ``flock`` file lock, so no
process ever ``CDLL``s a half-written ``.so``.
"""

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)

_loaded = {}
_failed = set()
_lock = threading.Lock()


def _build(lib_name):
    lock_path = os.path.join(NATIVE_DIR, ".build.lock")
    try:
        import fcntl

        with open(lock_path, "w") as lock_file:
            fcntl.flock(lock_file, fcntl.LOCK_EX)
            try:
                if not os.path.exists(os.path.join(NATIVE_DIR, lib_name)):
                    subprocess.run(
                        ["make", "-C", NATIVE_DIR],
                        check=True,
                        capture_output=True,
                        timeout=120,
                    )
            finally:
                fcntl.flock(lock_file, fcntl.LOCK_UN)
    except ImportError:  # pragma: no cover - non-posix
        subprocess.run(
            ["make", "-C", NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )


def load_library(lib_name, configure):
    """Load (building if needed) ``native/<lib_name>``.

    Args:
      lib_name: shared-object filename, e.g. ``"libtfrecord_codec.so"``.
      configure: ``fn(lib)`` that sets restype/argtypes; called once.

    Returns the configured ``ctypes.CDLL``, or ``None`` when the build
    toolchain is unavailable (callers fall back to pure Python).
    """
    if lib_name in _loaded:
        return _loaded[lib_name]
    if lib_name in _failed:
        return None
    with _lock:
        if lib_name in _loaded:
            return _loaded[lib_name]
        if lib_name in _failed:
            return None
        path = os.path.join(NATIVE_DIR, lib_name)
        if not os.path.exists(path):
            try:
                _build(lib_name)
            except Exception as e:  # noqa: BLE001 - fall back to python
                logger.warning(
                    "native build of %s failed (%s); using pure-python "
                    "fallback", lib_name, e,
                )
                _failed.add(lib_name)
                return None
        try:
            lib = ctypes.CDLL(path)
            configure(lib)
        except (OSError, AttributeError) as e:
            logger.warning(
                "native load of %s failed (%s); using pure-python "
                "fallback", lib_name, e,
            )
            _failed.add(lib_name)
            return None
        _loaded[lib_name] = lib
        return lib
