"""Single-host batch serving path + CLI.

Re-designed from the reference's JVM serving stack — ``TFModel.scala``
(per-executor singleton ``SavedModelBundle`` cache + Row→Tensor→Row
conversion, reference: src/main/scala/com/yahoo/tensorflowonspark/
TFModel.scala:24-29,51-239,257-281) and the ``Inference.scala`` CLI
(reference: Inference.scala:27-79).  The TPU equivalents:

- a *serving export* is an orbax params directory plus ``metadata.json``
  written by :func:`tensorflowonspark_tpu.checkpoint.save_for_serving`
  (the SavedModel role);
- the "graph" half of a SavedModel is a **predictor builder**: a plain
  function ``builder(params, config) -> predict`` where
  ``predict(batch: dict[str, np.ndarray]) -> dict[str, np.ndarray]``.
  It is named in the export metadata as ``model_ref``
  (``"pkg.module:attr"``) so a bare export directory is self-describing
  the way a SavedModel is, or passed directly as a callable;
- batches are padded to a fixed ``batch_size`` so the jitted predict
  compiles once (XLA static shapes), then outputs are truncated — the
  TFMU-friendly version of the reference's per-batch ``session.run``;
- the CLI reads TFRecords through the native codec
  (:mod:`tensorflowonspark_tpu.data.tfrecord` backed by
  ``native/tfrecord_codec.cc``) and writes JSON lines, mirroring
  ``Inference --export_dir --input --schema_hint --input_mapping
  --output_mapping --output`` (reference: Inference.scala:30-44).

Run the CLI with ``python -m tensorflowonspark_tpu.serving ...``.
"""

import importlib
import itertools
import json
import logging
import os
import time

import numpy as np

from tensorflowonspark_tpu import serving_engine
# re-exported robustness surface (see serving_engine / docs/serving.md
# "Robustness & overload") + the shared latency accounting (ISSUE 7:
# BOTH schedules observe submit→finish into ONE telemetry histogram,
# so p50/p99 report identical semantics — docs/observability.md)
from tensorflowonspark_tpu.serving_engine import (  # noqa: F401
    LATENCY_METRIC,
    RequestError,
    RequestValidationError,
    ServingEngine,
    ServingError,
    WatchdogTimeout,
    error_record,
    latency_histogram,
    latency_summary,
)

logger = logging.getLogger(__name__)

#: Per-process predictor cache keyed by (export_dir, builder digest) —
#: the reference cached one SavedModelBundle per executor JVM
#: (TFModel.scala:24-29,257-263) / one session per python worker
#: (pipeline.py:492-496).
_PREDICTOR_CACHE = {}


def _builder_key(builder):
    """Content digest of a builder callable, stable across pickling —
    ``id()`` would miss on every per-job unpickled copy and can collide
    after GC address reuse."""
    if builder is None:
        return None
    import hashlib

    try:
        import cloudpickle as _cp

        return hashlib.sha256(_cp.dumps(builder)).hexdigest()
    except Exception:  # noqa: BLE001 - unpicklable builder: don't cache
        return object()  # unique → never a cache hit


def resolve_ref(ref):
    """Resolve a ``"pkg.module:attr"`` reference string to the object."""
    module_name, _, attr = ref.partition(":")
    if not module_name or not attr:
        raise ValueError(
            "model_ref must look like 'pkg.module:attr', got {0!r}".format(ref)
        )
    obj = importlib.import_module(module_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def with_preprocess(predict, preprocess):
    """Fuse an ON-DEVICE preprocess stage in front of ``predict``.

    ``preprocess`` (a callable or a
    :func:`~tensorflowonspark_tpu.data.preprocess.make_preprocess`
    kwargs dict) is jitted and applied to the assembled batch before
    the predictor — so rows kept in their narrow wire dtype (uint8
    pixels) cross the host→device link narrow and widen in HBM
    (docs/data_plane.md), instead of the host pre-inflating the batch
    to float32.  Predictor batch-shape attributes (``column_padding``,
    ``pad_multiple``, ``pad_cap``, ``make_slot_decoder``) are carried
    over; note the continuous schedule drives ``make_slot_decoder``
    directly, so a preprocess stage applies to the STATIC schedule and
    non-generation predictors.
    """
    import jax

    from tensorflowonspark_tpu.data import preprocess as pp_mod

    pre = jax.jit(pp_mod.resolve_preprocess(preprocess))

    def wrapped(batch):
        return predict(pre(batch))

    for attr in (
        "column_padding", "pad_multiple", "pad_cap", "make_slot_decoder"
    ):
        if hasattr(predict, attr):
            setattr(wrapped, attr, getattr(predict, attr))
    return wrapped


def _preprocess_key(preprocess):
    """Cache-key component for a preprocess argument: dict specs key by
    their (sorted) contents, callables by content digest."""
    if preprocess is None:
        return None
    if isinstance(preprocess, dict):
        return json.dumps(preprocess, sort_keys=True, default=str)
    return _builder_key(preprocess)


def load_predictor(export_dir, builder=None, use_cache=True,
                   preprocess=None, config_overrides=None):
    """Load a serving export and return its ``predict`` callable.

    Args:
      export_dir: directory written by
        :func:`~tensorflowonspark_tpu.checkpoint.save_for_serving`.
      builder: optional ``builder(params, config) -> predict`` override;
        defaults to the export metadata's ``model_ref``.
      use_cache: reuse a previously built predictor for the same export
        (the per-process singleton the reference kept,
        TFModel.scala:257-263).
      preprocess: optional on-device preprocess fused in front of the
        predictor (see :func:`with_preprocess`) — a callable or a
        ``make_preprocess`` kwargs dict.  Defaults to the export
        metadata's ``"preprocess"`` key, so an export can declare its
        own wire contract ("ship me uint8, I widen on device"):
        ``save_for_serving(..., extra_metadata={"preprocess":
        {"scale": 1/255}})``.  Pass ``False`` to disable even the
        metadata-declared stage (the caller widens on the host).
      config_overrides: optional dict laid over the export metadata's
        ``model_config`` before the builder runs — deployment-time
        knobs that don't belong in the export (prefix-cache sizing,
        ``draft_config`` toggles, ``chunk_size``...).  Exposed on the
        Spark pipeline as ``TFModel.setModelConfig`` (pipeline.py).
    """
    key = (
        os.path.abspath(os.fspath(export_dir)),
        _builder_key(builder),
        _preprocess_key(preprocess),
        json.dumps(config_overrides, sort_keys=True, default=str)
        if config_overrides else None,
    )
    if use_cache and key in _PREDICTOR_CACHE:
        return _PREDICTOR_CACHE[key]

    from tensorflowonspark_tpu.checkpoint import load_for_serving

    params, meta = load_for_serving(export_dir)
    if builder is None:
        ref = meta.get("model_ref")
        if not ref:
            raise ValueError(
                "export {0} has no model_ref metadata and no builder was "
                "given; write it via save_for_serving(..., extra_metadata="
                "{{'model_ref': 'pkg.module:builder'}})".format(export_dir)
            )
        builder = resolve_ref(ref)
    model_config = dict(meta.get("model_config") or {})
    if config_overrides:
        model_config.update(config_overrides)
    predict = builder(params, model_config)
    if preprocess is None:
        preprocess = meta.get("preprocess")
    if preprocess is not None and preprocess is not False:
        predict = with_preprocess(predict, preprocess)
    if use_cache:
        _PREDICTOR_CACHE[key] = predict
    return predict


# ----------------------------------------------------------------------
# batched row prediction (Row -> device array -> Row, the
# batch2tensors/tensors2batch role, TFModel.scala:51-239)
# ----------------------------------------------------------------------


def _stack_column(values, column=None):
    """Stack uniform rows into one batch array.  Ragged rows used to
    die deep inside ``np.stack`` with a shapeless error; now the
    ValueError NAMES the offending rows — the common trip-wire is the
    speculative generation predictor, which takes uniform-length
    batches only (no ``column_padding`` — see docs/inference.md
    "Speculative decoding")."""
    arrs = [np.asarray(v) for v in values]
    shapes = {a.shape for a in arrs}
    if len(shapes) > 1:
        majority = max(shapes, key=lambda s: sum(
            1 for a in arrs if a.shape == s
        ))
        ragged = [
            (i, a.shape) for i, a in enumerate(arrs)
            if a.shape != majority
        ][:8]
        raise ValueError(
            "cannot stack ragged rows for input {0}: batch majority "
            "shape is {1} but row(s) {2} differ.  This predictor "
            "declares no padding for this input — uniform-length rows "
            "only (speculative generation serving is the usual case; "
            "see docs/inference.md)".format(
                repr(column) if column else "batch", majority, ragged
            )
        )
    return np.stack(arrs)


def _stack_ragged_left(values, pad_value, multiple=1, cap=None):
    """Stack ragged 1-D rows by LEFT-padding to the batch max length
    (rounded up to ``multiple`` — shape BUCKETING, so the jitted
    generate program retraces once per bucket instead of once per
    unique prompt length); returns ``(stacked [n, max_len],
    pad_counts [n] int32)``.  Left-padding keeps every row's real
    tokens ending at the same position, so the compiled decode scan
    starts uniformly (the model masks the pad slots via
    ``pad_start``).  ``cap`` bounds the BUCKETED length (generation
    predictors set it to ``max_seq_len - max_new_tokens``): rounding
    up must never push prompts that fit past the cache capacity; a
    row genuinely longer than ``cap`` still stacks at its own length
    and fails downstream with the model's capacity error."""
    arrs = [np.asarray(v) for v in values]
    if any(a.ndim != 1 for a in arrs):
        raise ValueError(
            "ragged padding supports 1-D token rows; got shapes %s"
            % ([a.shape for a in arrs],)
        )
    raw_max = max(a.shape[0] for a in arrs)
    max_len = ((raw_max + multiple - 1) // multiple) * multiple
    if cap is not None:
        max_len = max(raw_max, min(max_len, int(cap)))
    pads = np.asarray([max_len - a.shape[0] for a in arrs], np.int32)
    out = np.full((len(arrs), max_len), pad_value, arrs[0].dtype)
    for i, a in enumerate(arrs):
        if a.shape[0]:
            out[i, max_len - a.shape[0]:] = a
    return out, pads


def predict_rows(
    predict,
    rows,
    input_mapping,
    output_mapping=None,
    batch_size=128,
    pad_to_batch=True,
    schedule="static",
    stats=None,
    on_error="raise",
    queue_depth=None,
    policy="block",
    watchdog_timeout=None,
    default_deadline=None,
    checkpoint_dir=None,
    watcher=None,
    rollback_window=8,
    replicas=1,
    replica_policy="least_loaded",
    fleet_queue_depth=None,
):
    """Run ``predict`` over dict-rows; yields output dict-rows.

    Args:
      predict: ``fn(batch: dict) -> dict`` of batched arrays.
      rows: iterable of dict rows.
      input_mapping: ``{column: input_name}`` — which row columns feed
        which predictor inputs (reference: TFParams.scala:27-33).
      output_mapping: ``{output_name: column}`` for the emitted rows;
        defaults to the predictor's own output names.
      batch_size: rows per predict call (reference default 128,
        TFParams.scala:14-18); in continuous mode, the number of
        in-flight KV-cache SLOTS.  ``"auto"`` reads the planner's
        chosen slot count off ``predict.plan`` (predictors built with
        ``config={"auto": ...}`` — docs/autotune.md); ``schedule=
        "auto"`` likewise picks continuous when the predictor
        supports it.
      pad_to_batch: zero-pad the final short batch so the jitted
        predict never sees a new shape (outputs are truncated back).
      schedule: ``"static"`` (fixed-size batches — every row in a
        batch pays the batch's full decode) or ``"continuous"``
        (in-flight batching for GENERATION predictors: finished rows
        are evicted and queued rows admitted into the freed KV-cache
        slots between chunked decode scans; requires a predictor
        exposing ``make_slot_decoder``, see
        ``transformer.serving_builder(mode="generate")`` and
        docs/serving.md).
      stats: optional dict the continuous scheduler fills with
        per-request latency accounting (``latency_sec`` in input
        order, plus admitted/evicted and robustness counters) — the
        serving bench's p50/p99 source.  Cross-request reuse counters
        land here too: ``prefix_hits`` / ``prefix_tokens_saved`` /
        ``evictions`` / ``pressure_evictions`` when the export enables
        the prefix cache, and ``spec_accepted`` / ``spec_proposed`` /
        ``spec_accept_rate`` when a draft model drives speculative
        chunks (docs/serving.md "Prefix cache & speculative
        decoding").  Exports with ``kv_layout: "paged"`` additionally
        report ``kv_layout`` and the page-pool occupancy gauges
        (``pool_pages`` / ``pool_pages_used`` / ``pool_pages_shared``
        — docs/serving.md "Paged KV & int4").
      on_error: ``"raise"`` (fail fast; admission errors name the
        request index and offending column) or ``"record"`` (poison
        isolation: a bad row yields a typed error record at its input
        position instead of killing the batch — see
        :func:`serving_engine.error_record` and docs/serving.md
        "Robustness & overload").
      queue_depth / policy / watchdog_timeout / default_deadline:
        continuous-only overload knobs, forwarded to
        :class:`~tensorflowonspark_tpu.serving_engine.ServingEngine`
        (bounded admission queue with ``block | reject | degrade``
        shedding, per-request deadlines, and the decode watchdog).
      checkpoint_dir / watcher / rollback_window: continuous-only
        LIFECYCLE knobs (docs/serving.md "Live weight swap &
        rollback"): a step-numbered export root (``publish_for_
        serving`` layout) or a pre-built
        :class:`~tensorflowonspark_tpu.hot_swap.CheckpointWatcher`
        arms validated live weight hot-swap between decode chunks —
        zero dropped requests, previous weights resident until
        ``rollback_window`` clean requests, automatic rollback on
        canary failure or a post-swap error spike.
      replicas / replica_policy / fleet_queue_depth: FLEET knobs
        (continuous only — docs/serving.md "Fleet routing & rolling
        deploys").  ``replicas > 1`` serves the job through a
        :class:`~tensorflowonspark_tpu.fleet.router.FleetRouter` over
        N engine replicas (each with its own slot decoder and radix
        cache, ``batch_size`` slots apiece): ``replica_policy`` picks
        the dispatch policy (``least_loaded`` / ``prefix_affinity`` /
        ``weighted_rr`` / ``random``), ``policy`` becomes the
        FLEET-level admission policy (pressure spills to a sibling
        replica before any single engine sheds), and
        ``fleet_queue_depth`` bounds the fleet admission queue.
        Outputs stay token-identical to a single-engine run and in
        input order; a replica death mid-decode re-dispatches its
        in-flight requests from their committed tokens.
    """
    # engine-side planner picks (ISSUE 18): a predictor built with
    # config={"auto": ...} carries predict.plan — "auto" here reads
    # the chosen slot count / schedule off it instead of a hand-set
    # number (zero knobs end to end)
    if batch_size == "auto" or schedule == "auto":
        chosen = (getattr(predict, "plan", None) or {}).get("chosen", {})
        if batch_size == "auto":
            batch_size = int(chosen.get("batch_size") or 128)
        if schedule == "auto":
            schedule = (
                "continuous"
                if hasattr(predict, "make_slot_decoder") else "static"
            )
    if schedule not in ("static", "continuous"):
        raise ValueError(
            "schedule must be 'static' or 'continuous', got %r"
            % (schedule,)
        )
    if on_error not in serving_engine.ON_ERROR:
        raise ValueError(
            "on_error must be one of %s, got %r"
            % (serving_engine.ON_ERROR, on_error)
        )
    if int(replicas or 1) > 1:
        if schedule != "continuous":
            raise ValueError(
                "replicas > 1 needs schedule='continuous' — the fleet "
                "router dispatches over slot-scheduler engines (see "
                "docs/serving.md)"
            )
        if checkpoint_dir is not None or watcher is not None:
            raise ValueError(
                "checkpoint_dir/watcher are single-engine lifecycle "
                "knobs; fleet weight changes go through rolling "
                "deploys (FleetRouter.start_rolling_deploy — see "
                "docs/serving.md 'Fleet routing & rolling deploys')"
            )
        from tensorflowonspark_tpu.fleet.router import predict_rows_fleet

        for r in predict_rows_fleet(
            predict, rows, input_mapping, output_mapping, batch_size,
            replicas=int(replicas), stats=stats, on_error=on_error,
            queue_depth=queue_depth, policy=policy,
            watchdog_timeout=watchdog_timeout,
            default_deadline=default_deadline,
            replica_policy=replica_policy,
            fleet_queue_depth=fleet_queue_depth,
        ):
            yield r
        return
    if schedule == "continuous":
        for r in _predict_rows_continuous(
            predict, rows, input_mapping, output_mapping, batch_size,
            stats, on_error=on_error, queue_depth=queue_depth,
            policy=policy, watchdog_timeout=watchdog_timeout,
            default_deadline=default_deadline,
            checkpoint_dir=checkpoint_dir, watcher=watcher,
            rollback_window=rollback_window,
        ):
            yield r
        return
    if (policy != "block" or queue_depth is not None
            or watchdog_timeout is not None
            or default_deadline is not None
            or checkpoint_dir is not None or watcher is not None):
        raise ValueError(
            "queue_depth/policy/watchdog_timeout/default_deadline/"
            "checkpoint_dir/watcher are continuous-schedule knobs; "
            "the static schedule has no admission queue or swap plane "
            "(see docs/serving.md)"
        )
    cols = sorted(input_mapping)
    buf = []  # ("ok", row) | ("rec", error_record) entries, input order
    n_seen = 0
    # static-schedule latency accounting: a request's latency is
    # submit (pulled from the source) → its row emitted — the SAME
    # semantics the continuous engine reports, observed into the
    # shared histogram (serving_engine.LATENCY_METRIC) and mirrored
    # into stats["latency_sec"] like the continuous scheduler's
    lat_hist = latency_histogram()
    submit_t = {}
    if stats is not None:
        stats.setdefault("latency_sec", {})
    # cost attribution (docs/observability.md "Cost attribution &
    # usage ledger"): the static schedule records one ledger row per
    # request too — tenant (reserved TENANT_INPUT column, validated
    # like the continuous path), tokens in/out, latency.  Rows key by
    # a per-job prefix so the process-wide ledger never collides
    # across jobs.
    from tensorflowonspark_tpu.telemetry import ledger as _ledger_mod

    _ledger = _ledger_mod.get_ledger()
    _job = "sj%d-" % next(_STATIC_JOB_SEQ)
    tenant_col = next(
        (c for c in input_mapping
         if input_mapping[c] == serving_engine.TENANT_INPUT), None
    )
    prompt_cols = [
        c for c in input_mapping
        if input_mapping[c] in (
            getattr(predict, "column_padding", None) or {}
        )
    ]
    tenants = {}
    # generation predictors declare ragged columns (prompts of varying
    # length) via ``predict.column_padding = {input_name: pad_value}``;
    # those stack left-padded and ship a ``<input>_pad`` count column
    # the model uses to mask the pad slots
    column_padding = getattr(predict, "column_padding", None) or {}

    def _assemble(chunk_rows, n_pad):
        batch = {}
        for c in cols:
            name = input_mapping[c]
            values = [r[c] for r in chunk_rows]
            if name in column_padding:
                batch[name], batch[name + "_pad"] = _stack_ragged_left(
                    values, column_padding[name],
                    getattr(predict, "pad_multiple", 1),
                    cap=getattr(predict, "pad_cap", None),
                )
            else:
                batch[name] = _stack_column(values, column=name)
        n = len(chunk_rows)
        if pad_to_batch and n < n_pad:
            batch = {
                k: np.concatenate(
                    [v, np.zeros((n_pad - n,) + v.shape[1:], v.dtype)]
                )
                for k, v in batch.items()
            }
        return batch

    def _predict_batch(chunk_rows):
        out = predict(_assemble(chunk_rows, batch_size))
        return {
            k: np.asarray(v)[:len(chunk_rows)] for k, v in out.items()
        }

    def _flush(chunk):
        ok = [(i, row) for i, (tag, row, _) in enumerate(chunk)
              if tag == "ok"]
        per_row = {}
        out = None
        if ok:
            try:
                out = _predict_batch([row for _, row in ok])
            except Exception as e:  # noqa: BLE001 - poison isolation
                if on_error == "raise":
                    raise
                # a poisoned row can kill batch ASSEMBLY (ragged
                # shapes) or the predict call itself; isolate it by
                # re-running each row alone (same padded batch shape,
                # so nothing recompiles) and record only the rows
                # that individually fail
                logger.warning(
                    "batch of %d rows failed (%s); isolating "
                    "per-row", len(ok), e,
                )
                for pos, row in ok:
                    idx = chunk[pos][2]
                    try:
                        per_row[pos] = ("out", _predict_batch([row]))
                    except Exception as re:  # noqa: BLE001
                        per_row[pos] = ("rec", serving_engine.error_record(
                            "predict", idx,
                            "request {0} failed in predict: "
                            "{1}".format(idx, re),
                        ))
        ok_pos = {p: i for i, (p, _) in enumerate(ok)}
        for pos, (tag, payload, _idx) in enumerate(chunk):
            if tag == "rec":
                yield _idx, payload
            elif out is not None:
                i = ok_pos[pos]
                yield _idx, _apply_output_mapping(
                    {k: v[i] for k, v in out.items()}, output_mapping
                )
            else:
                kind, o = per_row[pos]
                if kind == "rec":
                    yield _idx, o
                else:
                    yield _idx, _apply_output_mapping(
                        {k: v[0] for k, v in o.items()}, output_mapping
                    )

    def _emit(flushed):
        for idx, r in flushed:
            rid = _job + "req%d" % idx
            t_sub = submit_t.pop(idx, None)
            lat = None
            if t_sub is not None:
                lat = time.monotonic() - t_sub
                # the trace-id exemplar rides the shared histogram so
                # tail buckets name a concrete request (ISSUE 14)
                lat_hist.observe(lat, exemplar=rid)
                if stats is not None:
                    stats["latency_sec"][idx] = lat
            if _ledger.enabled:
                toks_out = 0
                if isinstance(r, dict) and "error" not in r:
                    if "generated_len" in r:
                        toks_out = int(np.asarray(r["generated_len"]))
                    elif "generated" in r:
                        toks_out = int(np.asarray(r["generated"]).size)
                _ledger.record(
                    rid, tenant=tenants.pop(idx, None),
                    tokens_in=tokens_in.pop(idx, 0),
                    tokens_out=toks_out, latency_sec=lat,
                )
            yield r

    tokens_in = {}
    for row in rows:
        idx = n_seen
        n_seen += 1
        submit_t[idx] = time.monotonic()
        try:
            tenant = _validate_static_row(
                row, idx, input_mapping, tenant_col
            )
            buf.append(("ok", row, idx))
            if _ledger.enabled and isinstance(row, dict):
                if tenant is not None:
                    tenants[idx] = tenant
                if prompt_cols:
                    try:
                        tokens_in[idx] = int(
                            np.asarray(row[prompt_cols[0]]).size
                        )
                    # tfoslint: disable=TFOS005(tokens_in accounting is best-effort; a ragged cell must never fail the request)
                    except Exception:  # noqa: BLE001 - accounting only
                        pass
        except serving_engine.RequestValidationError as e:
            if on_error == "raise":
                raise
            buf.append((
                "rec", serving_engine.error_record(e.kind, idx, e), idx
            ))
        if len(buf) == batch_size:
            for r in _emit(_flush(buf)):
                yield r
            buf = []
    if buf:
        for r in _emit(_flush(buf)):
            yield r


#: per-process static-job sequence (ledger row namespacing)
_STATIC_JOB_SEQ = itertools.count(1)


def _validate_static_row(row, idx, input_mapping, tenant_col=None):
    """Static-schedule admission validation: every mapped input column
    must be present — a missing key used to surface as a bare
    ``KeyError`` from deep inside the batch flush; now the error names
    the request index and the missing column at admission.  A mapped
    reserved ``tenant`` column is validated here too (the SAME rule as
    the continuous engine: non-empty string, typed ``bad_tenant``
    error naming the request index and offending value)."""
    for col in sorted(input_mapping):
        if col not in row:
            raise serving_engine.RequestValidationError(
                "request {0} is missing input column {1!r} (mapped to "
                "predictor input {2!r}); present columns: {3}".format(
                    idx, col, input_mapping[col],
                    sorted(row) if isinstance(row, dict) else type(row),
                ),
                kind="missing_input", request_index=idx,
            )
    if tenant_col is not None:
        return serving_engine.validate_tenant(row, idx, tenant_col)
    return None


def _apply_output_mapping(out, output_mapping):
    if not output_mapping:
        return out
    missing = [n for n in output_mapping if n not in out]
    if missing:
        raise KeyError(
            "output_mapping names {0} not produced by the predictor "
            "(outputs: {1})".format(missing, sorted(out))
        )
    return {col: out[name] for name, col in output_mapping.items()}


#: reserved input names (re-exported from serving_engine): a row
#: column mapped to BUDGET_INPUT carries that request's token budget
#: (evicted after ``min(max_new, budget)`` tokens even without eos);
#: one mapped to DEADLINE_INPUT carries its deadline in seconds; one
#: mapped to TENANT_INPUT carries its tenant key for the usage ledger
#: (validated on BOTH schedules — non-string/empty values are typed
#: ``bad_tenant`` errors naming the request); TRACE_INPUT carries an
#: explicit request trace id (the fleet router mints one per request
#: when the caller doesn't)
BUDGET_INPUT = serving_engine.BUDGET_INPUT
DEADLINE_INPUT = serving_engine.DEADLINE_INPUT
TENANT_INPUT = serving_engine.TENANT_INPUT
TRACE_INPUT = serving_engine.TRACE_INPUT


def _predict_rows_continuous(predict, rows, input_mapping,
                             output_mapping, num_slots, stats,
                             on_error="raise", queue_depth=None,
                             policy="block", watchdog_timeout=None,
                             default_deadline=None, checkpoint_dir=None,
                             watcher=None, rollback_window=8):
    """Continuous in-flight batching over a generation predictor.

    The scheduling loop lives in
    :class:`~tensorflowonspark_tpu.serving_engine.ServingEngine` (the
    overload-safe serving layer: bounded admission queue with
    ``block | reject | degrade`` shedding, per-request deadlines with
    slot-level cancellation, poison isolation via ``on_error``, and a
    decode watchdog with in-flight recovery — see docs/serving.md
    "Robustness & overload").  A request queue feeds ``num_slots``
    KV-cache slots; decode runs in compiled chunks
    (:class:`~tensorflowonspark_tpu.models.transformer.SlotDecoder`),
    and BETWEEN chunks finished rows (first eos, the row's budget, or
    an expired deadline) are evicted and queued prompts admitted into
    the freed lanes — so a short row never pays a long neighbor's
    decode.  Rows are yielded in INPUT order (completion order is
    recorded in ``stats``); outputs are token-identical to the static
    ``generate`` path per request (parity-tested)."""
    engine = serving_engine.ServingEngine(
        predict, input_mapping, output_mapping, num_slots,
        queue_depth=queue_depth, policy=policy,
        default_deadline=default_deadline,
        watchdog_timeout=watchdog_timeout, on_error=on_error,
        stats=stats, checkpoint_dir=checkpoint_dir, watcher=watcher,
        rollback_window=rollback_window,
    )
    for r in engine.serve(rows):
        yield r


def infer_output_schema(predict, sample_row, input_mapping,
                        output_mapping=None):
    """Derive the output DataFrame schema of ``predict`` by running ONE
    row through :func:`predict_rows` — at EXPORT time, so the schema
    can be written into the serving metadata
    (``save_for_serving(..., output_schema=...)``) and the
    distributed transform never has to run its legacy one-row probe
    job (which evaluates the predictor over a whole partition-0 batch
    and throws the results away — a full compiled decode, twice, for
    generation exports; see pipeline.TFModel._transform_native).

    Returns an interchange field list ``[(column, type_str), ...]``.
    """
    from tensorflowonspark_tpu.pipeline import _infer_output_type

    out = next(iter(predict_rows(
        predict, [sample_row], input_mapping, output_mapping,
        batch_size=1,
    )))
    return [(name, _infer_output_type(out[name])) for name in sorted(out)]


# ----------------------------------------------------------------------
# CLI (Inference.scala equivalent)
# ----------------------------------------------------------------------


def _parse_mapping(text):
    """Accept JSON (``{"col":"x"}``) or ``col=x,col2=y`` shorthand."""
    text = text.strip()
    if text.startswith("{"):
        return json.loads(text)
    out = {}
    for part in text.split(","):
        k, _, v = part.partition("=")
        if not _:
            raise ValueError("mapping entries must be key=value: " + part)
        out[k.strip()] = v.strip()
    return out


def _json_default(o):
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, (np.floating, np.integer)):
        return o.item()
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    raise TypeError("not JSON serializable: {0}".format(type(o)))


def main(argv=None):
    """Batch-inference CLI (reference: Inference.scala:27-79): load a
    serving export, read TFRecords, write predictions as JSON lines."""
    import argparse

    p = argparse.ArgumentParser(
        prog="tensorflowonspark_tpu.serving",
        description="Batch inference over TFRecords with a serving export",
    )
    p.add_argument("--export_dir", required=True,
                   help="serving export directory (save_for_serving output)")
    p.add_argument("--input", required=True,
                   help="TFRecord file or directory of shards")
    p.add_argument("--schema_hint", default=None,
                   help="struct<name:type,...> schema for the input records")
    p.add_argument("--input_mapping", required=True,
                   help="JSON or col=input,... mapping of record columns "
                        "to predictor inputs")
    p.add_argument("--output_mapping", default=None,
                   help="JSON or output=col,... mapping of predictor "
                        "outputs to result columns")
    p.add_argument("--output", required=True,
                   help="output directory for JSON-line part files")
    p.add_argument("--batch_size", type=int, default=128)
    p.add_argument("--schedule", choices=("static", "continuous"),
                   default="static",
                   help="batching schedule: 'static' fixed-size "
                        "batches, or 'continuous' in-flight batching "
                        "for generation exports (slot-level KV-cache "
                        "scheduler; batch_size = in-flight slots — "
                        "see docs/serving.md)")
    p.add_argument("--on_error", choices=serving_engine.ON_ERROR,
                   default="raise",
                   help="per-request failure policy: 'raise' fails "
                        "fast naming the request, 'record' isolates "
                        "poison rows as typed error records")
    p.add_argument("--policy", choices=serving_engine.POLICIES,
                   default="block",
                   help="continuous admission policy under overload: "
                        "block (backpressure), reject (shed past the "
                        "queue bound), degrade (shrink token budgets "
                        "against the backlog)")
    p.add_argument("--queue_depth", type=int, default=None,
                   help="continuous admission-queue bound "
                        "(default 2x slots)")
    p.add_argument("--watchdog_timeout", type=float, default=None,
                   help="seconds before a wedged decode chunk is "
                        "abandoned and in-flight requests are "
                        "re-admitted from their committed tokens")
    p.add_argument("--deadline", type=float, default=None,
                   help="default per-request deadline in seconds "
                        "(expired requests return a typed record "
                        "with their partial tokens)")
    p.add_argument("--checkpoint_dir", default=None,
                   help="step-numbered serving-export root "
                        "(publish_for_serving layout) to watch for "
                        "live weight hot-swaps during the job "
                        "(continuous schedule only)")
    p.add_argument("--checkpoint_poll", type=float, default=5.0,
                   help="seconds between checkpoint_dir scans")
    p.add_argument("--rollback_window", type=int, default=8,
                   help="clean requests a swapped-in generation must "
                        "serve before the previous weights are "
                        "released (automatic rollback inside it)")
    p.add_argument("--replicas", type=int, default=1,
                   help="serve through a fleet of N engine replicas "
                        "behind the router (continuous schedule only; "
                        "batch_size slots per replica — see "
                        "docs/serving.md 'Fleet routing & rolling "
                        "deploys')")
    p.add_argument("--replica_policy", default="least_loaded",
                   choices=("least_loaded", "prefix_affinity",
                            "weighted_rr", "random"),
                   help="fleet dispatch policy: least_loaded (replica "
                        "load snapshots), prefix_affinity (shared "
                        "prompt prefixes land on the replica whose "
                        "radix cache holds them), weighted_rr, random")
    p.add_argument("--fleet_queue_depth", type=int, default=None,
                   help="fleet admission-queue bound (default: the "
                        "summed replica capacity)")
    p.add_argument("--tp", type=int, default=None,
                   help="tensor-parallel degree: shard weights and the "
                        "paged KV pool over a {'model': N} mesh (the "
                        "whole predictor becomes ONE logical replica "
                        "spanning N chips; see docs/serving.md "
                        "'Disaggregated prefill/decode & TP sharding')")
    p.add_argument("--disaggregate", action="store_true",
                   help="split prefill and decode into separate jitted "
                        "programs with a zero-copy paged-KV handoff "
                        "(needs kv_layout='paged'; bounds TTFT/p99 "
                        "under mixed prompt lengths)")
    args = p.parse_args(argv)

    from tensorflowonspark_tpu.data import interchange

    rows, schema = interchange.load_tfrecords(
        args.input, schema=args.schema_hint
    )
    logger.info("loaded %d rows (schema: %s)", len(rows),
                interchange.format_schema(schema))
    overrides = {}
    if args.tp:
        overrides["tp"] = args.tp
    if args.disaggregate:
        overrides["disaggregate"] = True
    predict = load_predictor(
        args.export_dir, config_overrides=overrides or None
    )
    input_mapping = _parse_mapping(args.input_mapping)
    output_mapping = (
        _parse_mapping(args.output_mapping) if args.output_mapping else None
    )

    from tensorflowonspark_tpu.utils import fs as fs_utils

    fs_utils.makedirs(args.output)
    out_path = fs_utils.join(args.output, "part-00000.jsonl")
    count = 0
    sched_stats = {}
    lat_base = latency_histogram().snapshot()
    with fs_utils.open_file(out_path, "w") as f:
        kwargs = {}
        if args.schedule == "continuous":
            kwargs = dict(
                queue_depth=args.queue_depth, policy=args.policy,
                watchdog_timeout=args.watchdog_timeout,
                default_deadline=args.deadline,
                rollback_window=args.rollback_window,
            )
            if args.replicas > 1:
                kwargs.update(
                    replicas=args.replicas,
                    replica_policy=args.replica_policy,
                    fleet_queue_depth=args.fleet_queue_depth,
                )
            if args.checkpoint_dir:
                from tensorflowonspark_tpu import hot_swap

                kwargs["watcher"] = hot_swap.CheckpointWatcher(
                    args.checkpoint_dir,
                    poll_interval=args.checkpoint_poll,
                )
        for out_row in predict_rows(
            predict, rows, input_mapping, output_mapping,
            args.batch_size, schedule=args.schedule, stats=sched_stats,
            on_error=args.on_error, **kwargs
        ):
            f.write(json.dumps(out_row, default=_json_default) + "\n")
            count += 1
    shed = sched_stats.get("shed", 0) + sched_stats.get("expired", 0)
    if shed or sched_stats.get("errors"):
        logger.warning(
            "robustness: %d shed/expired, %d error record(s), "
            "%d watchdog fire(s)", shed,
            sched_stats.get("errors", 0),
            sched_stats.get("watchdog_fires", 0),
        )
    if sched_stats.get("swaps") or sched_stats.get("rollbacks"):
        logger.info(
            "lifecycle: %d weight swap(s) (%d committed, %d rolled "
            "back), %d in-flight request(s) requeued across swaps, "
            "serving generation %d",
            sched_stats.get("swaps", 0),
            sched_stats.get("swap_commits", 0),
            sched_stats.get("rollbacks", 0),
            sched_stats.get("swap_requeued", 0),
            sched_stats.get("weight_generation", 0),
        )
    # p50/p99 come from the SHARED telemetry histogram, scoped to this
    # run — identical semantics on both schedules (the old code
    # computed continuous-only percentiles from a raw list)
    summ = latency_summary(since=lat_base)
    if summ["count"]:
        logger.info(
            "%s schedule: %d request(s)%s, per-request latency "
            "p50=%.1fms p99=%.1fms",
            args.schedule, summ["count"],
            " over %d chunks" % sched_stats["chunks"]
            if sched_stats.get("chunks") else "",
            summ["p50_ms"], summ["p99_ms"],
        )
    logger.info("wrote %d predictions to %s", count, out_path)
    return count


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
