"""Single-host batch serving path + CLI.

Re-designed from the reference's JVM serving stack — ``TFModel.scala``
(per-executor singleton ``SavedModelBundle`` cache + Row→Tensor→Row
conversion, reference: src/main/scala/com/yahoo/tensorflowonspark/
TFModel.scala:24-29,51-239,257-281) and the ``Inference.scala`` CLI
(reference: Inference.scala:27-79).  The TPU equivalents:

- a *serving export* is an orbax params directory plus ``metadata.json``
  written by :func:`tensorflowonspark_tpu.checkpoint.save_for_serving`
  (the SavedModel role);
- the "graph" half of a SavedModel is a **predictor builder**: a plain
  function ``builder(params, config) -> predict`` where
  ``predict(batch: dict[str, np.ndarray]) -> dict[str, np.ndarray]``.
  It is named in the export metadata as ``model_ref``
  (``"pkg.module:attr"``) so a bare export directory is self-describing
  the way a SavedModel is, or passed directly as a callable;
- batches are padded to a fixed ``batch_size`` so the jitted predict
  compiles once (XLA static shapes), then outputs are truncated — the
  TFMU-friendly version of the reference's per-batch ``session.run``;
- the CLI reads TFRecords through the native codec
  (:mod:`tensorflowonspark_tpu.data.tfrecord` backed by
  ``native/tfrecord_codec.cc``) and writes JSON lines, mirroring
  ``Inference --export_dir --input --schema_hint --input_mapping
  --output_mapping --output`` (reference: Inference.scala:30-44).

Run the CLI with ``python -m tensorflowonspark_tpu.serving ...``.
"""

import importlib
import json
import logging
import os

import numpy as np

logger = logging.getLogger(__name__)

#: Per-process predictor cache keyed by (export_dir, builder digest) —
#: the reference cached one SavedModelBundle per executor JVM
#: (TFModel.scala:24-29,257-263) / one session per python worker
#: (pipeline.py:492-496).
_PREDICTOR_CACHE = {}


def _builder_key(builder):
    """Content digest of a builder callable, stable across pickling —
    ``id()`` would miss on every per-job unpickled copy and can collide
    after GC address reuse."""
    if builder is None:
        return None
    import hashlib

    try:
        import cloudpickle as _cp

        return hashlib.sha256(_cp.dumps(builder)).hexdigest()
    except Exception:  # noqa: BLE001 - unpicklable builder: don't cache
        return object()  # unique → never a cache hit


def resolve_ref(ref):
    """Resolve a ``"pkg.module:attr"`` reference string to the object."""
    module_name, _, attr = ref.partition(":")
    if not module_name or not attr:
        raise ValueError(
            "model_ref must look like 'pkg.module:attr', got {0!r}".format(ref)
        )
    obj = importlib.import_module(module_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def load_predictor(export_dir, builder=None, use_cache=True):
    """Load a serving export and return its ``predict`` callable.

    Args:
      export_dir: directory written by
        :func:`~tensorflowonspark_tpu.checkpoint.save_for_serving`.
      builder: optional ``builder(params, config) -> predict`` override;
        defaults to the export metadata's ``model_ref``.
      use_cache: reuse a previously built predictor for the same export
        (the per-process singleton the reference kept,
        TFModel.scala:257-263).
    """
    key = (os.path.abspath(os.fspath(export_dir)), _builder_key(builder))
    if use_cache and key in _PREDICTOR_CACHE:
        return _PREDICTOR_CACHE[key]

    from tensorflowonspark_tpu.checkpoint import load_for_serving

    params, meta = load_for_serving(export_dir)
    if builder is None:
        ref = meta.get("model_ref")
        if not ref:
            raise ValueError(
                "export {0} has no model_ref metadata and no builder was "
                "given; write it via save_for_serving(..., extra_metadata="
                "{{'model_ref': 'pkg.module:builder'}})".format(export_dir)
            )
        builder = resolve_ref(ref)
    predict = builder(params, meta.get("model_config") or {})
    if use_cache:
        _PREDICTOR_CACHE[key] = predict
    return predict


# ----------------------------------------------------------------------
# batched row prediction (Row -> device array -> Row, the
# batch2tensors/tensors2batch role, TFModel.scala:51-239)
# ----------------------------------------------------------------------


def _stack_column(values):
    return np.stack([np.asarray(v) for v in values])


def _stack_ragged_left(values, pad_value, multiple=1, cap=None):
    """Stack ragged 1-D rows by LEFT-padding to the batch max length
    (rounded up to ``multiple`` — shape BUCKETING, so the jitted
    generate program retraces once per bucket instead of once per
    unique prompt length); returns ``(stacked [n, max_len],
    pad_counts [n] int32)``.  Left-padding keeps every row's real
    tokens ending at the same position, so the compiled decode scan
    starts uniformly (the model masks the pad slots via
    ``pad_start``).  ``cap`` bounds the BUCKETED length (generation
    predictors set it to ``max_seq_len - max_new_tokens``): rounding
    up must never push prompts that fit past the cache capacity; a
    row genuinely longer than ``cap`` still stacks at its own length
    and fails downstream with the model's capacity error."""
    arrs = [np.asarray(v) for v in values]
    if any(a.ndim != 1 for a in arrs):
        raise ValueError(
            "ragged padding supports 1-D token rows; got shapes %s"
            % ([a.shape for a in arrs],)
        )
    raw_max = max(a.shape[0] for a in arrs)
    max_len = ((raw_max + multiple - 1) // multiple) * multiple
    if cap is not None:
        max_len = max(raw_max, min(max_len, int(cap)))
    pads = np.asarray([max_len - a.shape[0] for a in arrs], np.int32)
    out = np.full((len(arrs), max_len), pad_value, arrs[0].dtype)
    for i, a in enumerate(arrs):
        if a.shape[0]:
            out[i, max_len - a.shape[0]:] = a
    return out, pads


def predict_rows(
    predict,
    rows,
    input_mapping,
    output_mapping=None,
    batch_size=128,
    pad_to_batch=True,
    schedule="static",
    stats=None,
):
    """Run ``predict`` over dict-rows; yields output dict-rows.

    Args:
      predict: ``fn(batch: dict) -> dict`` of batched arrays.
      rows: iterable of dict rows.
      input_mapping: ``{column: input_name}`` — which row columns feed
        which predictor inputs (reference: TFParams.scala:27-33).
      output_mapping: ``{output_name: column}`` for the emitted rows;
        defaults to the predictor's own output names.
      batch_size: rows per predict call (reference default 128,
        TFParams.scala:14-18); in continuous mode, the number of
        in-flight KV-cache SLOTS.
      pad_to_batch: zero-pad the final short batch so the jitted
        predict never sees a new shape (outputs are truncated back).
      schedule: ``"static"`` (fixed-size batches — every row in a
        batch pays the batch's full decode) or ``"continuous"``
        (in-flight batching for GENERATION predictors: finished rows
        are evicted and queued rows admitted into the freed KV-cache
        slots between chunked decode scans; requires a predictor
        exposing ``make_slot_decoder``, see
        ``transformer.serving_builder(mode="generate")`` and
        docs/serving.md).
      stats: optional dict the continuous scheduler fills with
        per-request latency accounting (``latency_sec`` in input
        order, plus admitted/evicted counters) — the serving bench's
        p50/p99 source.
    """
    if schedule not in ("static", "continuous"):
        raise ValueError(
            "schedule must be 'static' or 'continuous', got %r"
            % (schedule,)
        )
    if schedule == "continuous":
        for r in _predict_rows_continuous(
            predict, rows, input_mapping, output_mapping, batch_size,
            stats,
        ):
            yield r
        return
    cols = sorted(input_mapping)
    buf = []
    # generation predictors declare ragged columns (prompts of varying
    # length) via ``predict.column_padding = {input_name: pad_value}``;
    # those stack left-padded and ship a ``<input>_pad`` count column
    # the model uses to mask the pad slots
    column_padding = getattr(predict, "column_padding", None) or {}

    def _flush(chunk):
        n = len(chunk)
        batch = {}
        for c in cols:
            name = input_mapping[c]
            values = [r[c] for r in chunk]
            if name in column_padding:
                batch[name], batch[name + "_pad"] = _stack_ragged_left(
                    values, column_padding[name],
                    getattr(predict, "pad_multiple", 1),
                    cap=getattr(predict, "pad_cap", None),
                )
            else:
                batch[name] = _stack_column(values)
        if pad_to_batch and n < batch_size:
            batch = {
                k: np.concatenate(
                    [v, np.zeros((batch_size - n,) + v.shape[1:], v.dtype)]
                )
                for k, v in batch.items()
            }
        out = predict(batch)
        out = {k: np.asarray(v)[:n] for k, v in out.items()}
        if output_mapping:
            missing = [n_ for n_ in output_mapping if n_ not in out]
            if missing:
                # fail fast like the reference's signature lookup
                # (pipeline.py:559-564), not silent empty rows
                raise KeyError(
                    "output_mapping names {0} not produced by the "
                    "predictor (outputs: {1})".format(missing, sorted(out))
                )
            out = {col: out[name] for name, col in output_mapping.items()}
        for i in range(n):
            yield {k: v[i] for k, v in out.items()}

    for row in rows:
        buf.append(row)
        if len(buf) == batch_size:
            for r in _flush(buf):
                yield r
            buf = []
    if buf:
        for r in _flush(buf):
            yield r


def _apply_output_mapping(out, output_mapping):
    if not output_mapping:
        return out
    missing = [n for n in output_mapping if n not in out]
    if missing:
        raise KeyError(
            "output_mapping names {0} not produced by the predictor "
            "(outputs: {1})".format(missing, sorted(out))
        )
    return {col: out[name] for name, col in output_mapping.items()}


#: reserved input name: a row column mapped to it carries that
#: request's token budget (continuous schedule only) — the scheduler
#: evicts the row after ``min(max_new, budget)`` tokens even when no
#: eos arrives, freeing its slot for the next queued prompt
BUDGET_INPUT = "max_new"


def _predict_rows_continuous(predict, rows, input_mapping,
                             output_mapping, num_slots, stats):
    """Continuous in-flight batching over a generation predictor.

    The scheduler role of the serving-side tentpole (see
    docs/serving.md): a request queue feeds ``num_slots`` KV-cache
    slots; decode runs in compiled chunks
    (:class:`~tensorflowonspark_tpu.models.transformer.SlotDecoder`),
    and BETWEEN chunks finished rows (first eos, or the row's budget)
    are evicted and queued prompts admitted into the freed lanes — so
    a short row never pays a long neighbor's decode.  Rows are
    yielded in INPUT order (completion order is recorded in
    ``stats``); outputs are token-identical to the static
    ``generate`` path per request (parity-tested).
    """
    import time as _time

    factory = getattr(predict, "make_slot_decoder", None)
    if factory is None:
        raise ValueError(
            "schedule='continuous' requires a generation predictor "
            "exposing make_slot_decoder (see transformer."
            "serving_builder with mode='generate'); this predictor "
            "has none"
        )
    column_padding = getattr(predict, "column_padding", None) or {}
    prompt_cols = [
        c for c in input_mapping if input_mapping[c] in column_padding
    ]
    if len(prompt_cols) != 1:
        raise ValueError(
            "continuous scheduling needs exactly one ragged prompt "
            "column in input_mapping; got {0}".format(prompt_cols)
        )
    prompt_col = prompt_cols[0]
    budget_cols = [
        c for c in input_mapping if input_mapping[c] == BUDGET_INPUT
    ]
    budget_col = budget_cols[0] if budget_cols else None

    decoder = factory(num_slots)
    max_new = decoder.max_new_tokens
    eos_id = decoder.eos_id
    fill = eos_id if eos_id is not None else 0
    now = _time.perf_counter

    if stats is None:
        stats = {}
    stats["latency_sec"] = {}
    stats["admitted"] = 0
    stats["chunks"] = 0
    stats["chunk_size"] = decoder.chunk_size

    it = iter(rows)
    pending = []
    state = {"n_in": 0, "exhausted": False}
    slot_req = {}   # slot -> in-flight request record
    finished = {}   # input idx -> output row
    emit_at = {"next": 0}

    def _pull():
        if state["exhausted"]:
            return
        try:
            row = next(it)
        except StopIteration:
            state["exhausted"] = True
            return
        budget = max_new
        if budget_col is not None:
            budget = max(1, min(int(row[budget_col]), max_new))
        pending.append({
            "idx": state["n_in"],
            "prompt": np.asarray(row[prompt_col]),
            "budget": budget,
            "eos_at": None,
            "out": None,
            "submit": now(),
        })
        state["n_in"] += 1

    def _finalize(req, t_done):
        arr = np.full((max_new,), fill, np.int32)
        toks = req["out"][:max_new]
        arr[: len(toks)] = toks
        gen_len = (
            req["eos_at"] if req["eos_at"] is not None else req["budget"]
        )
        out = {"generated": arr}
        if eos_id is not None or budget_col is not None:
            out["generated_len"] = np.int32(gen_len)
        finished[req["idx"]] = _apply_output_mapping(out, output_mapping)
        stats["latency_sec"][req["idx"]] = t_done - req["submit"]

    def _admit_free():
        for slot in decoder.free_slots():
            if not pending:
                _pull()
            if not pending:
                return
            req = pending.pop(0)
            # admit is a single ASYNC dispatch; the first token comes
            # back as an unsynchronized device scalar, resolved at the
            # next chunk boundary together with the token block
            req["out"] = [decoder.admit(slot, req["prompt"])]
            stats["admitted"] += 1
            slot_req[slot] = req

    def _consume(req, chunk_row):
        """Fold a slot's chunk tokens into its request; True when the
        request completed (first eos, or its budget)."""
        if req["out"] and not isinstance(req["out"][0], int):
            first = int(np.asarray(req["out"][0]))
            req["out"][0] = first
            if eos_id is not None and first == eos_id:
                req["eos_at"] = 0
        for t in (() if chunk_row is None else chunk_row):
            if req["eos_at"] is not None or len(req["out"]) >= req["budget"]:
                break
            req["out"].append(int(t))
            if eos_id is not None and int(t) == eos_id:
                req["eos_at"] = len(req["out"]) - 1
        return req["eos_at"] is not None or len(req["out"]) >= req["budget"]

    while True:
        _admit_free()
        if not slot_req:
            while emit_at["next"] in finished:
                yield finished.pop(emit_at["next"])
                emit_at["next"] += 1
            if pending or not state["exhausted"]:
                # only reachable when there are zero slots; guard
                # against an impossible-progress spin
                raise RuntimeError(
                    "continuous scheduler cannot make progress "
                    "(no slots available)"
                )
            return
        toks = decoder.step_chunk()
        stats["chunks"] += 1
        t_chunk = now()
        for slot, req in list(slot_req.items()):
            if _consume(req, toks[slot]):
                _finalize(req, t_chunk)
                decoder.evict(slot)
                del slot_req[slot]
        # stream completed rows in input order as soon as the head of
        # the reorder buffer is ready
        while emit_at["next"] in finished:
            yield finished.pop(emit_at["next"])
            emit_at["next"] += 1


def infer_output_schema(predict, sample_row, input_mapping,
                        output_mapping=None):
    """Derive the output DataFrame schema of ``predict`` by running ONE
    row through :func:`predict_rows` — at EXPORT time, so the schema
    can be written into the serving metadata
    (``save_for_serving(..., output_schema=...)``) and the
    distributed transform never has to run its legacy one-row probe
    job (which evaluates the predictor over a whole partition-0 batch
    and throws the results away — a full compiled decode, twice, for
    generation exports; see pipeline.TFModel._transform_native).

    Returns an interchange field list ``[(column, type_str), ...]``.
    """
    from tensorflowonspark_tpu.pipeline import _infer_output_type

    out = next(iter(predict_rows(
        predict, [sample_row], input_mapping, output_mapping,
        batch_size=1,
    )))
    return [(name, _infer_output_type(out[name])) for name in sorted(out)]


# ----------------------------------------------------------------------
# CLI (Inference.scala equivalent)
# ----------------------------------------------------------------------


def _parse_mapping(text):
    """Accept JSON (``{"col":"x"}``) or ``col=x,col2=y`` shorthand."""
    text = text.strip()
    if text.startswith("{"):
        return json.loads(text)
    out = {}
    for part in text.split(","):
        k, _, v = part.partition("=")
        if not _:
            raise ValueError("mapping entries must be key=value: " + part)
        out[k.strip()] = v.strip()
    return out


def _json_default(o):
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, (np.floating, np.integer)):
        return o.item()
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    raise TypeError("not JSON serializable: {0}".format(type(o)))


def main(argv=None):
    """Batch-inference CLI (reference: Inference.scala:27-79): load a
    serving export, read TFRecords, write predictions as JSON lines."""
    import argparse

    p = argparse.ArgumentParser(
        prog="tensorflowonspark_tpu.serving",
        description="Batch inference over TFRecords with a serving export",
    )
    p.add_argument("--export_dir", required=True,
                   help="serving export directory (save_for_serving output)")
    p.add_argument("--input", required=True,
                   help="TFRecord file or directory of shards")
    p.add_argument("--schema_hint", default=None,
                   help="struct<name:type,...> schema for the input records")
    p.add_argument("--input_mapping", required=True,
                   help="JSON or col=input,... mapping of record columns "
                        "to predictor inputs")
    p.add_argument("--output_mapping", default=None,
                   help="JSON or output=col,... mapping of predictor "
                        "outputs to result columns")
    p.add_argument("--output", required=True,
                   help="output directory for JSON-line part files")
    p.add_argument("--batch_size", type=int, default=128)
    p.add_argument("--schedule", choices=("static", "continuous"),
                   default="static",
                   help="batching schedule: 'static' fixed-size "
                        "batches, or 'continuous' in-flight batching "
                        "for generation exports (slot-level KV-cache "
                        "scheduler; batch_size = in-flight slots — "
                        "see docs/serving.md)")
    args = p.parse_args(argv)

    from tensorflowonspark_tpu.data import interchange

    rows, schema = interchange.load_tfrecords(
        args.input, schema=args.schema_hint
    )
    logger.info("loaded %d rows (schema: %s)", len(rows),
                interchange.format_schema(schema))
    predict = load_predictor(args.export_dir)
    input_mapping = _parse_mapping(args.input_mapping)
    output_mapping = (
        _parse_mapping(args.output_mapping) if args.output_mapping else None
    )

    from tensorflowonspark_tpu.utils import fs as fs_utils

    fs_utils.makedirs(args.output)
    out_path = fs_utils.join(args.output, "part-00000.jsonl")
    count = 0
    sched_stats = {}
    with fs_utils.open_file(out_path, "w") as f:
        for out_row in predict_rows(
            predict, rows, input_mapping, output_mapping,
            args.batch_size, schedule=args.schedule, stats=sched_stats,
        ):
            f.write(json.dumps(out_row, default=_json_default) + "\n")
            count += 1
    if sched_stats.get("latency_sec"):
        lat = sorted(sched_stats["latency_sec"].values())
        logger.info(
            "continuous schedule: %d admitted over %d chunks, "
            "per-request latency p50=%.1fms p99=%.1fms",
            sched_stats["admitted"], sched_stats["chunks"],
            1e3 * lat[len(lat) // 2],
            1e3 * lat[min(len(lat) - 1, int(len(lat) * 0.99))],
        )
    logger.info("wrote %d predictions to %s", count, out_path)
    return count


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
