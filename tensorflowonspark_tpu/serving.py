"""Single-host batch serving path + CLI.

Re-designed from the reference's JVM serving stack — ``TFModel.scala``
(per-executor singleton ``SavedModelBundle`` cache + Row→Tensor→Row
conversion, reference: src/main/scala/com/yahoo/tensorflowonspark/
TFModel.scala:24-29,51-239,257-281) and the ``Inference.scala`` CLI
(reference: Inference.scala:27-79).  The TPU equivalents:

- a *serving export* is an orbax params directory plus ``metadata.json``
  written by :func:`tensorflowonspark_tpu.checkpoint.save_for_serving`
  (the SavedModel role);
- the "graph" half of a SavedModel is a **predictor builder**: a plain
  function ``builder(params, config) -> predict`` where
  ``predict(batch: dict[str, np.ndarray]) -> dict[str, np.ndarray]``.
  It is named in the export metadata as ``model_ref``
  (``"pkg.module:attr"``) so a bare export directory is self-describing
  the way a SavedModel is, or passed directly as a callable;
- batches are padded to a fixed ``batch_size`` so the jitted predict
  compiles once (XLA static shapes), then outputs are truncated — the
  TFMU-friendly version of the reference's per-batch ``session.run``;
- the CLI reads TFRecords through the native codec
  (:mod:`tensorflowonspark_tpu.data.tfrecord` backed by
  ``native/tfrecord_codec.cc``) and writes JSON lines, mirroring
  ``Inference --export_dir --input --schema_hint --input_mapping
  --output_mapping --output`` (reference: Inference.scala:30-44).

Run the CLI with ``python -m tensorflowonspark_tpu.serving ...``.
"""

import importlib
import json
import logging
import os

import numpy as np

logger = logging.getLogger(__name__)

#: Per-process predictor cache keyed by (export_dir, builder digest) —
#: the reference cached one SavedModelBundle per executor JVM
#: (TFModel.scala:24-29,257-263) / one session per python worker
#: (pipeline.py:492-496).
_PREDICTOR_CACHE = {}


def _builder_key(builder):
    """Content digest of a builder callable, stable across pickling —
    ``id()`` would miss on every per-job unpickled copy and can collide
    after GC address reuse."""
    if builder is None:
        return None
    import hashlib

    try:
        import cloudpickle as _cp

        return hashlib.sha256(_cp.dumps(builder)).hexdigest()
    except Exception:  # noqa: BLE001 - unpicklable builder: don't cache
        return object()  # unique → never a cache hit


def resolve_ref(ref):
    """Resolve a ``"pkg.module:attr"`` reference string to the object."""
    module_name, _, attr = ref.partition(":")
    if not module_name or not attr:
        raise ValueError(
            "model_ref must look like 'pkg.module:attr', got {0!r}".format(ref)
        )
    obj = importlib.import_module(module_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def load_predictor(export_dir, builder=None, use_cache=True):
    """Load a serving export and return its ``predict`` callable.

    Args:
      export_dir: directory written by
        :func:`~tensorflowonspark_tpu.checkpoint.save_for_serving`.
      builder: optional ``builder(params, config) -> predict`` override;
        defaults to the export metadata's ``model_ref``.
      use_cache: reuse a previously built predictor for the same export
        (the per-process singleton the reference kept,
        TFModel.scala:257-263).
    """
    key = (os.path.abspath(os.fspath(export_dir)), _builder_key(builder))
    if use_cache and key in _PREDICTOR_CACHE:
        return _PREDICTOR_CACHE[key]

    from tensorflowonspark_tpu.checkpoint import load_for_serving

    params, meta = load_for_serving(export_dir)
    if builder is None:
        ref = meta.get("model_ref")
        if not ref:
            raise ValueError(
                "export {0} has no model_ref metadata and no builder was "
                "given; write it via save_for_serving(..., extra_metadata="
                "{{'model_ref': 'pkg.module:builder'}})".format(export_dir)
            )
        builder = resolve_ref(ref)
    predict = builder(params, meta.get("model_config") or {})
    if use_cache:
        _PREDICTOR_CACHE[key] = predict
    return predict


# ----------------------------------------------------------------------
# batched row prediction (Row -> device array -> Row, the
# batch2tensors/tensors2batch role, TFModel.scala:51-239)
# ----------------------------------------------------------------------


def _stack_column(values):
    return np.stack([np.asarray(v) for v in values])


def _stack_ragged_left(values, pad_value, multiple=1):
    """Stack ragged 1-D rows by LEFT-padding to the batch max length
    (rounded up to ``multiple`` — shape BUCKETING, so the jitted
    generate program retraces once per bucket instead of once per
    unique prompt length); returns ``(stacked [n, max_len],
    pad_counts [n] int32)``.  Left-padding keeps every row's real
    tokens ending at the same position, so the compiled decode scan
    starts uniformly (the model masks the pad slots via
    ``pad_start``)."""
    arrs = [np.asarray(v) for v in values]
    if any(a.ndim != 1 for a in arrs):
        raise ValueError(
            "ragged padding supports 1-D token rows; got shapes %s"
            % ([a.shape for a in arrs],)
        )
    max_len = max(a.shape[0] for a in arrs)
    max_len = ((max_len + multiple - 1) // multiple) * multiple
    pads = np.asarray([max_len - a.shape[0] for a in arrs], np.int32)
    out = np.full((len(arrs), max_len), pad_value, arrs[0].dtype)
    for i, a in enumerate(arrs):
        if a.shape[0]:
            out[i, max_len - a.shape[0]:] = a
    return out, pads


def predict_rows(
    predict,
    rows,
    input_mapping,
    output_mapping=None,
    batch_size=128,
    pad_to_batch=True,
):
    """Run ``predict`` over dict-rows in fixed-size batches; yields
    output dict-rows.

    Args:
      predict: ``fn(batch: dict) -> dict`` of batched arrays.
      rows: iterable of dict rows.
      input_mapping: ``{column: input_name}`` — which row columns feed
        which predictor inputs (reference: TFParams.scala:27-33).
      output_mapping: ``{output_name: column}`` for the emitted rows;
        defaults to the predictor's own output names.
      batch_size: rows per predict call (reference default 128,
        TFParams.scala:14-18).
      pad_to_batch: zero-pad the final short batch so the jitted
        predict never sees a new shape (outputs are truncated back).
    """
    cols = sorted(input_mapping)
    buf = []
    # generation predictors declare ragged columns (prompts of varying
    # length) via ``predict.column_padding = {input_name: pad_value}``;
    # those stack left-padded and ship a ``<input>_pad`` count column
    # the model uses to mask the pad slots
    column_padding = getattr(predict, "column_padding", None) or {}

    def _flush(chunk):
        n = len(chunk)
        batch = {}
        for c in cols:
            name = input_mapping[c]
            values = [r[c] for r in chunk]
            if name in column_padding:
                batch[name], batch[name + "_pad"] = _stack_ragged_left(
                    values, column_padding[name],
                    getattr(predict, "pad_multiple", 1),
                )
            else:
                batch[name] = _stack_column(values)
        if pad_to_batch and n < batch_size:
            batch = {
                k: np.concatenate(
                    [v, np.zeros((batch_size - n,) + v.shape[1:], v.dtype)]
                )
                for k, v in batch.items()
            }
        out = predict(batch)
        out = {k: np.asarray(v)[:n] for k, v in out.items()}
        if output_mapping:
            missing = [n_ for n_ in output_mapping if n_ not in out]
            if missing:
                # fail fast like the reference's signature lookup
                # (pipeline.py:559-564), not silent empty rows
                raise KeyError(
                    "output_mapping names {0} not produced by the "
                    "predictor (outputs: {1})".format(missing, sorted(out))
                )
            out = {col: out[name] for name, col in output_mapping.items()}
        for i in range(n):
            yield {k: v[i] for k, v in out.items()}

    for row in rows:
        buf.append(row)
        if len(buf) == batch_size:
            for r in _flush(buf):
                yield r
            buf = []
    if buf:
        for r in _flush(buf):
            yield r


# ----------------------------------------------------------------------
# CLI (Inference.scala equivalent)
# ----------------------------------------------------------------------


def _parse_mapping(text):
    """Accept JSON (``{"col":"x"}``) or ``col=x,col2=y`` shorthand."""
    text = text.strip()
    if text.startswith("{"):
        return json.loads(text)
    out = {}
    for part in text.split(","):
        k, _, v = part.partition("=")
        if not _:
            raise ValueError("mapping entries must be key=value: " + part)
        out[k.strip()] = v.strip()
    return out


def _json_default(o):
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, (np.floating, np.integer)):
        return o.item()
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    raise TypeError("not JSON serializable: {0}".format(type(o)))


def main(argv=None):
    """Batch-inference CLI (reference: Inference.scala:27-79): load a
    serving export, read TFRecords, write predictions as JSON lines."""
    import argparse

    p = argparse.ArgumentParser(
        prog="tensorflowonspark_tpu.serving",
        description="Batch inference over TFRecords with a serving export",
    )
    p.add_argument("--export_dir", required=True,
                   help="serving export directory (save_for_serving output)")
    p.add_argument("--input", required=True,
                   help="TFRecord file or directory of shards")
    p.add_argument("--schema_hint", default=None,
                   help="struct<name:type,...> schema for the input records")
    p.add_argument("--input_mapping", required=True,
                   help="JSON or col=input,... mapping of record columns "
                        "to predictor inputs")
    p.add_argument("--output_mapping", default=None,
                   help="JSON or output=col,... mapping of predictor "
                        "outputs to result columns")
    p.add_argument("--output", required=True,
                   help="output directory for JSON-line part files")
    p.add_argument("--batch_size", type=int, default=128)
    args = p.parse_args(argv)

    from tensorflowonspark_tpu.data import interchange

    rows, schema = interchange.load_tfrecords(
        args.input, schema=args.schema_hint
    )
    logger.info("loaded %d rows (schema: %s)", len(rows),
                interchange.format_schema(schema))
    predict = load_predictor(args.export_dir)
    input_mapping = _parse_mapping(args.input_mapping)
    output_mapping = (
        _parse_mapping(args.output_mapping) if args.output_mapping else None
    )

    from tensorflowonspark_tpu.utils import fs as fs_utils

    fs_utils.makedirs(args.output)
    out_path = fs_utils.join(args.output, "part-00000.jsonl")
    count = 0
    with fs_utils.open_file(out_path, "w") as f:
        for out_row in predict_rows(
            predict, rows, input_mapping, output_mapping, args.batch_size
        ):
            f.write(json.dumps(out_row, default=_json_default) + "\n")
            count += 1
    logger.info("wrote %d predictions to %s", count, out_path)
    return count


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
