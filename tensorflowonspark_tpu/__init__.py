"""TensorFlowOnSpark-TPU: a TPU-native distributed ML framework.

A ground-up redesign of the capabilities of TensorFlowOnSpark
(reference: tensorflowonspark/ @ v2.2.0) for TPU pods:

- Cluster orchestration: turn a fleet of executors (Spark or local
  processes) into a JAX/XLA accelerator cluster with one API call
  (reference: tensorflowonspark/TFCluster.py).
- Data bridging: stream RDD/DataFrame/iterator data into device-resident
  JAX arrays and pull results back (reference: tensorflowonspark/TFNode.py
  DataFeed, TFSparkNode.py train/inference paths).
- ML pipeline Estimator/Model wrappers (reference: tensorflowonspark/pipeline.py).
- TFRecord <-> columnar-data interchange (reference: tensorflowonspark/dfutil.py,
  src/main/scala/com/yahoo/tensorflowonspark/DFUtil.scala).
- First-class mesh parallelism the reference delegated or lacked:
  DP/TP/PP/SP(ring attention, Ulysses)/EP over a jax.sharding.Mesh with
  XLA collectives riding ICI.

The compute core is JAX/XLA/pallas; the orchestration layer is pure
Python with a C++ fast path for the TFRecord codec.
"""

import logging

# Library etiquette: never configure the root logger at import time.
# Framework-owned processes (executor runners, serving CLI) call
# ``setup_logging`` in their own bootstrap instead.
logging.getLogger(__name__).addHandler(logging.NullHandler())

LOG_FORMAT = "%(asctime)s %(levelname)s (%(threadName)s-%(process)d) %(message)s"


def setup_logging(level=logging.INFO):
    """Opt-in root logging config for framework-owned processes
    (the reference did this unconditionally at import,
    tensorflowonspark/__init__.py:3; we make it explicit)."""
    logging.basicConfig(level=level, format=LOG_FORMAT)


__version__ = "0.1.0"

_LAZY = {
    "InputMode": ("tensorflowonspark_tpu.cluster.cluster", "InputMode"),
    "TPUCluster": ("tensorflowonspark_tpu.cluster.cluster", "TPUCluster"),
    # Drop-in style alias for users migrating from the reference API surface.
    "TFCluster": ("tensorflowonspark_tpu.cluster.cluster", "TPUCluster"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        try:
            return getattr(importlib.import_module(module), attr)
        except ImportError as e:
            # Per the module-__getattr__ contract, only AttributeError may
            # escape (hasattr() must not crash on a broken lazy target).
            raise AttributeError(
                "lazy attribute {0!r} failed to import: {1}".format(name, e)
            ) from e
    raise AttributeError("module {0!r} has no attribute {1!r}".format(__name__, name))
