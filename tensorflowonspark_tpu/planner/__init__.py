"""Cost-model auto-parallelism planner with live re-planning
(ISSUE 18).

Three layers:

- :mod:`~tensorflowonspark_tpu.planner.cost` — measured calibration
  probes (cached per host; analytic roofline fallback) feeding a cost
  model that prices candidate configs as modeled critical paths over
  :func:`tensorflowonspark_tpu.forensics.critical_path`;
- :mod:`~tensorflowonspark_tpu.planner.planner` — the search layer:
  enumerate the legal knob lattice (pruned by the repo's own
  validators), pick the min-modeled-critical-path point, log every
  decision (``planner_decision`` journal events; ``python -m
  tensorflowonspark_tpu.planner explain`` renders the story);
- :mod:`~tensorflowonspark_tpu.planner.replan` — the live re-planner:
  DCN-RTT drift retunes ``push_every``, prompt-mix shift regrows the
  slot buckets, page occupancy resizes ``kv_pages`` — all through the
  existing safe actuation seams, every change an audited ``replan``
  journal event.

Entry points: ``config="auto"``/``{"auto": True}`` on
``serving_builder``/``load_predictor``; ``plan(workload="train")``
for the hier-PS cadence; the knob registry in
:mod:`~tensorflowonspark_tpu.planner.knobs` doubles as the builders'
unknown-key validation surface.
"""

from tensorflowonspark_tpu.planner.cost import (
    ROOFLINE,
    CostModel,
    DeviceProfile,
    calibrate,
    measure_dcn_rtt,
    probes_enabled,
)
from tensorflowonspark_tpu.planner.knobs import (
    KNOBS,
    UnknownKnobError,
    planner_owned,
    render_table,
    validate_keys,
)
from tensorflowonspark_tpu.planner.planner import (
    Plan,
    auto_serving_config,
    plan,
    validate_candidate,
)
from tensorflowonspark_tpu.planner.replan import LivePlanner, Replan

__all__ = [
    "ROOFLINE",
    "CostModel",
    "DeviceProfile",
    "KNOBS",
    "LivePlanner",
    "Plan",
    "Replan",
    "UnknownKnobError",
    "auto_serving_config",
    "calibrate",
    "measure_dcn_rtt",
    "plan",
    "planner_owned",
    "probes_enabled",
    "render_table",
    "validate_candidate",
    "validate_keys",
]
