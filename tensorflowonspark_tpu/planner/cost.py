"""The planner's cost model: measured probes in, modeled critical
paths out (ISSUE 18).

Pricing reuses the repo's OWN critical-path engine
(:func:`tensorflowonspark_tpu.forensics.critical_path`, PR 11): a
candidate config is rendered as the synthetic span tree its execution
would record — queue wait, prefill, decode chunks with their HBM /
collective / dispatch-overhead components, or ICI step windows
overlapped with DCN pushes — and the walk over that tree gives the
modeled end-to-end seconds plus the binding phase.  The span
*self-times* come from a short startup calibration pass
(:func:`calibrate`): micro-bench matmul, memory-bandwidth, collective
and DCN-RTT probes, cached per host so repeat runs skip the bench.
With probes disabled (``TFOS_PLANNER_PROBES=0`` or
``calibrate(probes=False)``) an analytic roofline table prices the
same spans — same search, coarser numbers.
"""

import json
import logging
import os
import socket
import time

from tensorflowonspark_tpu import forensics, telemetry

logger = logging.getLogger(__name__)

#: analytic roofline fallback per platform: (matmul GFLOP/s per
#: device, HBM/mem GB/s, collective latency floor sec, DCN RTT sec).
#: TPU numbers are the v4 datasheet ballpark; CPU numbers a
#: conservative laptop-class core — the point of the fallback is
#: RANKING candidates, not absolute seconds.
ROOFLINE = {
    "tpu": (137000.0, 1200.0, 15e-6, 1e-3),
    "gpu": (60000.0, 900.0, 20e-6, 1e-3),
    "cpu": (40.0, 8.0, 50e-6, 0.5e-3),
}

def _registry():
    # call-time lookup (the serving_engine idiom): handles taken at
    # import time would go stale across test registry resets
    return telemetry.get_registry()


class DeviceProfile(object):
    """What one host's devices measure: the numbers every span price
    derives from.  ``source`` records how they were obtained —
    ``probe`` (micro-bench), ``cache`` (per-host JSON), ``roofline``
    (analytic fallback)."""

    FIELDS = ("platform", "device_count", "matmul_gflops", "mem_gbs",
              "collective_lat_sec", "dcn_rtt_sec", "source", "host")

    def __init__(self, platform, device_count, matmul_gflops, mem_gbs,
                 collective_lat_sec, dcn_rtt_sec, source="roofline",
                 host=None):
        self.platform = str(platform)
        self.device_count = int(device_count)
        self.matmul_gflops = float(matmul_gflops)
        self.mem_gbs = float(mem_gbs)
        self.collective_lat_sec = float(collective_lat_sec)
        self.dcn_rtt_sec = float(dcn_rtt_sec)
        self.source = source
        self.host = host or socket.gethostname()

    def to_dict(self):
        return {f: getattr(self, f) for f in self.FIELDS}

    @classmethod
    def from_dict(cls, d):
        return cls(**{f: d[f] for f in cls.FIELDS if f in d})

    def __repr__(self):
        return ("DeviceProfile({0} x{1}, {2:.1f} GFLOP/s, {3:.1f} "
                "GB/s, dcn {4:.2f}ms, {5})").format(
                    self.platform, self.device_count,
                    self.matmul_gflops, self.mem_gbs,
                    1e3 * self.dcn_rtt_sec, self.source)


def probes_enabled():
    """Probe gate: ``TFOS_PLANNER_PROBES=0`` forces the analytic
    roofline fallback (CI determinism; air-gapped startup paths)."""
    return os.environ.get("TFOS_PLANNER_PROBES", "1") not in (
        "0", "false", "off"
    )


def _cache_path(platform, device_count):
    base = os.environ.get("TFOS_PLANNER_CACHE")
    if base is None:
        base = os.path.join(
            os.path.expanduser("~"), ".cache", "tfos_planner"
        )
    return os.path.join(base, "profile-{0}-{1}-x{2}.json".format(
        socket.gethostname(), platform, device_count
    ))


def _probe_matmul(n=384, repeats=3):
    """Best-of-N jitted f32 matmul GFLOP/s on the default backend."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((n, n), jnp.float32)
    f = jax.jit(lambda a: a @ a)
    f(x).block_until_ready()  # compile off the clock
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return (2.0 * n ** 3 / best) / 1e9


def _probe_mem(mb=32, repeats=3):
    """Streaming-read GB/s: sum over a buffer too big for L2."""
    import jax
    import jax.numpy as jnp

    n = mb * (1 << 20) // 4
    x = jnp.ones((n,), jnp.float32)
    f = jax.jit(jnp.sum)
    f(x).block_until_ready()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return (4.0 * n / best) / 1e9


def _probe_collective(repeats=3):
    """Small all-reduce latency floor across the local devices; None
    on a single device (the roofline constant fills in)."""
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    if len(devs) < 2:
        return None
    f = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")
    x = jnp.ones((len(devs), 8), jnp.float32)
    f(x).block_until_ready()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return best


def measure_dcn_rtt(addr, samples=3, timeout=5.0, payload=b"tfos-rtt"):
    """Measured cross-pod RTT: TCP round-trips of a tiny payload to an
    echo endpoint ``(host, port)``.  This is the live re-planner's
    drift sensor — in the chaos e2e the endpoint sits behind a
    ``testing.chaos.TcpGremlin``, so an injected ``delay`` IS a
    measured drift.  Returns the best (minimum) of ``samples`` — RTT
    floors, not tail noise, drive the cadence rule."""
    best = float("inf")
    for _ in range(max(1, int(samples))):
        with socket.create_connection(addr, timeout=timeout) as s:
            t0 = time.perf_counter()
            s.sendall(payload)
            got = b""
            while len(got) < len(payload):
                chunk = s.recv(len(payload) - len(got))
                if not chunk:
                    break
                got += chunk
            best = min(best, time.perf_counter() - t0)
    return best


def calibrate(probes=None, cache=True, dcn_addr=None, force=False):
    """The startup calibration pass -> :class:`DeviceProfile`.

    Probe results are cached per (host, platform, device count) under
    ``~/.cache/tfos_planner`` (``TFOS_PLANNER_CACHE`` overrides), so
    only the first run on a host pays the micro-bench.  ``probes=
    False`` (or ``TFOS_PLANNER_PROBES=0``) returns the
    :data:`ROOFLINE` row for the platform unmeasured.  ``dcn_addr``
    optionally replaces the roofline DCN RTT with a measured TCP
    round-trip (:func:`measure_dcn_rtt`)."""
    import jax

    devs = jax.devices()
    platform = devs[0].platform
    n = len(devs)
    base = ROOFLINE.get(platform, ROOFLINE["cpu"])
    if probes is None:
        probes = probes_enabled()
    if not probes:
        return DeviceProfile(platform, n, *base, source="roofline")
    path = _cache_path(platform, n)
    if cache and not force and os.path.exists(path):
        try:
            with open(path) as f:
                prof = DeviceProfile.from_dict(json.load(f))
            prof.source = "cache"
            if dcn_addr is not None:
                prof.dcn_rtt_sec = measure_dcn_rtt(dcn_addr)
            return prof
        except (OSError, ValueError, KeyError, TypeError):
            pass  # unreadable cache: re-probe and rewrite
    t0 = time.perf_counter()
    _registry().counter("planner.calibrations").inc()
    coll = _probe_collective()
    prof = DeviceProfile(
        platform, n,
        matmul_gflops=_probe_matmul(),
        mem_gbs=_probe_mem(),
        collective_lat_sec=coll if coll is not None else base[2],
        dcn_rtt_sec=(
            measure_dcn_rtt(dcn_addr) if dcn_addr is not None
            else base[3]
        ),
        source="probe",
    )
    _registry().histogram("planner.calibration_sec").observe(
        time.perf_counter() - t0
    )
    if cache:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                json.dump(prof.to_dict(), f)
        except OSError as e:
            logger.debug("planner: profile cache not writable: %s", e)
    return prof


# ----------------------------------------------------------------------
# candidate pricing
# ----------------------------------------------------------------------


def _bytes_per_weight(weights):
    return {"int8": 1.0, "int4": 0.5}.get(weights, 4.0)


def _param_count(mc):
    """Approximate transformer parameter count from config dims."""
    E = int(mc.get("embed_dim", 64))
    L = int(mc.get("num_layers", 2))
    H = int(mc.get("num_heads", 4))
    Hkv = int(mc.get("num_kv_heads", H))
    D = int(mc.get("head_dim", E // max(1, H)))
    F = int(mc.get("mlp_dim", 4 * E))
    V = int(mc.get("vocab_size", 256))
    attn = E * H * D + 2 * E * Hkv * D + H * D * E
    return V * E + L * (attn + 2 * E * F)


def _kv_bytes_per_token(mc):
    L = int(mc.get("num_layers", 2))
    H = int(mc.get("num_heads", 4))
    Hkv = int(mc.get("num_kv_heads", H))
    D = int(mc.get("head_dim", 16))
    per = 1.0 if mc.get("cache_dtype") == "int8" else 4.0
    return 2.0 * L * Hkv * D * per


class CostModel(object):
    """Prices candidate configs as modeled critical paths over the
    measured :class:`DeviceProfile`.

    Every ``price_*`` method builds the synthetic span tree the
    candidate would record (ids/parents/t0/dur — the tracer's record
    shape) and runs :func:`forensics.critical_path` over it; the
    result carries ``total_sec``, the walked ``path``, and
    ``bottleneck`` — the component with the largest modeled
    self-time, the planner's "why this config" answer."""

    #: fixed host-side cost per engine dispatch (queue pop, stack,
    #: transfer glue) — measured ~0.5-2ms on the CPU substrate; the
    #: chunk_size knob trades this against admit latency
    DISPATCH_OVERHEAD_SEC = 1e-3

    def __init__(self, profile):
        self.profile = profile

    # -- span plumbing --------------------------------------------------

    @staticmethod
    def _walk(spans, components):
        cp = forensics.critical_path(spans)
        bottleneck = None
        if components:
            bottleneck = max(components.items(), key=lambda kv: kv[1])[0]
        return {
            "total_sec": cp["total_sec"],
            "path": cp["path"],
            "dominant_phase": cp["dominant_phase"],
            "bottleneck": bottleneck,
            "components": components,
        }

    # -- serving --------------------------------------------------------

    def price_serving(self, model_config, cand, hint):
        """Modeled per-request seconds for a continuous-batching
        serving candidate at the hinted workload.

        Spans: ``request`` > (``queue_wait``, ``prefill``, ``decode``)
        with ``decode`` > (``decode_hbm``, ``decode_collective``,
        ``dispatch_overhead``) — decode components start together and
        the one ending last is the link the walk descends into."""
        p = self.profile
        mc = model_config
        tp = int(cand.get("tp") or 1)
        slots = int(cand.get("batch_size", 8))
        chunk = int(cand.get("chunk_size", 16))
        weights = cand.get("weights") or cand.get("quantize")
        prompt = float(hint.get("prompt_tokens", 32))
        max_new = int(
            cand.get("max_new_tokens")
            or mc.get("max_new_tokens") or 16
        )
        shared = float(hint.get("shared_prefix_frac", 0.0))
        if cand.get("prefix_cache"):
            prompt = prompt * (1.0 - 0.9 * shared)

        params = _param_count(mc)
        wbytes = params * _bytes_per_weight(weights)
        gflops = p.matmul_gflops * tp
        # prefill: compute-bound batched matmuls over the prompt
        prefill = (2.0 * params * prompt * slots) / (gflops * 1e9)
        if tp > 1:
            prefill += int(mc.get("num_layers", 2)) * p.collective_lat_sec
        # decode: bandwidth-bound — every step re-reads the weights
        # (sharded over tp) and the resident KV of all slots
        ctx = prompt + 0.5 * max_new
        kv = _kv_bytes_per_token(mc) * ctx * slots
        paged_factor = 1.1 if cand.get("kv_layout") == "paged" else 1.0
        step = ((wbytes / tp + kv * paged_factor)
                / (p.mem_gbs * 1e9))
        coll = (int(mc.get("num_layers", 2)) * p.collective_lat_sec
                if tp > 1 else 0.0)
        hbm_total = max_new * step
        coll_total = max_new * coll
        n_chunks = max(1, (max_new + chunk - 1) // chunk)
        overhead = n_chunks * self.DISPATCH_OVERHEAD_SEC
        decode = hbm_total + coll_total + overhead
        # queue wait under the hinted offered load: rows queue while a
        # full generation turns over the slots
        qps = float(hint.get("qps", 0.0))
        service = max(1e-9, prefill + decode)
        queue = 0.0
        if qps > 0:
            util = qps * service / max(1, slots)
            queue = service * min(8.0, util ** 2 / max(1e-6, 1 - util)) \
                if util < 1 else 8.0 * service
        spans = [
            {"id": 1, "parent": None, "name": "request", "t0": 0.0,
             "dur": queue + prefill + decode, "trace": "plan"},
            {"id": 2, "parent": 1, "name": "queue_wait", "t0": 0.0,
             "dur": queue, "trace": "plan"},
            {"id": 3, "parent": 1, "name": "prefill", "t0": queue,
             "dur": prefill, "trace": "plan"},
            {"id": 4, "parent": 1, "name": "decode",
             "t0": queue + prefill, "dur": decode, "trace": "plan"},
            {"id": 5, "parent": 4, "name": "decode_hbm",
             "t0": queue + prefill, "dur": hbm_total, "trace": "plan"},
            {"id": 6, "parent": 4, "name": "decode_collective",
             "t0": queue + prefill, "dur": coll_total, "trace": "plan"},
            {"id": 7, "parent": 4, "name": "dispatch_overhead",
             "t0": queue + prefill, "dur": overhead, "trace": "plan"},
        ]
        return self._walk(spans, {
            "queue_wait": queue, "prefill": prefill,
            "decode_hbm": hbm_total, "decode_collective": coll_total,
            "dispatch_overhead": overhead,
        })

    # -- training (hierarchical data parallel) --------------------------

    def price_train(self, model_config, cand, hint):
        """Modeled per-step seconds for a hier-PS training candidate.

        Spans: one steady-state DCN ``window`` > (``ici_steps``,
        ``dcn_push``) — the push overlaps compute across
        ``max_inflight`` windows, so its effective span is
        ``dcn_time / max_inflight``; whichever child ends last is the
        binding constraint (the docs/communication.md cadence rule,
        priced instead of hand-applied)."""
        p = self.profile
        pe = int(cand.get("push_every", 8))
        inflight = int(cand.get("max_inflight", 2))
        batch = float(hint.get("batch", 8))
        seq = float(hint.get("seq_len", 128))
        params = _param_count(model_config)
        flops = 6.0 * params * batch * seq  # fwd + bwd
        step = flops / (p.matmul_gflops * p.device_count * 1e9)
        step += p.collective_lat_sec  # per-step ICI all-reduce floor
        grad_bytes = 4.0 * params * float(
            hint.get("dcn_compression", 1.0)
        )
        dcn_bw = float(hint.get("dcn_gbs", 1.0)) * 1e9
        dcn = p.dcn_rtt_sec + grad_bytes / dcn_bw
        ici = pe * step
        dcn_eff = dcn / max(1, inflight)
        window = max(ici, dcn_eff)
        spans = [
            {"id": 1, "parent": None, "name": "window", "t0": 0.0,
             "dur": window, "trace": "plan"},
            {"id": 2, "parent": 1, "name": "ici_steps", "t0": 0.0,
             "dur": ici, "trace": "plan"},
            {"id": 3, "parent": 1, "name": "dcn_push", "t0": 0.0,
             "dur": dcn_eff, "trace": "plan"},
        ]
        priced = self._walk(spans, {
            "ici_steps": ici, "dcn_push": dcn_eff,
        })
        priced["per_step_sec"] = window / pe
        priced["step_sec"] = step
        # the cadence rule as a priced quantity: windows shorter than
        # the RTT serialize on acks — surfaced so explain() can show
        # WHY a push_every was rejected, not just that it cost more
        priced["cadence_ok"] = ici > p.dcn_rtt_sec
        return priced
