"""``python -m tensorflowonspark_tpu.planner`` — the planner CLI.

``explain`` plans a workload and prints the chosen point, the
runner-up, and the modeled gap (ISSUE 18's "why is the config what it
is" surface); ``knobs`` prints the registry table docs/autotune.md
embeds.
"""

import argparse
import json
import sys


def _parse_json(text, what):
    if not text:
        return {}
    try:
        got = json.loads(text)
    except ValueError as e:
        raise SystemExit("bad {0} JSON: {1}".format(what, e))
    if not isinstance(got, dict):
        raise SystemExit("{0} must be a JSON object".format(what))
    return got


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tensorflowonspark_tpu.planner",
        description="cost-model auto-parallelism planner",
    )
    sub = ap.add_subparsers(dest="cmd")
    ex = sub.add_parser(
        "explain",
        help="plan a workload and print the decision story",
    )
    ex.add_argument("--workload", choices=("serving", "train"),
                    default="serving")
    ex.add_argument("--devices", type=int, default=None,
                    help="device count (default: local jax backend)")
    ex.add_argument("--config", default="",
                    help="model config JSON (TransformerConfig fields "
                         "+ any pinned knobs)")
    ex.add_argument("--hint", default="",
                    help="workload hint JSON (prompt_tokens, qps, "
                         "mixed, shared_prefix_frac, ...)")
    ex.add_argument("--no-probes", action="store_true",
                    help="use the analytic roofline instead of "
                         "calibration probes")
    ex.add_argument("--json", action="store_true",
                    help="emit the plan summary as JSON")
    sub.add_parser("knobs", help="print the knob registry table")
    args = ap.parse_args(argv)

    from tensorflowonspark_tpu import planner as P

    if args.cmd == "knobs":
        print(P.render_table())
        return 0
    if args.cmd != "explain":
        ap.print_help()
        return 2

    config = _parse_json(args.config, "--config")
    hint = _parse_json(args.hint, "--hint")
    owned = {k.name for k in P.planner_owned()}
    overrides = {k: v for k, v in config.items() if k in owned}
    profile = P.calibrate(probes=False) if args.no_probes else None
    p = P.plan(
        model_config=config, workload=args.workload,
        device_count=args.devices, hint=hint, profile=profile,
        overrides=overrides,
    )
    if args.json:
        print(json.dumps(p.summary(), indent=2, sort_keys=True))
    else:
        print(p.explain())
    return 0


if __name__ == "__main__":
    sys.exit(main())
