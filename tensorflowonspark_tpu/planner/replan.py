"""The live re-planner: keep the planned config true as the world
drifts (ISSUE 18 part 3).

A :class:`LivePlanner` consumes the sensors the fleet already ships —
the health plane's :class:`~tensorflowonspark_tpu.telemetry.health.
TimeSeriesStore`, the usage ledger's mirror counters, and a measured
DCN-RTT probe (:func:`~tensorflowonspark_tpu.planner.cost.
measure_dcn_rtt`) — and drives three re-plan triggers:

- **DCN-RTT drift** -> retune ``push_every`` (the
  docs/communication.md cadence rule, ``push_every x step_time >
  RTT``, re-applied against the measured RTT);
- **prompt-length-mix shift** -> regrow the slot buckets
  (``max_prompt_len``/cache geometry — applied through the
  hot-swap/quiesce seam the actuator wraps);
- **page-pool occupancy** -> resize ``kv_pages`` (same seam: pool
  geometry is a decoder rebuild).

Changes go ONLY through the actuator callbacks the integrator binds
(``set_push_every`` is :meth:`~tensorflowonspark_tpu.parallel.
hier_ps.HierTrainer.set_push_every`, applied at the window boundary;
geometry actuators wrap the engine's quiesce/hot-swap machinery;
scalar engine knobs go through ``ServingEngine.request_retune``,
applied between decode chunks).  Every applied re-plan is a typed
``replan`` journal event carrying the triggering evidence — the
measured values, the threshold, the sustain count — so ``forensics
explain`` answers "why did the config change?".  Hysteresis
(``sustain`` consecutive asserting rounds) and per-trigger cooldowns
bound the churn: one drift episode is ONE re-plan, not a flap storm
(asserted by the chaos e2e: an injected ``TcpGremlin.delay`` drift
triggers exactly one audited ``push_every`` re-plan).
"""

import logging
import math
import time

from tensorflowonspark_tpu import telemetry

logger = logging.getLogger(__name__)


class Replan(object):
    """One applied (or attempted) re-plan decision."""

    __slots__ = ("trigger", "knob", "old", "new", "evidence",
                 "applied", "error")

    def __init__(self, trigger, knob, old, new, evidence,
                 applied=False, error=None):
        self.trigger = trigger
        self.knob = knob
        self.old = old
        self.new = new
        self.evidence = dict(evidence)
        self.applied = applied
        self.error = error

    def to_dict(self):
        return {
            "trigger": self.trigger, "knob": self.knob,
            "old": self.old, "new": self.new,
            "evidence": self.evidence, "applied": self.applied,
            "error": self.error,
        }


class LivePlanner(object):
    """Periodic trigger evaluation over live sensors.

    Args:
      baseline: the startup :class:`~tensorflowonspark_tpu.planner.
        cost.DeviceProfile` (its ``dcn_rtt_sec`` anchors drift) — or a
        plain float RTT.
      actuators: dict binding trigger outputs to safe seams —
        ``push_every``: fn(new) applied at the window boundary
        (:meth:`HierTrainer.set_push_every`); ``slot_buckets``:
        fn(new_max_prompt_len) through hot-swap/quiesce;
        ``kv_pages``: fn(new_pages) through the same seam.  A missing
        binding disables that trigger's actuation (the decision is
        still journaled as unapplied).
      rtt_probe: fn() -> measured RTT seconds (e.g. ``lambda:
        measure_dcn_rtt(addr)``); None disables the RTT trigger.
      store: a TimeSeriesStore for the mix/occupancy sensors; None
        disables those triggers unless explicit sensor fns are given.
      prompt_mix_fn: fn() -> mean prompt tokens over the recent
        window (default: derived from the usage-ledger mirror via
        ``store``-less callers passing their own).
      occupancy_fn: fn() -> page-pool occupancy fraction [0, 1].
      step_time_fn: fn() -> measured seconds per training step (for
        the cadence rule); default: the planned step time.
    """

    def __init__(self, baseline, actuators=None, rtt_probe=None,
                 store=None, prompt_mix_fn=None, occupancy_fn=None,
                 step_time_fn=None, push_every=8, step_time_sec=None,
                 planned_prompt_tokens=None, kv_pages=None,
                 rtt_drift_factor=2.0, mix_drift_factor=1.5,
                 occupancy_high=0.9, occupancy_low=0.3,
                 sustain=2, cooldown_sec=60.0, cadence_margin=1.25,
                 clock=time.monotonic):
        rtt = getattr(baseline, "dcn_rtt_sec", baseline)
        self.baseline_rtt = float(rtt)
        self.actuators = dict(actuators or {})
        self.rtt_probe = rtt_probe
        self.store = store
        self.prompt_mix_fn = prompt_mix_fn
        self.occupancy_fn = occupancy_fn
        self.step_time_fn = step_time_fn
        self.push_every = int(push_every)
        self.step_time_sec = float(step_time_sec or 1e-2)
        self.planned_prompt_tokens = planned_prompt_tokens
        self.kv_pages = kv_pages
        self.rtt_drift_factor = float(rtt_drift_factor)
        self.mix_drift_factor = float(mix_drift_factor)
        self.occupancy_high = float(occupancy_high)
        self.occupancy_low = float(occupancy_low)
        self.sustain = max(1, int(sustain))
        self.cooldown_sec = float(cooldown_sec)
        self.cadence_margin = float(cadence_margin)
        self._clock = clock
        self._asserting = {}     # trigger -> consecutive rounds
        self._last_applied = {}  # trigger -> clock time
        self.history = []

    # -- plumbing -------------------------------------------------------

    def _cooled(self, trigger):
        last = self._last_applied.get(trigger)
        return last is None or (
            self._clock() - last >= self.cooldown_sec
        )

    def _sustained(self, trigger, asserting):
        if not asserting:
            self._asserting[trigger] = 0
            return False
        self._asserting[trigger] = self._asserting.get(trigger, 0) + 1
        return self._asserting[trigger] >= self.sustain

    def _apply(self, rec):
        """Drive the actuator and journal the typed event either way."""
        fn = self.actuators.get(rec.knob)
        reg = telemetry.get_registry()
        if fn is not None:
            try:
                fn(rec.new)
                rec.applied = True
            except Exception as e:  # noqa: BLE001 - journaled, not fatal
                rec.error = "{0}: {1}".format(type(e).__name__, e)
                logger.warning("replan %s -> %s failed: %s",
                               rec.knob, rec.new, rec.error)
        if rec.applied:
            self._asserting[rec.trigger] = 0
            self._last_applied[rec.trigger] = self._clock()
            reg.counter("planner.replans").inc()
        telemetry.get_tracer().mark(
            "replan", trace="planner",
            severity="info" if rec.applied else "warn",
            trigger=rec.trigger, knob=rec.knob,
            old=rec.old, new=rec.new, applied=rec.applied,
            error=rec.error, evidence=rec.evidence,
        )
        self.history.append(rec)
        return rec

    def _skip(self, trigger):
        telemetry.get_registry().counter(
            "planner.replan_suppressed"
        ).inc()
        logger.debug("replan trigger %s suppressed (cooldown)", trigger)

    # -- triggers -------------------------------------------------------

    def _check_rtt(self):
        if self.rtt_probe is None:
            return None
        rtt = float(self.rtt_probe())
        drifted = rtt >= self.rtt_drift_factor * self.baseline_rtt
        if not self._sustained("dcn_rtt", drifted):
            return None
        if not self._cooled("dcn_rtt"):
            self._skip("dcn_rtt")
            return None
        step = float(
            self.step_time_fn() if self.step_time_fn
            else self.step_time_sec
        )
        # the cadence rule against the MEASURED rtt: smallest window
        # that keeps push_every x step_time above margin x RTT
        new = max(
            self.push_every,
            int(math.ceil(self.cadence_margin * rtt / max(1e-9, step))),
        )
        if new == self.push_every:
            return None
        rec = Replan(
            "dcn_rtt", "push_every", self.push_every, new,
            evidence={
                "measured_rtt_ms": round(1e3 * rtt, 3),
                "baseline_rtt_ms": round(1e3 * self.baseline_rtt, 3),
                "drift_factor": round(rtt / self.baseline_rtt, 2),
                "threshold_factor": self.rtt_drift_factor,
                "step_time_ms": round(1e3 * step, 3),
                "cadence_margin": self.cadence_margin,
                "sustained_rounds": self._asserting.get("dcn_rtt", 0),
            },
        )
        self._apply(rec)
        if rec.applied:
            self.push_every = new
            # the new cadence is the new normal: drift is judged
            # against what we re-planned FOR, so one episode is one
            # re-plan (the chaos e2e's exactly-once assertion)
            self.baseline_rtt = rtt
        return rec

    def _prompt_mix(self):
        if self.prompt_mix_fn is not None:
            return self.prompt_mix_fn()
        if self.store is not None:
            mean = self.store.mean_over("serving.prompt_tokens", None)
            return mean
        return None

    def _check_mix(self):
        if self.planned_prompt_tokens is None:
            return None
        mean = self._prompt_mix()
        if mean is None:
            return None
        shifted = mean >= self.mix_drift_factor * float(
            self.planned_prompt_tokens
        )
        if not self._sustained("prompt_mix", shifted):
            return None
        if not self._cooled("prompt_mix"):
            self._skip("prompt_mix")
            return None
        new = int(2 ** math.ceil(math.log2(max(1.0, mean))))
        rec = Replan(
            "prompt_mix", "slot_buckets",
            self.planned_prompt_tokens, new,
            evidence={
                "mean_prompt_tokens": round(float(mean), 1),
                "planned_prompt_tokens": self.planned_prompt_tokens,
                "threshold_factor": self.mix_drift_factor,
                "sustained_rounds": self._asserting.get(
                    "prompt_mix", 0
                ),
            },
        )
        self._apply(rec)
        if rec.applied:
            self.planned_prompt_tokens = new
        return rec

    def _occupancy(self):
        if self.occupancy_fn is not None:
            return self.occupancy_fn()
        if self.store is not None:
            used = self.store.gauge_last("serving.pool_pages_used")
            total = self.store.gauge_last("serving.pool_pages")
            if used is not None and total:
                return float(used) / float(total)
        return None

    def _check_pages(self):
        if self.kv_pages is None:
            return None
        occ = self._occupancy()
        if occ is None:
            return None
        high = occ >= self.occupancy_high
        low = occ <= self.occupancy_low
        if not self._sustained("page_occupancy", high or low):
            return None
        if not self._cooled("page_occupancy"):
            self._skip("page_occupancy")
            return None
        new = (
            int(self.kv_pages * 1.5) + 1 if high
            else max(2, int(self.kv_pages * 0.75))
        )
        if new == self.kv_pages:
            return None
        rec = Replan(
            "page_occupancy", "kv_pages", self.kv_pages, new,
            evidence={
                "occupancy": round(float(occ), 3),
                "high_watermark": self.occupancy_high,
                "low_watermark": self.occupancy_low,
                "sustained_rounds": self._asserting.get(
                    "page_occupancy", 0
                ),
            },
        )
        self._apply(rec)
        if rec.applied:
            self.kv_pages = new
        return rec

    def step(self):
        """One evaluation round over every armed trigger; returns the
        re-plans decided this round (applied or failed — suppressed
        and non-asserting triggers return nothing)."""
        out = []
        for check in (self._check_rtt, self._check_mix,
                      self._check_pages):
            try:
                rec = check()
            except Exception as e:  # noqa: BLE001 - sensor faults skip a round
                logger.warning("replan sensor failed: %s", e)
                continue
            if rec is not None:
                out.append(rec)
        return out
