"""The search layer: enumerate the legal knob lattice, price every
point with the cost model, pick the min-modeled-critical-path point
(ISSUE 18; PAPERS: "it's the critical path", not greedy per-axis
choices).

Legality is the EXISTING validators', not a parallel rulebook:
``ops/paged_attention.check_tiles`` for paged kernel geometries, the
SlotDecoder pool rule (``kv_pages >= slots x span + 1``), mesh
divisibility (``parallel/mesh.serving_mesh`` semantics), the
``pad_cap`` bucketing bound, the quantized-weights x mesh exclusion
and the greedy-only speculative constraint — a candidate the planner
emits is a candidate the builders accept (property-tested in
tests/test_planner.py).

Every decision is logged: the chosen point, the runner-up and the
modeled gap ride a typed ``planner_decision`` journal event (the
tracer mark auto-bridges), so ``forensics explain`` can answer "why
is the config what it is"; :meth:`Plan.explain` renders the same
story as text (the ``python -m tensorflowonspark_tpu.planner
explain`` CLI).
"""

import itertools
import logging
import time

from tensorflowonspark_tpu import telemetry
from tensorflowonspark_tpu.planner import cost as cost_mod
from tensorflowonspark_tpu.planner import knobs as knobs_mod

logger = logging.getLogger(__name__)

#: default workload facts when the caller gives no hint — a short
#: interactive generation mix
DEFAULT_HINT = {
    "prompt_tokens": 32, "prompt_max": 64, "qps": 0.0,
    "shared_prefix_frac": 0.0, "mixed": False,
    "batch": 8, "seq_len": 128, "dcn_gbs": 1.0, "dcn_compression": 1.0,
}

#: the serving lattice axes the search sweeps (overrides pin axes to
#: one value); slots/chunk powers of two keep the compiled-program
#: bucket count bounded
SERVING_AXES = {
    "batch_size": (4, 8, 16, 32),
    "chunk_size": (4, 8, 16, 32),
    "kv_layout": ("contiguous", "paged"),
    "kv_page_tokens": (8, 16, 32),
    "tp": (1, 2, 4, 8),
}
TRAIN_AXES = {
    "push_every": (1, 2, 4, 8, 16, 32, 64),
    "max_inflight": (1, 2, 4),
}


def _bucket(n, multiple):
    return ((int(n) + multiple - 1) // multiple) * multiple


def _page_span(model_config, cand):
    """Blocks per slot at this geometry — the SlotDecoder table
    width the pool rule is stated over."""
    max_new = int(cand.get("max_new_tokens")
                  or model_config.get("max_new_tokens") or 16)
    cache_len = int(model_config.get("max_seq_len", 256))
    if cand.get("max_prompt_len"):
        b = _bucket(cand["max_prompt_len"], cand.get("pad_multiple", 64))
        cache_len = min(cache_len, b + max_new)
    pt = int(cand.get("kv_page_tokens") or cand.get("prefix_block") or 16)
    return (cache_len + pt - 1) // pt


def validate_candidate(model_config, cand, device_count=1):
    """``None`` when every legality validator the planner claims to
    respect accepts ``cand``; else the rejection reason.  The property
    test sweeps planner OUTPUT through this with randomized shapes —
    and this function delegates to the real validators, so the claim
    is checked against the code that enforces it at build time."""
    mc = model_config
    tp = int(cand.get("tp") or 1)
    if tp > 1:
        if device_count % tp:
            return "tp={0} does not divide {1} devices".format(
                tp, device_count
            )
        if int(mc.get("num_heads", 4)) % tp \
                or int(mc.get("num_kv_heads", mc.get("num_heads", 4))) % tp:
            return "tp={0} does not divide the head counts".format(tp)
        weights = cand.get("weights") or cand.get("quantize")
        if weights in ("int8", "int4"):
            # SlotDecoder's quantized-weights x mesh exclusion
            return "quantized weights cannot shard over a mesh"
    if cand.get("disaggregate") and cand.get("kv_layout") != "paged":
        return "disaggregate needs kv_layout='paged'"
    if cand.get("speculative") and float(cand.get("temperature", 0.0)):
        return "speculative serving is greedy-only"
    if cand.get("kv_layout") == "paged":
        pt = int(cand.get("kv_page_tokens")
                 or cand.get("prefix_block") or 16)
        if cand.get("paged_impl", "kernel") == "kernel" and tp == 1:
            from tensorflowonspark_tpu.ops import paged_attention as pa

            try:
                pa.check_tiles(
                    pt, int(mc.get("head_dim", 16)),
                    "int8" if mc.get("cache_dtype") == "int8"
                    else mc.get("dtype", "float32"),
                )
            except pa.TileLegalityError as e:
                return "tile-illegal paged geometry: {0}".format(e)
        if cand.get("kv_pages") is not None:
            span = _page_span(mc, cand)
            slots = int(cand.get("batch_size", 8))
            need = slots * span + 1
            if int(cand["kv_pages"]) < need:
                return ("kv_pages={0} below the pool rule "
                        "slots x span + 1 = {1}").format(
                            cand["kv_pages"], need)
    # pad_cap: bucketing must never push a fitting prompt past the
    # cache (serving.py honors predict.pad_cap when left-padding)
    max_new = int(cand.get("max_new_tokens")
                  or mc.get("max_new_tokens") or 16)
    cap = int(mc.get("max_seq_len", 256)) - max_new
    if cap < 1:
        return "max_new_tokens leaves no cache room for prompts"
    if cand.get("max_prompt_len") and int(cand["max_prompt_len"]) > cap:
        return "max_prompt_len {0} beyond pad_cap {1}".format(
            cand["max_prompt_len"], cap
        )
    return None


def _serving_candidates(model_config, device_count, hint, overrides):
    """The pruned serving lattice (generator of candidate dicts)."""
    axes = {}
    for name, values in SERVING_AXES.items():
        if name in overrides:
            axes[name] = (overrides[name],)
        else:
            axes[name] = values
    shared = float(hint.get("shared_prefix_frac", 0.0))
    prompt_max = int(hint.get("prompt_max", hint.get("prompt_tokens", 64)))
    names = sorted(axes)
    for point in itertools.product(*(axes[n] for n in names)):
        cand = dict(zip(names, point))
        if cand["kv_layout"] == "contiguous":
            if cand.get("kv_page_tokens") != SERVING_AXES[
                    "kv_page_tokens"][0] and "kv_page_tokens" not in \
                    overrides:
                continue  # page width is meaningless off-paged: dedup
            cand["kv_page_tokens"] = None
        # decisions computed, not searched: prefix reuse follows the
        # workload's shared fraction; disaggregation follows the mixed
        # prompt mix (the regime the split exists for, ISSUE 17); the
        # pool is sized by the rule with headroom
        cand["prefix_cache"] = overrides.get(
            "prefix_cache", shared >= 0.2
        )
        cand["disaggregate"] = overrides.get(
            "disaggregate",
            bool(hint.get("mixed")) and cand["kv_layout"] == "paged",
        )
        cand["max_prompt_len"] = overrides.get(
            "max_prompt_len",
            prompt_max if prompt_max and prompt_max < int(
                model_config.get("max_seq_len", 256)
            ) else None,
        )
        cand["pad_multiple"] = overrides.get("pad_multiple", 16)
        if cand["kv_layout"] == "paged":
            span = _page_span(model_config, cand)
            cand["kv_pages"] = overrides.get(
                "kv_pages",
                cand["batch_size"] * span * 2 + 1,
            )
            if cand["prefix_cache"] and cand["kv_page_tokens"]:
                cand["prefix_block"] = cand["kv_page_tokens"]
        for k, v in overrides.items():
            cand.setdefault(k, v)
        yield cand


def _train_candidates(hint, overrides):
    axes = {
        name: ((overrides[name],) if name in overrides else values)
        for name, values in TRAIN_AXES.items()
    }
    names = sorted(axes)
    for point in itertools.product(*(axes[n] for n in names)):
        cand = dict(zip(names, point))
        for k, v in overrides.items():
            cand.setdefault(k, v)
        yield cand


class Plan(object):
    """One planning outcome: the chosen point, the priced runner-up,
    the modeled gap, and the per-knob decision log."""

    def __init__(self, workload, chosen, priced, runner_up, gap_pct,
                 decisions, profile, hint, model_config, pruned):
        self.workload = workload
        self.chosen = chosen
        self.priced = priced            # cost dict of the chosen point
        self.runner_up = runner_up      # (cand, cost) or None
        self.gap_pct = gap_pct
        self.decisions = decisions
        self.profile = profile
        self.hint = hint
        self.model_config = dict(model_config or {})
        self.pruned = pruned            # [(cand, reason)] sample

    def config(self):
        """The fully-specified config: model fields + every chosen
        knob (``None``-valued knobs drop out — builder defaults)."""
        out = dict(self.model_config)
        out.pop("auto", None)
        for k, v in self.chosen.items():
            if v is not None:
                out[k] = v
        return out

    def summary(self):
        return {
            "workload": self.workload,
            "chosen": {k: v for k, v in sorted(self.chosen.items())},
            "modeled_sec": round(self.priced["total_sec"], 6),
            "bottleneck": self.priced.get("bottleneck"),
            "runner_up": (
                {k: v for k, v in sorted(self.runner_up[0].items())}
                if self.runner_up else None
            ),
            "runner_up_sec": (
                round(self.runner_up[1]["total_sec"], 6)
                if self.runner_up else None
            ),
            "gap_pct": self.gap_pct,
            "profile": self.profile.to_dict(),
        }

    def explain(self):
        """The ``plan explain`` rendering: chosen point, runner-up,
        modeled gap, per-knob decisions, and the modeled critical
        path itself."""
        lines = ["== planner explain ({0}) ==".format(self.workload)]
        lines.append("profile         : {0!r}".format(self.profile))
        lines.append("modeled total   : {0:.6f}s (bottleneck: {1})".format(
            self.priced["total_sec"], self.priced.get("bottleneck"),
        ))
        for link in self.priced.get("path", []):
            lines.append(
                "    {0:<20} dur {1:>10.6f}s  self {2:>10.6f}s".format(
                    link["name"], link["dur"], link["self_sec"]
                )
            )
        lines.append("chosen          :")
        for d in self.decisions:
            lines.append("    {0:<16} = {1!r:<12} [{2}] {3}".format(
                d["knob"], d["value"], d["source"], d.get("why", "")
            ))
        if self.runner_up is not None:
            ru, rc = self.runner_up
            diff = {
                k: ru.get(k) for k in sorted(set(ru) | set(self.chosen))
                if ru.get(k) != self.chosen.get(k)
            }
            lines.append(
                "runner-up       : {0!r} at {1:.6f}s "
                "(modeled gap {2:+.1f}%)".format(
                    diff, rc["total_sec"], self.gap_pct
                )
            )
        if self.pruned:
            lines.append("pruned examples :")
            for cand, why in self.pruned[:5]:
                lines.append("    {0}".format(why))
        return "\n".join(lines)


def _decision_log(chosen, overrides, computed_keys):
    out = []
    for k in sorted(chosen):
        if chosen[k] is None:
            continue
        if k in overrides:
            source, why = "override", "pinned by the caller"
        elif k in computed_keys:
            source, why = "computed", computed_keys[k]
        else:
            source, why = "search", "min modeled critical path"
        out.append({"knob": k, "value": chosen[k], "source": source,
                    "why": why})
    return out


def plan(model_config=None, workload="serving", device_count=None,
         hint=None, profile=None, overrides=None, journal=True):
    """Turn (model config, device inventory, interconnect profile,
    workload hint) into a fully-specified config.

    Args:
      model_config: TransformerConfig-style dict (serving) — the model
        facts the lattice is validated against.
      workload: ``"serving"`` or ``"train"``.
      device_count: devices the deployment owns (default: the local
        jax backend's).
      hint: workload facts (see :data:`DEFAULT_HINT`).
      profile: a :class:`~tensorflowonspark_tpu.planner.cost.
        DeviceProfile`; default: :func:`~tensorflowonspark_tpu.
        planner.cost.calibrate` (probe cache / roofline fallback).
      overrides: knobs pinned by the caller — each pinned axis
        collapses to that value and the decision log says so.
      journal: emit the typed ``planner_decision`` journal event.
    """
    t0 = time.perf_counter()
    model_config = dict(model_config or {})
    hint = dict(DEFAULT_HINT, **(hint or {}))
    overrides = dict(overrides or {})
    if device_count is None:
        try:
            import jax

            device_count = len(jax.devices())
        except Exception:  # noqa: BLE001 - planning without a backend
            device_count = 1
    if profile is None:
        profile = cost_mod.calibrate()
    model = cost_mod.CostModel(profile)
    reg = telemetry.get_registry()

    if workload == "train":
        cands = _train_candidates(hint, overrides)
        price = lambda c: model.price_train(model_config, c, hint)  # noqa: E731
    elif workload == "serving":
        cands = _serving_candidates(
            model_config, device_count, hint, overrides
        )
        price = lambda c: model.price_serving(model_config, c, hint)  # noqa: E731
    else:
        raise ValueError(
            "workload must be 'serving' or 'train', got {0!r}".format(
                workload
            )
        )

    scored, pruned = [], []
    for cand in cands:
        why = validate_candidate(model_config, cand, device_count)
        if why is not None:
            if len(pruned) < 32:
                pruned.append((cand, why))
            reg.counter("planner.pruned").inc()
            continue
        scored.append((cand, price(cand)))
        reg.counter("planner.candidates").inc()
    if not scored:
        raise ValueError(
            "no legal candidate in the {0} lattice (device_count={1}; "
            "first rejections: {2})".format(
                workload, device_count, [w for _, w in pruned[:3]]
            )
        )
    # freshest-first tie-break on training: among near-equal points
    # prefer the smallest push_every (less staleness for free)
    if workload == "train":
        scored.sort(key=lambda cw: (
            round(cw[1]["total_sec"] / max(1, cw[0]["push_every"]), 9),
            cw[0]["push_every"], cw[0]["max_inflight"],
        ))
    else:
        scored.sort(key=lambda cw: (
            cw[1]["total_sec"],
            repr(sorted(cw[0].items(), key=lambda kv: kv[0])),
        ))
    chosen, priced = scored[0]
    runner_up = scored[1] if len(scored) > 1 else None
    gap_pct = None
    if runner_up is not None:
        base = max(1e-12, priced["total_sec"])
        if workload == "train":
            a = priced["total_sec"] / max(1, chosen["push_every"])
            b = runner_up[1]["total_sec"] / max(
                1, runner_up[0]["push_every"]
            )
            gap_pct = round(100.0 * (b - a) / max(1e-12, a), 2)
        else:
            gap_pct = round(
                100.0 * (runner_up[1]["total_sec"] - base) / base, 2
            )
    computed = {
        "prefix_cache": "shared_prefix_frac {0} in the hint".format(
            hint.get("shared_prefix_frac")
        ),
        "disaggregate": "mixed prompt mix in the hint",
        "kv_pages": "pool rule slots x span + headroom",
        "max_prompt_len": "prompt_max in the hint",
        "prefix_block": "aligned to the page width",
        "pad_multiple": "bucket width floor",
    }
    result = Plan(
        workload, chosen, priced, runner_up, gap_pct,
        _decision_log(chosen, overrides, computed),
        profile, hint, model_config, pruned,
    )
    reg.histogram("planner.plan_sec").observe(time.perf_counter() - t0)
    if journal:
        telemetry.get_tracer().mark(
            "planner_decision", trace="planner", severity="info",
            workload=workload,
            chosen={k: v for k, v in sorted(chosen.items())
                    if v is not None},
            runner_up=(
                {k: v for k, v in sorted(runner_up[0].items())
                 if v is not None} if runner_up else None
            ),
            gap_pct=gap_pct,
            modeled_sec=round(priced["total_sec"], 6),
            bottleneck=priced.get("bottleneck"),
            candidates=len(scored), pruned_count=len(pruned),
            profile_source=profile.source,
            overrides=sorted(overrides),
        )
    return result


def auto_serving_config(config, device_count=None, profile=None,
                        hint=None):
    """The ``config="auto"`` surface behind ``serving_builder`` /
    ``load_predictor``: plan the workload and fill every planner-owned
    knob the caller did NOT set — explicit keys always win, so every
    decision is individually overridable.  Returns ``(merged_config,
    plan)`` with the ``auto`` key dropped from the merged dict."""
    config = dict(config)
    config.pop("auto", None)
    owned = {k.name for k in knobs_mod.planner_owned("serving")}
    overrides = {k: config[k] for k in owned if k in config}
    h = dict(hint or {})
    if config.get("max_prompt_len") and "prompt_max" not in h:
        h["prompt_max"] = int(config["max_prompt_len"])
    if config.get("max_new_tokens") and "max_new_tokens" not in h:
        h.setdefault("prompt_tokens", h.get("prompt_max", 32))
    p = plan(
        model_config=config, workload="serving",
        device_count=device_count, hint=h, profile=profile,
        overrides=overrides,
    )
    merged = dict(config)
    serving_keys = {
        k.name for k in knobs_mod.KNOBS if k.subsystem == "serving"
    }
    for k, v in p.chosen.items():
        # engine-side picks (batch_size...) ride the Plan, not the
        # builder config — predict_rows reads them off predict.plan
        if k in serving_keys and k not in merged and v is not None:
            merged[k] = v
    return merged, p
