"""The knob registry: every tunable the stack exposes, in one table.

Two consumers (ISSUE 18):

- the **planner** reads it as the search-space inventory — which knobs
  exist, which subsystem owns each, which the search layer may set vs.
  which are workload facts the caller states (``docs/autotune.md``
  renders this table);
- the **builders** read it as the validation surface — an unknown key
  in ``serving_builder``'s config or ``load_predictor(
  config_overrides=)`` raises :class:`UnknownKnobError` naming the
  near-misses and the valid table, instead of silently degrading to
  defaults (the ``kv_page_token`` typo bug).

Import-light on purpose: no jax, no sibling modules — the transformer
builder calls into here at build time.
"""

import collections
import difflib

#: one registry row.  ``planner`` marks knobs the search layer itself
#: assigns (vs. workload facts / escape hatches the caller states);
#: ``subsystem`` groups the docs table and scopes validation.
Knob = collections.namedtuple(
    "Knob", ["name", "subsystem", "default", "planner", "desc"]
)


def _k(subsystem, planner, *rows):
    return [
        Knob(name, subsystem, default, planner, desc)
        for name, default, desc in rows
    ]


#: the full inventory.  ``serving`` rows are the non-TransformerConfig
#: keys ``models/transformer.serving_builder`` reads (its validation
#: set = these + the TransformerConfig field names); ``engine`` rows
#: are ``predict_rows``/ServingEngine arguments; ``train`` rows are
#: the hierarchical data-parallel plane's.
KNOBS = (
    _k("serving", False, *[
        ("mode", None, "builder mode: 'generate' or logits serving"),
        ("auto", False, "fill every unset planner-owned knob from the "
                        "cost-model planner (ISSUE 18)"),
        ("max_new_tokens", None, "decode budget per request (required "
                                 "in generate mode)"),
        ("temperature", 0.0, "sampling temperature (0 = greedy)"),
        ("top_k", 0, "top-k sampling cutoff (0 = off)"),
        ("top_p", 0.0, "nucleus sampling cutoff (0 = off)"),
        ("seed", 0, "sampling PRNG seed"),
        ("speculative", False, "static-path speculative decoding "
                               "(greedy-only)"),
        ("ngram", 2, "n-gram order for draft-free speculation"),
        ("pad_id", 0, "prompt pad token id"),
        ("eos_id", None, "stop token id (None = run to budget)"),
        ("input_name", "tokens", "prompt column name"),
        ("draft_config", None, "draft model TransformerConfig fields "
                               "(arms draft-model speculation)"),
        ("draft_params", None, "in-process draft weights"),
        ("profile_dir", None, "on-demand jax.profiler capture dir"),
        ("profile_steps", 0, "profiler capture length in decode "
                             "chunks"),
        ("check_tiles", None, "force the Mosaic tile-legality "
                              "preflight on/off"),
        ("mesh_shape", None, "explicit {axis: size} serving mesh"),
    ]),
    _k("serving", True, *[
        ("weights", None, "weight dtype: 'int8'/'int4'/'float'"),
        ("quantize", None, "pre-ISSUE-12 alias of weights"),
        ("int4_group", 64, "int4 group-wise scale width"),
        ("draft_len", 4, "speculative draft length per round"),
        ("pad_multiple", 64, "prompt-length bucket width"),
        ("max_prompt_len", None, "cache sized to bucket(max_prompt_"
                                 "len) + max_new instead of "
                                 "max_seq_len"),
        ("chunk_size", 16, "decode steps between admit/evict points"),
        ("prefix_cache", False, "cross-request radix KV reuse"),
        ("prefix_block", 16, "radix block width (tokens)"),
        ("prefix_mem_mb", 256.0, "prefix-cache HBM budget"),
        ("kv_layout", "contiguous", "'contiguous' or 'paged' KV"),
        ("kv_pages", None, "physical page-pool size (paged layout); "
                           "must hold slots x blocks + 1"),
        ("kv_page_tokens", None, "page width in tokens (defaults to "
                                 "prefix_block)"),
        ("paged_impl", None, "'kernel' (pallas) or 'gather' (XLA)"),
        ("tp", None, "tensor-parallel degree (model-axis mesh)"),
        ("disaggregate", False, "split prefill into its own jitted "
                                "worker (paged layout only)"),
    ]),
    _k("engine", True, *[
        ("batch_size", 32, "static batch / continuous slot count"),
        ("schedule", "static", "'static' or 'continuous' batching"),
        ("queue_depth", 64, "bounded admission queue length"),
        ("policy", "block", "overload policy: block/reject/degrade"),
        ("watchdog_timeout", None, "per-chunk dispatch watchdog (sec)"),
        ("default_deadline", None, "per-request deadline default "
                                   "(sec)"),
        ("replicas", 1, "fleet replica count"),
    ]),
    _k("train", True, *[
        ("push_every", 8, "ICI steps per DCN window (cadence rule: "
                          "push_every x step_time > DCN RTT — "
                          "planner-owned since ISSUE 18)"),
        ("max_inflight", 2, "unacked DCN windows before the leader "
                            "blocks"),
        ("num_ps", 0, "parameter-server task count"),
    ]),
)
KNOBS = tuple(k for group in KNOBS for k in group)

#: name -> Knob
BY_NAME = {k.name: k for k in KNOBS}

#: the keys ``serving_builder`` accepts beyond TransformerConfig fields
SERVING_KEYS = frozenset(
    k.name for k in KNOBS if k.subsystem == "serving"
)


class UnknownKnobError(ValueError):
    """An unknown config key reached a builder — named error instead
    of a silent degrade-to-default (ISSUE 18 satellite: a typo'd
    ``kv_page_token`` used to fall through every ``config.get`` and
    serve with the default page width, no signal).  Carries the
    offending keys, per-key suggestions, and the valid table."""

    def __init__(self, unknown, valid, where):
        self.unknown = tuple(sorted(unknown))
        self.valid = tuple(sorted(valid))
        self.where = where
        parts = []
        for key in self.unknown:
            close = difflib.get_close_matches(key, self.valid, n=2)
            parts.append(
                "{0!r}{1}".format(
                    key,
                    " (did you mean {0}?)".format(
                        " or ".join(repr(c) for c in close)
                    ) if close else "",
                )
            )
        super(UnknownKnobError, self).__init__(
            "unknown config key(s) for {0}: {1}.  Valid keys: {2}".format(
                where, ", ".join(parts), ", ".join(self.valid)
            )
        )


def validate_keys(config, extra_valid=(), where="serving_builder"):
    """Raise :class:`UnknownKnobError` when ``config`` holds keys that
    are neither registry serving knobs nor ``extra_valid`` (the
    caller's TransformerConfig field names)."""
    valid = SERVING_KEYS | frozenset(extra_valid)
    unknown = [k for k in config if k not in valid]
    if unknown:
        raise UnknownKnobError(unknown, valid, where)


def planner_owned(subsystem=None):
    """The knobs the search layer assigns (``docs/autotune.md``'s
    search-space table rows)."""
    return [
        k for k in KNOBS
        if k.planner and (subsystem is None or k.subsystem == subsystem)
    ]


def render_table(knobs=None):
    """Markdown table of (a subset of) the registry — the CLI's and
    docs' rendering."""
    rows = list(knobs if knobs is not None else KNOBS)
    out = ["| knob | subsystem | default | planner-set | description |",
           "|---|---|---|---|---|"]
    for k in rows:
        out.append("| `{0}` | {1} | `{2!r}` | {3} | {4} |".format(
            k.name, k.subsystem, k.default,
            "yes" if k.planner else "no", k.desc,
        ))
    return "\n".join(out)
