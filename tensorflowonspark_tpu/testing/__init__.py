"""Test-support subpackage: deterministic fault injection (chaos.py).

Shipped inside the library (not under tests/) because the runtime has
exactly one sanctioned chaos hook — the supervisor's heartbeat-drop
callable — and it must resolve the plan without importing test code.
"""
