"""Deterministic chaos injection for fault-tolerance tests.

Three fault families, all reproducible (no randomness — a chaos test
that fails must fail the same way every run):

- **kill worker k at step s** — a :class:`ChaosPlan` written to a JSON
  file and advertised via the ``TFOS_CHAOS_PLAN`` env var; the user fn
  under test calls :func:`step_fault_fn` and invokes the returned
  callable once per training step.  The kill is a SIGKILL to the
  compute process's own pid: no atexit handlers, no error-queue post —
  exactly what a preemption or OOM kill looks like to the rest of the
  system.
- **drop heartbeats** — the same plan file can order executor k to drop
  its next N heartbeat frames; the supervisor threads this through
  :class:`~tensorflowonspark_tpu.cluster.reservation.Heartbeater`'s
  ``chaos_fn``, exercising the miss-threshold path a real network
  partition would take.
- **sever a TCP connection** — :class:`TcpGremlin`, a forwarding proxy
  to put in front of a reservation server or node manager; it can
  refuse the next N connections or cut every live one on command,
  driving the client retry/backoff paths end to end.
- **serving faults** (the serving-side family, PR 4): the same plan
  file can order a ``wedge_dispatch`` (a decode chunk that stalls
  like a hung XLA call, driving the ServingEngine watchdog through
  :func:`serving_wedge_fn`); :func:`poison_row` builds deterministic
  malformed requests for every admission-validation class; and
  :func:`slow_consumer` stalls the output side the way a slow
  downstream does.
- **straggler faults** (the health-plane family, ISSUE 10):
  ``ChaosPlan.slow_executor`` stalls one executor's feed pulls
  (:func:`slow_feed_fn` + :class:`SlowFeed` — the stall lands in the
  ``feed`` phase of its telemetry series), and
  :meth:`TcpGremlin.delay` stalls a live TCP link (the WIRE-phase
  flavor); the fleet health plane's straggler detector must name the
  injected node, attribute the right phase, and auto-fire the
  profiler on it only (tests/test_chaos.py, tests/test_health.py).
- **swap faults** (the lifecycle family, ISSUE 8):
  :func:`corrupt_checkpoint` inflicts one corrupt-export variant per
  hot-swap validation stage (truncated array file, garbage manifest,
  shape-mismatched params — every one must quarantine with its typed
  reason, never serve); the plan can order ``slow_ingest`` (a stalled
  checkpoint store — background ingest must keep serving on the old
  generation) and ``swap_during_wedge`` (a validated swap pending
  while a dispatch wedges — watchdog recovery and the swap must both
  land, dropping nothing).

Every injected fault also leaves a FORENSIC record (ISSUE 11): the
fault sites it drives all mark the tracer, marks bridge into the
typed event journal, and the flight recorder dumps on the fault kinds
— so ``python -m tensorflowonspark_tpu.forensics explain`` over a
chaos run's dumps must name the injected fault in the chaos-plan
vocabulary (``wedge_dispatch``, ``kill_leader``, ``kill``; see
``forensics.FAULT_MAP``) and the executor it targeted
(tests/test_blackbox.py pins the wedge + kill-leader e2e).

Nothing here runs unless a test opts in: ``heartbeat_chaos_fn`` returns
``None`` when ``TFOS_CHAOS_PLAN`` is unset, so production paths carry a
single dict lookup of overhead.
"""

import json
import logging
import os
import signal
import socket
import threading

logger = logging.getLogger(__name__)

#: Env var naming the JSON chaos-plan file executors should load.
TFOS_CHAOS_PLAN = "TFOS_CHAOS_PLAN"


class ChaosPlan(object):
    """A deterministic fault plan, serializable to the plan file.

    Build with the fluent helpers::

        plan = (ChaosPlan()
                .kill_worker(executor_id=1, at_step=5)
                .drop_heartbeats(executor_id=0, beats=4))
        plan.save(path)          # point TFOS_CHAOS_PLAN at this
    """

    def __init__(self, faults=None):
        self.faults = list(faults or [])

    def kill_worker(self, executor_id, at_step):
        """SIGKILL executor ``executor_id``'s compute process the first
        time its step counter reaches ``at_step``."""
        self.faults.append(
            {"kind": "kill", "executor_id": int(executor_id),
             "at_step": int(at_step)}
        )
        return self

    def kill_leader(self, at_window):
        """Kill the hierarchical gradient plane's pod leader the first
        time its DCN push window sequence reaches ``at_window`` (the
        fault surfaces as
        :class:`~tensorflowonspark_tpu.parallel.hier_ps.LeaderKilled`
        inside the pusher — exactly what a leader death mid-push looks
        like to the trainer, which must re-elect and resume with no
        window double-applied and none lost).  Each entry fires once,
        in plan order."""
        self.faults.append(
            {"kind": "kill_leader", "at_window": int(at_window)}
        )
        return self

    def slow_executor(self, executor_id, per_batch_sec, batches=0):
        """Make executor ``executor_id`` a STRAGGLER: stall each of its
        feed pulls by ``per_batch_sec`` (the slow-data-pipeline node a
        congested NIC or a TcpGremlin ``delay()`` in front of its feed
        produces — the stall lands in the ``feed`` phase of the health
        plane's per-executor series).  ``batches=0`` stalls every
        batch; otherwise only the first ``batches``.  Consumed via
        :func:`slow_feed_fn` / :class:`SlowFeed` in the user fn under
        test; the fleet health plane's straggler detector is expected
        to name this executor and its ``feed`` phase
        (tests/test_chaos.py)."""
        self.faults.append(
            {"kind": "slow_executor", "executor_id": int(executor_id),
             "per_batch_sec": float(per_batch_sec),
             "batches": int(batches)}
        )
        return self

    def kill_replica(self, replica_id, at_chunk):
        """Kill serving replica ``replica_id``'s engine the first time
        its decode-chunk counter reaches ``at_chunk``: the fault
        surfaces as :class:`~tensorflowonspark_tpu.fleet.replica.
        ReplicaKilled` inside the engine's chunk dispatch — exactly
        what a replica process/chip death mid-decode looks like to the
        fleet router, which must re-dispatch the replica's in-flight
        requests from their committed tokens onto a sibling with
        nothing silently dropped (tests/test_fleet.py).  Each entry
        fires once, in plan order."""
        self.faults.append(
            {"kind": "kill_replica", "replica_id": int(replica_id),
             "at_chunk": int(at_chunk)}
        )
        return self

    def slow_replica(self, replica_id, per_chunk_sec, chunks=0):
        """Make serving replica ``replica_id`` a STRAGGLER: stall each
        of its decode-chunk dispatches by ``per_chunk_sec`` (a
        thermally-throttled or noisy-neighbor chip).  ``chunks=0``
        stalls every chunk; otherwise only the first ``chunks`` after
        the fault arms — after the budget the replica runs at full
        speed again, and the router is expected to ROUTE AROUND it
        while slow, then RE-ADMIT it after N clean probe rounds
        (tests/test_fleet.py)."""
        self.faults.append(
            {"kind": "slow_replica", "replica_id": int(replica_id),
             "per_chunk_sec": float(per_chunk_sec),
             "chunks": int(chunks)}
        )
        return self

    def drop_heartbeats(self, executor_id, beats):
        """Drop the next ``beats`` HEARTBEAT frames of ``executor_id``
        (simulates a network partition of exactly that length)."""
        self.faults.append(
            {"kind": "drop_heartbeats", "executor_id": int(executor_id),
             "beats": int(beats)}
        )
        return self

    def slow_ingest(self, sec):
        """Stall every checkpoint ingest (the hot-swap watcher's
        orbax load + validation) for ``sec`` seconds — what a slow
        or far-away checkpoint store looks like.  With the watcher's
        default background ingest thread, serving must keep decoding
        on the old generation for the whole stall
        (tests/test_chaos_serving.py)."""
        self.faults.append({"kind": "slow_ingest", "sec": float(sec)})
        return self

    def swap_during_wedge(self, at_chunk, hang_sec=30.0):
        """The nastiest lifecycle ordering: a decode dispatch wedges
        at ``at_chunk`` (watchdog territory) WHILE a validated new
        checkpoint is waiting to swap.  Installs the wedge fault and
        records the chunk so the test harness can time its publish
        (:func:`swap_chunk_from_plan`); the engine must recover the
        wedge, land the swap, and drop nothing."""
        self.wedge_dispatch(at_chunk, hang_sec=hang_sec)
        self.faults.append(
            {"kind": "swap_at_chunk", "at_chunk": int(at_chunk)}
        )
        return self

    def wedge_dispatch(self, at_chunk, hang_sec=30.0):
        """Wedge the serving engine's decode dispatch: the first chunk
        whose index reaches ``at_chunk`` stalls for ``hang_sec``
        before the device call — what a hung XLA dispatch looks like
        to the scheduler.  Fires once per fault entry; the serving
        watchdog (``watchdog_timeout``) is expected to abandon it and
        re-admit the in-flight requests
        (tests/test_chaos_serving.py)."""
        self.faults.append(
            {"kind": "wedge_dispatch", "at_chunk": int(at_chunk),
             "hang_sec": float(hang_sec)}
        )
        return self

    def kill_prefill(self, at_admit):
        """Kill the disaggregated PrefillWorker the first time its
        prefill counter reaches ``at_admit``: the fault surfaces as
        :class:`~tensorflowonspark_tpu.serving_disagg.
        PrefillWorkerDead` mid-handoff, with the pool lease already
        open — what a prefill-side chip death looks like.  The engine
        must reap the orphaned lease, re-prefill the stranded request
        through the unified path token-identically, and rebuild the
        worker (tests/test_chaos_serving.py).  Fires once per entry,
        in plan order."""
        self.faults.append(
            {"kind": "kill_prefill", "at_admit": int(at_admit)}
        )
        return self

    def wedge_prefill(self, at_admit, hang_sec=30.0):
        """Wedge the disaggregated prefill dispatch: the prefill whose
        counter reaches ``at_admit`` stalls ``hang_sec`` with its pool
        lease open — a hung prefill program.  The engine's prefill
        watchdog must abandon it, reap the lease, and recover through
        the unified path; the wedged thread aborts when it wakes
        (``PrefillAbandoned``).  Fires once per entry."""
        self.faults.append(
            {"kind": "wedge_prefill", "at_admit": int(at_admit),
             "hang_sec": float(hang_sec)}
        )
        return self

    def leak_lease(self, at_admit, deadline_sec=0.5):
        """Leak a page-pool handoff lease: at prefill ``at_admit`` the
        worker opens an EXTRA one-page lease (owner
        ``chaos:leak_lease``, deadline ``deadline_sec``) and drops the
        handle — a worker that lost track of an in-flight handoff.
        The engine's deadline reaper must reclaim it
        (``lease_reaped`` journal event) with refcounts balanced."""
        self.faults.append(
            {"kind": "leak_lease", "at_admit": int(at_admit),
             # tfoslint: disable=TFOS004(lease deadline, not request column)
             "deadline_sec": float(deadline_sec)}
        )
        return self

    def device_error(self, replica_id, at_chunk):
        """Raise a DEVICE error (:class:`~tensorflowonspark_tpu.fleet.
        replica.ReplicaDeviceError`) inside replica ``replica_id``'s
        chunk dispatch at ``at_chunk`` — what an XLA runtime fault on
        a mesh-sharded engine looks like.  Unlike ``kill_replica``
        (terminal), the replica QUARANTINES: it posts its wreckage,
        rebuilds its engine, and serves probe traffic while routed
        around; the router re-dispatches committed-token-safe onto a
        survivor (tests/test_fleet.py).  Fires once per entry."""
        self.faults.append(
            {"kind": "device_error", "replica_id": int(replica_id),
             "at_chunk": int(at_chunk)}
        )
        return self

    @classmethod
    def combined(cls, slow_executor=None, kill_leader=None,
                 kill_replica=None, corrupt_checkpoint=None):
        """The ROADMAP's combined fault storm as ONE plan (ISSUE 16):
        ``slow_executor + kill_leader + kill_replica +
        corrupt_checkpoint``, each argument a dict of that builder's
        kwargs plus an optional ``at_sec`` wall-clock trigger offset
        (seconds from harness start) the DRIVING harness schedules
        by — the in-band triggers (``at_window``/``at_chunk``/feed
        pulls) still gate exactly when each fault lands inside its
        subsystem.

        ``corrupt_checkpoint`` has no in-band hook (it is the
        driver-side :func:`corrupt_checkpoint` applied to a published
        export), so its record only carries ``corrupt_kind`` +
        ``at_sec`` for the harness; executors ignore it.  Example::

            plan = ChaosPlan.combined(
                slow_executor={"executor_id": 1,
                               "per_batch_sec": 0.4, "at_sec": 2},
                kill_leader={"at_window": 3, "at_sec": 5},
                kill_replica={"replica_id": 1, "at_chunk": 4,
                              "at_sec": 8},
                corrupt_checkpoint={"corrupt_kind": "truncate_array",
                                    "at_sec": 11},
            )

        The remediation acceptance e2e drives this plan against a
        live training cluster + fleet and requires one audited
        decision per fault (tests/test_remediation.py).
        """
        plan = cls()

        def _take(spec, builder):
            spec = dict(spec)
            at_sec = spec.pop("at_sec", None)
            builder(**spec)
            if at_sec is not None:
                plan.faults[-1]["at_sec"] = float(at_sec)

        if slow_executor is not None:
            _take(slow_executor, plan.slow_executor)
        if kill_leader is not None:
            _take(kill_leader, plan.kill_leader)
        if kill_replica is not None:
            _take(kill_replica, plan.kill_replica)
        if corrupt_checkpoint is not None:
            spec = dict(corrupt_checkpoint)
            kind = spec.pop("corrupt_kind", spec.pop("kind", None))
            if kind not in CORRUPT_KINDS:
                raise ValueError(
                    "corrupt_checkpoint needs corrupt_kind in {0}, "
                    "got {1!r}".format(CORRUPT_KINDS, kind)
                )
            fault = {"kind": "corrupt_checkpoint", "corrupt_kind": kind}
            if "at_sec" in spec:
                fault["at_sec"] = float(spec.pop("at_sec"))
            if spec:
                raise ValueError(
                    "unknown corrupt_checkpoint keys {0}".format(
                        sorted(spec)
                    )
                )
            plan.faults.append(fault)
        return plan

    def schedule(self):
        """``(at_sec, fault)`` pairs sorted by trigger time (faults
        with no ``at_sec`` sort first at 0.0) — the harness-side view
        of a :meth:`combined` plan."""
        return sorted(
            ((float(f.get("at_sec", 0.0)), f) for f in self.faults),
            key=lambda p: p[0],
        )

    def save(self, path):
        path = os.fspath(path)
        with open(path, "w") as f:
            json.dump({"faults": self.faults}, f)
        return path

    def env(self, path):
        """The env dict to hand a LocalEngine so executors see the plan."""
        return {TFOS_CHAOS_PLAN: os.fspath(path)}

    @classmethod
    def load(cls, path):
        with open(path) as f:
            return cls(json.load(f).get("faults", []))


def load_plan():
    """The plan advertised via ``TFOS_CHAOS_PLAN``, or None."""
    path = os.environ.get(TFOS_CHAOS_PLAN)
    if not path:
        return None
    try:
        return ChaosPlan.load(path)
    except (OSError, ValueError):
        logger.warning("unreadable chaos plan at %r", path, exc_info=True)
        return None


def step_fault_fn(ctx):
    """Build the per-step fault hook for this compute process.

    Returns ``fault(step)`` — call it once per training step; it
    SIGKILLs this process when a ``kill`` fault for this executor is
    due.  Kill faults fire once per *incarnation reborn after them*:
    a restarted process (``ctx.generation > 0``) skips faults already
    spent, so kill-at-step-5 does not re-kill the replacement when it
    replays step 5 from the checkpoint.  With no plan configured the
    hook is a no-op lambda.
    """
    plan = load_plan()
    if plan is None:
        return lambda step: None
    kills = [
        f for f in plan.faults
        if f["kind"] == "kill" and f["executor_id"] == ctx.executor_id
    ]
    generation = getattr(ctx, "generation", 0)

    def fault(step):
        for i, f in enumerate(kills):
            # fault i belongs to incarnation i: generation 0 arms the
            # first kill, the replacement (generation 1) the second, ...
            if i == generation and step >= f["at_step"]:
                logger.warning(
                    "chaos: killing executor %d compute (pid %d) at "
                    "step %d per plan", ctx.executor_id, os.getpid(), step,
                )
                os.kill(os.getpid(), signal.SIGKILL)

    return fault


def slow_feed_fn(ctx):
    """Build this executor's straggler-injection hook from the plan,
    or None when no ``slow_executor`` fault targets it (the common
    case — one None check of production overhead, like every other
    plan hook).  Returns ``delay()`` — call it once per feed pull; it
    sleeps ``per_batch_sec`` while the fault's batch budget lasts.
    Compose with :class:`SlowFeed` to stall a real feed."""
    plan = load_plan()
    if plan is None:
        return None
    faults = [
        f for f in plan.faults
        if f["kind"] == "slow_executor"
        and f["executor_id"] == int(ctx.executor_id)
    ]
    if not faults:
        return None
    import time as _time

    state = {"pulled": 0}
    per_sec = max(f["per_batch_sec"] for f in faults)
    budget = max(f["batches"] for f in faults)

    def delay():
        state["pulled"] += 1
        if budget and state["pulled"] > budget:
            return
        _time.sleep(per_sec)

    return delay


class SlowFeed(object):
    """Wrap a :class:`~tensorflowonspark_tpu.data.feed.DataFeed` so
    every pull stalls through ``delay_fn`` first — the injection
    vehicle of :meth:`ChaosPlan.slow_executor` (the stall lands inside
    the consumer's ``feed_wait`` phase, exactly where a slow data
    pipeline would).  Everything else proxies to the wrapped feed."""

    def __init__(self, feed, delay_fn):
        self._feed = feed
        self._delay = delay_fn

    def next_batch(self, *a, **kw):
        self._delay()
        return self._feed.next_batch(*a, **kw)

    def next_arrays(self, *a, **kw):
        self._delay()
        return self._feed.next_arrays(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._feed, name)


def heartbeat_chaos_fn(executor_id):
    """Build the Heartbeater ``chaos_fn`` for this executor, or None
    when no plan orders heartbeat drops for it (the common case —
    callers pass the None straight through, zero overhead)."""
    plan = load_plan()
    if plan is None:
        return None
    budget = sum(
        f["beats"] for f in plan.faults
        if f["kind"] == "drop_heartbeats"
        and f["executor_id"] == int(executor_id)
    )
    if budget <= 0:
        return None
    state = {"left": budget}

    def drop():
        if state["left"] > 0:
            state["left"] -= 1
            return True
        return False

    return drop


def hier_leader_fault_fn():
    """Build the hierarchical trainer's DCN ``fault_fn`` from the plan,
    or None when no plan orders ``kill_leader`` faults (the common
    case — one None check of production overhead, like every other
    plan hook).

    Returns ``fault(window_seq)``: raises ``LeaderKilled`` inside the
    leader's pusher thread when an armed fault's ``at_window`` is due.
    Each fault fires once, in plan order — two entries model the
    SUCCESSOR dying too."""
    plan = load_plan()
    if plan is None:
        return None
    kills = [f for f in plan.faults if f["kind"] == "kill_leader"]
    if not kills:
        return None
    spent = set()

    def fault(window_seq):
        from tensorflowonspark_tpu.parallel.hier_ps import LeaderKilled

        for i, f in enumerate(kills):
            if i not in spent and window_seq >= f["at_window"]:
                spent.add(i)
                logger.warning(
                    "chaos: killing pod leader at DCN window %d per plan",
                    window_seq,
                )
                raise LeaderKilled(
                    "chaos kill_leader at window {0}".format(window_seq)
                )

    return fault


def serving_wedge_fn():
    """Build the :class:`ServingEngine` wedge hook from the plan, or
    None when no plan orders ``wedge_dispatch`` faults (the common
    case — the engine carries a single None check of overhead).

    Returns ``maybe_wedge(chunk_index)``: sleeps ``hang_sec`` inside
    the engine's dispatch thread when an armed fault's ``at_chunk``
    is due.  Each fault fires once, in plan order — two entries model
    a dispatch that wedges again after recovery."""
    plan = load_plan()
    if plan is None:
        return None
    wedges = [f for f in plan.faults if f["kind"] == "wedge_dispatch"]
    if not wedges:
        return None
    import time as _time

    spent = set()

    def maybe_wedge(chunk_index):
        for i, f in enumerate(wedges):
            if i not in spent and chunk_index >= f["at_chunk"]:
                spent.add(i)
                logger.warning(
                    "chaos: wedging decode dispatch at chunk %d for "
                    "%.1fs per plan", chunk_index, f["hang_sec"],
                )
                _time.sleep(f["hang_sec"])
                return

    return maybe_wedge


def prefill_fault_fn():
    """Build the :class:`PrefillWorker` fault hook from the plan, or
    None when no plan orders prefill faults (the common case — one
    None check of production overhead, like the other plan hooks).

    Returns ``fault(prefill_index, worker)``, called once per
    prefill with the handoff lease already open and the rng stream
    untouched (the containment point the faults exist to probe):

    - ``leak_lease`` opens an extra one-page lease with owner
      ``chaos:leak_lease`` and the plan's deadline, drops the handle,
      and lets the prefill continue;
    - ``wedge_prefill`` sleeps ``hang_sec`` (the prefill watchdog's
      territory);
    - ``kill_prefill`` marks the worker dead and raises
      :class:`~tensorflowonspark_tpu.serving_disagg.
      PrefillWorkerDead`.

    Each entry fires once, in plan order; leak runs before wedge
    before kill when several are due at the same index."""
    plan = load_plan()
    if plan is None:
        return None
    kills = [f for f in plan.faults if f["kind"] == "kill_prefill"]
    wedges = [f for f in plan.faults if f["kind"] == "wedge_prefill"]
    leaks = [f for f in plan.faults if f["kind"] == "leak_lease"]
    if not kills and not wedges and not leaks:
        return None
    import time as _time

    spent = set()

    def fault(prefill_index, worker):
        for i, f in enumerate(leaks):
            if ("leak", i) not in spent and \
                    prefill_index >= f["at_admit"]:
                spent.add(("leak", i))
                pool = worker.decoder.page_pool
                page = worker.decoder._alloc_pages(1)
                pool.begin_handoff(
                    page, owner="chaos:leak_lease",
                    # tfoslint: disable=TFOS004(lease deadline, not request column)
                    deadline_sec=f["deadline_sec"],
                )
                logger.warning(
                    "chaos: leaked handoff lease (page %s, deadline "
                    "%.2fs) at prefill %d per plan",
                    # tfoslint: disable=TFOS004(lease deadline, not request column)
                    page, f["deadline_sec"], prefill_index,
                )
        for i, f in enumerate(wedges):
            if ("wedge", i) not in spent and \
                    prefill_index >= f["at_admit"]:
                spent.add(("wedge", i))
                logger.warning(
                    "chaos: wedging prefill dispatch at prefill %d "
                    "for %.1fs per plan", prefill_index, f["hang_sec"],
                )
                _time.sleep(f["hang_sec"])
        for i, f in enumerate(kills):
            if ("kill", i) not in spent and \
                    prefill_index >= f["at_admit"]:
                spent.add(("kill", i))
                from tensorflowonspark_tpu.serving_disagg import (
                    PrefillWorkerDead,
                )

                worker.dead = True
                logger.warning(
                    "chaos: killing prefill worker at prefill %d "
                    "per plan", prefill_index,
                )
                raise PrefillWorkerDead(
                    "chaos kill_prefill at prefill {0}".format(
                        prefill_index
                    )
                )

    return fault


def replica_fault_fn(replica_id):
    """Build the fleet replica's chunk-dispatch fault hook from the
    plan, or None when no ``kill_replica`` / ``slow_replica`` fault
    targets it (the common case — one None check of production
    overhead, like every other plan hook).

    Returns ``fault(chunk_index)``, installed as the replica engine's
    ``wedge_fn`` (it runs right before every chunk dispatch): a due
    ``kill_replica`` raises
    :class:`~tensorflowonspark_tpu.fleet.replica.ReplicaKilled` (each
    entry fires once, in plan order); a ``device_error`` raises
    :class:`~tensorflowonspark_tpu.fleet.replica.ReplicaDeviceError`
    (the replica quarantines instead of dying); a ``slow_replica``
    sleeps ``per_chunk_sec`` while its chunk budget lasts."""
    plan = load_plan()
    if plan is None:
        return None
    rid = int(replica_id)
    kills = [
        f for f in plan.faults
        if f["kind"] == "kill_replica" and f["replica_id"] == rid
    ]
    devs = [
        f for f in plan.faults
        if f["kind"] == "device_error" and f["replica_id"] == rid
    ]
    slows = [
        f for f in plan.faults
        if f["kind"] == "slow_replica" and f["replica_id"] == rid
    ]
    if not kills and not devs and not slows:
        return None
    import time as _time

    spent = set()
    slowed = {"chunks": 0}

    def fault(chunk_index):
        for i, f in enumerate(kills):
            if i not in spent and chunk_index >= f["at_chunk"]:
                spent.add(i)
                from tensorflowonspark_tpu.fleet.replica import (
                    ReplicaKilled,
                )

                logger.warning(
                    "chaos: killing serving replica %d at chunk %d "
                    "per plan", rid, chunk_index,
                )
                raise ReplicaKilled(
                    "chaos kill_replica {0} at chunk {1}".format(
                        rid, chunk_index
                    )
                )
        for i, f in enumerate(devs):
            if ("dev", i) not in spent and chunk_index >= f["at_chunk"]:
                spent.add(("dev", i))
                from tensorflowonspark_tpu.fleet.replica import (
                    ReplicaDeviceError,
                )

                logger.warning(
                    "chaos: device error on serving replica %d at "
                    "chunk %d per plan", rid, chunk_index,
                )
                raise ReplicaDeviceError(
                    "chaos device_error on replica {0} at chunk "
                    "{1}".format(rid, chunk_index)
                )
        for f in slows:
            if f["chunks"] and slowed["chunks"] >= f["chunks"]:
                continue
            slowed["chunks"] += 1
            _time.sleep(f["per_chunk_sec"])
            return

    return fault


def ingest_delay():
    """Seconds the chaos plan orders checkpoint ingests stalled
    (``slow_ingest``), or None without a plan — the hot-swap
    watcher's default ``ingest_delay`` hook (a single None check of
    production overhead, like the other plan hooks)."""
    plan = load_plan()
    if plan is None:
        return None
    secs = [f["sec"] for f in plan.faults if f["kind"] == "slow_ingest"]
    return max(secs) if secs else None


def swap_chunk_from_plan():
    """The chunk index a ``swap_during_wedge`` fault targets, or None
    — the test-harness half of that fault (the harness publishes the
    new checkpoint so it lands while the wedge holds the dispatch)."""
    plan = load_plan()
    if plan is None:
        return None
    for f in plan.faults:
        if f["kind"] == "swap_at_chunk":
            return int(f["at_chunk"])
    return None


#: corrupt-checkpoint kinds :func:`corrupt_checkpoint` can inflict —
#: one per hot-swap validation stage (docs/serving.md "Live weight
#: swap & rollback"): a truncated array file fails the LOAD stage, a
#: garbage manifest fails the MANIFEST stage, shape-mismatched params
#: fail the TREE stage.  Every kind must be quarantined with its
#: typed reason and never served (tests/test_chaos_serving.py).
CORRUPT_KINDS = ("truncate_array", "bad_manifest", "shape_mismatch")


def corrupt_checkpoint(step_dir, kind):
    """Deterministically corrupt a PUBLISHED step export in place.

    - ``truncate_array``: the largest file under ``params/`` is cut
      to a third — the orbax restore must fail (``load_failed``);
    - ``bad_manifest``: the completion manifest becomes garbage bytes
      (``bad_manifest``);
    - ``shape_mismatch``: the export is re-published with its largest
      ``>=2``-D leaf padded by one along the last axis — loads fine,
      fails the live-model census check (``shape_mismatch``).

    Returns the path corrupted/republished.
    """
    import numpy as np  # noqa: F401 - shape kind below

    step_dir = os.fspath(step_dir)
    if kind == "truncate_array":
        biggest, size = None, -1
        for root, _dirs, files in os.walk(os.path.join(step_dir, "params")):
            for name in files:
                p = os.path.join(root, name)
                s = os.path.getsize(p)
                if s > size:
                    biggest, size = p, s
        if biggest is None:
            raise RuntimeError("no array files under %s" % step_dir)
        with open(biggest, "r+b") as f:
            f.truncate(max(1, size // 3))
        return biggest
    if kind == "bad_manifest":
        path = os.path.join(step_dir, "manifest.json")
        with open(path, "wb") as f:
            f.write(b"\x00garbage{{{not json")
        return path
    if kind == "shape_mismatch":
        from tensorflowonspark_tpu import checkpoint as ckpt

        params, _meta = ckpt.load_for_serving(step_dir)
        manifest = ckpt.read_manifest(step_dir) or {}
        ckpt.save_for_serving(
            step_dir, shape_mismatched_params(params),
            step=manifest.get("step"),
        )
        return step_dir
    raise ValueError(
        "unknown corrupt kind {0!r}; pick one of {1}".format(
            kind, CORRUPT_KINDS
        )
    )


def shape_mismatched_params(params):
    """A copy of ``params`` whose LARGEST ``>=2``-D leaf grew by one
    along its last axis — the shape-mismatch corrupt variant (loads
    cleanly, must be quarantined by the tree/shape validation
    stage)."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(params)
    target, target_size = None, -1
    for i, leaf in enumerate(leaves):
        if getattr(leaf, "ndim", 0) >= 2 and leaf.size > target_size:
            target, target_size = i, leaf.size
    if target is None:
        raise RuntimeError("params has no >=2-D leaf to mis-shape")
    out = list(leaves)
    a = np.asarray(out[target])
    pad = [(0, 0)] * (a.ndim - 1) + [(0, 1)]
    out[target] = np.pad(a, pad)
    return jax.tree_util.tree_unflatten(treedef, out)


#: poison-payload kinds :func:`poison_row` can build — one per
#: admission-validation failure class of the serving engine
POISON_KINDS = (
    "missing_key", "bad_dtype", "bad_shape", "empty", "oversized",
    "bad_budget",
)


def poison_row(kind, prompt_col="prompt", length=8, vocab=64, seed=0):
    """A deterministic malformed serving request of a named ``kind``
    (see :data:`POISON_KINDS`) — the poison half of the serving chaos
    family.  Each returns a dict row that passes through the normal
    request path and must be isolated at admission
    (``on_error="record"``) instead of killing the batch."""
    import numpy as np

    rng = np.random.RandomState(seed)
    good = rng.randint(0, vocab, (length,)).astype(np.int32)
    if kind == "missing_key":
        return {prompt_col + "_typo": good}
    if kind == "bad_dtype":
        return {prompt_col: good.astype(np.float32) + 0.5}
    if kind == "bad_shape":
        return {prompt_col: np.stack([good, good])}
    if kind == "empty":
        return {prompt_col: np.zeros((0,), np.int32)}
    if kind == "oversized":
        return {prompt_col: rng.randint(
            0, vocab, (1 << 16,)
        ).astype(np.int32)}
    if kind == "bad_budget":
        from tensorflowonspark_tpu.serving_engine import BUDGET_INPUT

        return {prompt_col: good, BUDGET_INPUT: "not-a-number"}
    raise ValueError(
        "unknown poison kind {0!r}; pick one of {1}".format(
            kind, POISON_KINDS
        )
    )


def slow_consumer(outputs, stall_sec=0.01, every=1):
    """Wrap a ``predict_rows`` output generator with consumer-side
    stalls: sleep ``stall_sec`` before every ``every``-th pull — the
    slow-downstream half of the serving chaos family.  The engine only
    advances between pulls, so a stalled consumer delays chunk
    boundaries; deadline expiry under the stall is CORRECT behavior
    and the emit-order/no-silent-drop invariants must survive it
    (tests/test_chaos_serving.py)."""
    import time as _time

    for i, row in enumerate(outputs):
        if i % max(1, int(every)) == 0:
            _time.sleep(stall_sec)
        yield row


def kill_compute(cluster, executor_id, sig=signal.SIGKILL):
    """Driver-side kill: SIGKILL the compute process of ``executor_id``
    right now (same-host clusters — the LocalEngine substrate).  Returns
    the pid killed.  The step-precise path is :func:`step_fault_fn`;
    this one is for tests that only need "a worker died mid-feed"."""
    from tensorflowonspark_tpu.cluster import manager as mgr_mod

    node = next(
        n for n in cluster.cluster_info if n["executor_id"] == executor_id
    )
    m = mgr_mod.connect(tuple(node["addr"]), bytes.fromhex(node["authkey"]))
    pid = m.get("compute_pid")._getvalue()
    if not pid:
        raise RuntimeError(
            "executor {0} has no compute pid recorded".format(executor_id)
        )
    os.kill(pid, sig)
    logger.warning(
        "chaos: killed compute pid %d of executor %d", pid, executor_id
    )
    return pid


class TcpGremlin(object):
    """A deterministic TCP fault proxy.

    Sits between a client and a real server::

        gremlin = TcpGremlin(server_addr)
        addr = gremlin.start()        # hand THIS to the client
        gremlin.refuse_next(2)        # next 2 connects are cut on accept
        gremlin.cut_all()             # sever every live connection NOW
        gremlin.stop()

    ``refuse_next`` models a server that is briefly unreachable (the
    client's connect succeeds at the TCP level, then the peer vanishes
    mid-handshake — the hard flavor of refusal to retry correctly);
    ``cut_all`` severs established connections the way a mid-request
    network partition does; ``delay(sec)`` stalls every forwarded
    chunk by ``sec`` — a congested/far link, the WIRE-phase straggler
    injection (``delay(0)`` restores full speed).
    """

    def __init__(self, target_addr, delay_sec=0.0):
        self.target_addr = tuple(target_addr)
        self._listener = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._refuse = 0
        self._delay = float(delay_sec)
        self._pairs = []  # live (client_sock, server_sock) pairs
        self.connections = 0  # total accepted (observability for tests)

    def start(self):
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(16)
        addr = ("127.0.0.1", self._listener.getsockname()[1])
        threading.Thread(
            target=self._accept_loop, daemon=True, name="gremlin-accept"
        ).start()
        return addr

    def refuse_next(self, n):
        with self._lock:
            self._refuse += int(n)

    def delay(self, sec):
        """Stall every forwarded chunk by ``sec`` seconds from now on
        (both directions) — deterministic wire-latency injection."""
        with self._lock:
            self._delay = float(sec)

    def cut_all(self):
        """Sever every live proxied connection immediately."""
        with self._lock:
            pairs, self._pairs = self._pairs, []
        for a, b in pairs:
            for s in (a, b):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
        return len(pairs)

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            self.connections += 1
            with self._lock:
                refuse = self._refuse > 0
                if refuse:
                    self._refuse -= 1
            if refuse:
                try:
                    client.close()
                except OSError:
                    pass
                continue
            try:
                server = socket.create_connection(self.target_addr, timeout=5)
            except OSError:
                client.close()
                continue
            with self._lock:
                self._pairs.append((client, server))
            for src, dst in ((client, server), (server, client)):
                threading.Thread(
                    target=self._pipe, args=(src, dst), daemon=True,
                    name="gremlin-pipe",
                ).start()

    def _pipe(self, src, dst):
        import time as _time

        try:
            while True:
                data = src.recv(1 << 16)
                if not data:
                    break
                with self._lock:
                    stall = self._delay
                if stall:
                    _time.sleep(stall)
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    def stop(self):
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self.cut_all()
