"""All-faults soak harness: one live stack, every chaos family,
continuous invariants.

The point of the fault-containment work (lease reaping, prefill
supervision, replica quarantine, remediation verbs) is that the FLEET
keeps its books balanced no matter which fault lands or when.  A
single-fault test proves one containment path; this harness proves the
conjunction: a :class:`SoakRunner` drives ONE live serving fleet
(including one disaggregated prefill/decode engine) — and, in full
mode, a live hier-training cluster with its health plane and
remediation engine — through a SEEDED schedule covering every chaos
family at once, while probing invariants between every load wave:

- **pool balance** — after each wave quiesces, every paged replica's
  :class:`~tensorflowonspark_tpu.prefix_cache.PagePool` refcount
  census equals exactly its radix cache's committed pages at one
  reference each, no handoff pages or leases in flight, the reserved
  trash page untouched (pages provably never leak, whatever died);
- **ledger exactness** — the usage ledger's per-request ``chip_sec``
  rows (plus its ``evicted_totals`` remainder, once traffic outgrows
  the bounded row table) sum to the fleet's measured decode wall to
  1e-6 relative, ACROSS kills, quarantine rebuilds, re-dispatches
  and row eviction;
- **zero silent drops** — every submitted request comes back exactly
  once, as either tokens or a named error record (poison rows must
  surface as error records naming their request, never vanish);
- **forensics naming** — every injected journal-visible fault family
  is named by ``forensics explain`` while its evidence is live
  (sampled each wave — the journal's bounded severity rings evict
  minute-one evidence before a long run ends), checked against the
  chaos-plan vocabulary (testing/chaos.py), so the soak's story is
  reconstructible from the black box alone.

Fault families (testing/chaos.py): ``wedge_dispatch``,
``kill_prefill``, ``wedge_prefill``, ``leak_lease``, ``kill_replica``,
``device_error`` and ``poison_rows`` on the serving plane;
``slow_executor``, ``kill_executor`` (chaos ``kill``), ``kill_leader``
and ``corrupt_checkpoint`` on the training plane (full mode only —
they need the live cluster).

CLI::

    python -m tensorflowonspark_tpu.testing.soak --minutes 5 --seed 7
    python -m tensorflowonspark_tpu.testing.soak --fast  # serving-only

``--fast`` skips the training cluster (the tier-1 CI lane: seeded,
deterministic schedule, well under a minute); the full run is the
acceptance soak (CI runs it behind ``-m slow`` via
tests/test_chaos_serving.py).  The JSON report lands at ``--report``
(default ``soak_report.json``) and is the CI artifact.
"""

import argparse
import json
import logging
import os
import sys
import tempfile
import time

import numpy as np

logger = logging.getLogger(__name__)

#: serving-plane families every soak injects (fast + full)
SERVING_FAMILIES = (
    "wedge_dispatch", "kill_prefill", "wedge_prefill", "leak_lease",
    "kill_replica", "device_error", "poison_rows",
)

#: training-plane families the FULL soak adds (they need the live
#: cluster + health plane + remediation engine)
TRAINING_FAMILIES = (
    "slow_executor", "kill", "kill_leader", "corrupt_checkpoint",
)

#: the tiny real transformer the soak serves (compiles in seconds on
#: CPU; the containment machinery under test is model-size-agnostic)
MODEL = {
    "vocab_size": 64, "num_layers": 2, "num_heads": 2, "head_dim": 8,
    "embed_dim": 16, "mlp_dim": 32, "max_seq_len": 128,
    "dtype": "float32",
}
PAGED = {"kv_layout": "paged", "prefix_cache": True, "prefix_block": 8}


class InvariantViolation(AssertionError):
    """A soak invariant probe failed — the report names which, when,
    and with what evidence."""


def pool_balance_probe(decoder, grace_sec=5.0, clock=None):
    """Assert ``decoder``'s page pool has settled back to exactly its
    radix cache's committed pages: no handoff pages or leases in
    flight, refcount census == radix census at one reference per page,
    reserved trash page(s) unreferenced.  Polls up to ``grace_sec``
    (slot releases lag the last emit by a scheduling pass).  Returns
    the settled census dict; raises :class:`InvariantViolation`."""
    clock = clock or time.monotonic
    pool = getattr(decoder, "page_pool", None)
    pc = getattr(decoder, "prefix_cache", None)
    if pool is None:
        return {"skipped": "not a paged decoder"}
    deadline = clock() + grace_sec
    last = None
    while True:
        stats = pool.stats()
        census = pool.refcount_census()
        radix = pc.page_census() if pc is not None else []
        want = {int(p): 1 for p in radix}
        trash = [p for p in census if p < pool.reserved]
        ok = (
            stats["pool_pages_handoff"] == 0
            and stats["pool_leases"] == 0
            and not trash
            and census == want
        )
        last = {
            "stats": stats, "refcounts": len(census),
            "radix_pages": len(radix), "trash_referenced": trash,
            "balanced": ok,
        }
        if ok:
            return last
        if clock() >= deadline:
            raise InvariantViolation(
                "page pool never rebalanced within {0:.1f}s: {1} "
                "(census {2} vs radix {3}; {4})".format(
                    grace_sec, stats, census, want, pool.lease_table()
                )
            )
        time.sleep(0.05)


def ledger_probe(router, ledger, rel=1e-6):
    """Assert the ledger's ``chip_sec`` rows sum to the fleet's decode
    wall — the cost-attribution exactness that must survive every
    kill/quarantine/re-dispatch (docs/observability.md).  The row
    table is BOUNDED (closed rows LRU-evict past ``max_rows``), so the
    conserved quantity is rows + the ledger's ``evicted_totals``
    remainder — a long soak pushes thousands of requests through a
    4096-row table and the law must keep holding."""
    chip = sum(r["chip_sec"] for r in ledger.rows())
    chip += ledger.evicted_totals["chip_sec"]
    wall = float(router.stats["decode_wall_sec"])
    if wall == 0.0 and chip == 0.0:
        return {"chip_sec": chip, "decode_wall_sec": wall}
    if abs(chip - wall) > rel * max(abs(chip), abs(wall)):
        raise InvariantViolation(
            "ledger chip-seconds ({0!r}) != fleet decode wall "
            "({1!r})".format(chip, wall)
        )
    return {"chip_sec": chip, "decode_wall_sec": wall}


class SoakRunner(object):
    """Drive the all-faults soak (module docstring).  ``run()``
    returns the JSON-able report and raises
    :class:`InvariantViolation` on the first broken invariant.

    Args:
      minutes: wall-clock load budget (waves stop at the deadline;
        every scheduled fault fires regardless because in-band
        triggers are index-based).
      seed: seeds the fault schedule, the prompts and the poison
        placement — same seed, same soak.
      include_training: full mode (live cluster + health plane +
        remediation + training faults); False is the fast serving-only
        lane.
      replicas: fleet width (>= 3 in full chaos so a kill plus a
        quarantine still leave a live replica).
      report_path: where ``run()`` writes the JSON report (None skips
        the write; the dict is returned either way).
    """

    def __init__(self, minutes=5.0, seed=0, include_training=True,
                 replicas=3, report_path=None, workdir=None):
        self.minutes = float(minutes)
        self.seed = int(seed)
        self.include_training = bool(include_training)
        self.replicas = max(2, int(replicas))
        self.report_path = report_path
        self.workdir = workdir or tempfile.mkdtemp(prefix="tfos_soak_")
        self.rng = np.random.RandomState(self.seed)
        #: run-long union of journal-visible fault families (sampled
        #: every wave — the bounded journal rings evict early
        #: evidence long before a 5-minute run ends)
        self._families_seen = set()
        self.report = {
            "seed": self.seed, "minutes": self.minutes,
            "mode": "full" if include_training else "serving_only",
            "families": list(SERVING_FAMILIES) + (
                list(TRAINING_FAMILIES) if include_training else []
            ),
            "faults": [], "waves": [], "invariants": {}, "passed": False,
        }

    # -- schedule -------------------------------------------------------

    def _serving_plan(self):
        """The seeded in-band serving fault schedule as ONE chaos plan
        (index-triggered: the counters are cumulative across the soak,
        so each fault lands in an early wave and later waves prove
        recovery held).  ``kill_replica`` targets the LAST replica and
        ``device_error`` the disaggregated replica 0, so the fleet
        always keeps a live survivor."""
        from tensorflowonspark_tpu.testing import chaos

        r = self.rng
        plan = chaos.ChaosPlan()
        plan.kill_prefill(at_admit=int(r.randint(1, 4)))
        plan.wedge_prefill(at_admit=int(r.randint(5, 8)), hang_sec=3.0)
        plan.leak_lease(at_admit=int(r.randint(9, 12)),
                        deadline_sec=0.3)
        plan.wedge_dispatch(at_chunk=int(r.randint(2, 6)), hang_sec=3.0)
        plan.device_error(0, at_chunk=int(r.randint(2, 8)))
        plan.kill_replica(self.replicas - 1,
                          at_chunk=int(r.randint(4, 10)))
        for f in plan.faults:
            self.report["faults"].append(dict(f, plane="serving"))
        return plan

    def _training_spec(self):
        """Seeded training-plane schedule: the in-band faults ride the
        cluster plan env; ``kill_leader`` / ``corrupt_checkpoint``
        fire driver-side at their offsets (the remediation acceptance
        e2e's protocol — tests/test_remediation.py)."""
        r = self.rng
        spec = {
            "slow_executor": {
                "executor_id": 1,
                "per_batch_sec": 0.06, "batches": 40,
            },
            "kill": {
                "executor_id": 1, "at_step": int(r.randint(30, 60)),
            },
            "kill_leader": {
                "at_window": 3,
                "at_sec": float(r.uniform(3.0, 6.0)),
            },
            "corrupt_checkpoint": {
                "corrupt_kind": "bad_manifest",
                "at_sec": float(r.uniform(6.0, 10.0)),
            },
        }
        for kind, f in spec.items():
            self.report["faults"].append(
                dict(f, kind=kind, plane="training")
            )
        return spec

    # -- stack ----------------------------------------------------------

    def _build_fleet(self, plan_path, readmit_gate=None):
        """The live fleet: replica 0 disaggregated (paged + prefix +
        PrefillWorker), the rest unified paged engines over the same
        weights — mixed on purpose, both shapes must stay
        token-identical through the storm.  Warms every compiled
        program, then advertises ``plan_path`` so the chaos hooks arm
        exactly when the replica engines construct."""
        import jax
        import jax.numpy as jnp

        from tensorflowonspark_tpu.fleet.router import FleetRouter
        from tensorflowonspark_tpu.models import transformer as tr

        model = tr.Transformer(tr.TransformerConfig(**MODEL))
        params = jax.tree.map(np.asarray, model.init(
            jax.random.PRNGKey(self.seed),
            jnp.zeros((1, 8), jnp.int32),
        )["params"])
        base = dict(MODEL, mode="generate", max_new_tokens=6,
                    pad_multiple=16, chunk_size=2, **PAGED)
        predicts = [tr.serving_builder(
            params, dict(base, disaggregate=True)
        )]
        for _ in range(self.replicas - 1):
            predicts.append(tr.serving_builder(params, dict(base)))

        def factory():
            # inexhaustible: remediation spawn_replica builds spares
            if predicts:
                return predicts.pop(0)
            return tr.serving_builder(params, dict(base))

        # warm every compiled program BEFORE the watchdogs go live
        # (repo convention — a watchdog timeout assumes compiled
        # programs; a cold compile would fire it spuriously).  The
        # chaos plan env is not yet advertised, so nothing faults.
        from tensorflowonspark_tpu import serving as _serving

        warm = [
            {"prompt": np.arange(1, 9, dtype=np.int32)},
            {"prompt": np.arange(1, 21, dtype=np.int32)},
        ]
        from tensorflowonspark_tpu.testing import chaos as _chaos

        os.environ.pop(_chaos.TFOS_CHAOS_PLAN, None)
        for p in predicts:
            list(_serving.predict_rows(
                p, [dict(r) for r in warm], {"prompt": "tokens"},
                batch_size=2, schedule="continuous",
            ))
        os.environ[_chaos.TFOS_CHAOS_PLAN] = plan_path
        router = FleetRouter(
            None, {"prompt": "tokens"}, replicas=self.replicas,
            num_slots=2, predict_factory=factory, on_error="record",
            poll_sec=0.01, probe_every=4, readmit_rounds=2,
            readmit_gate=readmit_gate,
            engine_opts={"watchdog_timeout": 2.0},
        )
        return router

    def _build_cluster(self, plan_path):
        """Full mode's training side: a 2-executor LocalEngine cluster
        running the telemetry-publishing feed loop, with the health
        plane scraping and the remediation engine closing the loop
        over BOTH planes."""
        from tensorflowonspark_tpu.cluster import cluster as tpu_cluster
        from tensorflowonspark_tpu.cluster.cluster import InputMode
        from tensorflowonspark_tpu.engine import LocalEngine
        from tensorflowonspark_tpu.remediation import Guardrails

        env = {
            "TFOS_CHAOS_PLAN": plan_path,
            "TFOS_TELEMETRY": "1",
            "TFOS_TELEMETRY_PUBLISH_INTERVAL": "0.2",
        }
        engine = LocalEngine(2, env=env, deterministic=True)
        cluster = tpu_cluster.run(
            engine, _soak_train_fn, args={}, num_executors=2,
            input_mode=InputMode.SPARK, elastic=True,
            heartbeat_interval=0.5, max_restarts=2,
        )
        plane = cluster.start_health_plane(
            interval=0.5,
            straggler_opts={
                "window": 20.0, "min_samples": 5, "ratio": 2.0,
            },
        )
        return engine, cluster, plane, Guardrails(
            cooldown_sec=30.0, budget=25
        )

    # -- load -----------------------------------------------------------

    def _wave_rows(self, wave):
        """One wave's request mix: shared prefix heads (radix traffic)
        plus fresh tails, with poison rows injected at seeded waves."""
        from tensorflowonspark_tpu.testing import chaos

        r = self.rng
        if not hasattr(self, "_heads"):
            self._heads = [
                r.randint(1, 64, (16,)).astype(np.int32)
                for _ in range(3)
            ]
        rows, poisons = [], []
        for i in range(8):
            if i % 3 == 0:
                head = self._heads[i % len(self._heads)]
                tail = r.randint(1, 64, (int(r.randint(2, 6)),))
                rows.append({"prompt": np.concatenate(
                    [head, tail]
                ).astype(np.int32)})
            else:
                rows.append({"prompt": r.randint(
                    1, 64, (int(r.randint(4, 20)),)
                ).astype(np.int32)})
        if wave in self._poison_waves:
            kind = self._poison_waves[wave]
            pos = int(r.randint(0, len(rows)))
            rows.insert(pos, chaos.poison_row(kind))
            poisons.append({"wave": wave, "kind": kind, "pos": pos})
            self.report["faults"].append(
                {"kind": "poison_rows", "poison_kind": kind,
                 "wave": wave, "plane": "serving"}
            )
        return rows, poisons

    def _snapshot_named_families(self, extra_events=None):
        """Fold the fault families currently visible in the journal
        into the run-long accumulator.  The journal's severity rings
        are BOUNDED: a straggler flagged in minute one is evicted by
        minute four's serving-fault traffic, so the naming invariant
        must be sampled while the evidence is live, not only at the
        end."""
        from tensorflowonspark_tpu import forensics
        from tensorflowonspark_tpu.telemetry import journal as jm

        for e in jm.get_journal().events():
            fam = forensics.FAULT_MAP.get(e.kind)
            if fam is not None:
                self._families_seen.add(fam)
        for e in extra_events or []:
            fam = forensics.FAULT_MAP.get(e.get("kind"))
            if fam is not None:
                self._families_seen.add(fam)

    def _probe(self, router, ledger, wave, accounted):
        self._snapshot_named_families()
        inv = {}
        for rep in router.replicas:
            if not (rep.alive and rep.state in ("live",
                                                "routed_around")):
                # a dead replica's pool is wreckage (its device memory
                # dies with the process in reality) — the leak
                # invariant audits serviceable pools
                continue
            dec = getattr(rep.engine, "decoder", None)
            if dec is None or not getattr(dec, "_paged", False):
                continue
            inv["pool_balance_r{0}".format(rep.replica_id)] = (
                pool_balance_probe(dec)
            )
        inv["ledger"] = ledger_probe(router, ledger)
        inv["accounting"] = dict(accounted)
        if accounted["returned"] != accounted["submitted"]:
            raise InvariantViolation(
                "dropped requests: submitted {0}, returned {1} "
                "(wave {2})".format(
                    accounted["submitted"], accounted["returned"], wave
                )
            )
        if accounted["errors"] != accounted["poisoned"]:
            raise InvariantViolation(
                "error records ({0}) != injected poison rows ({1}) — "
                "a healthy request errored or a poison vanished "
                "(wave {2})".format(
                    accounted["errors"], accounted["poisoned"], wave
                )
            )
        return inv

    def _forensics_probe(self, extra_events=None):
        """``explain`` over the journal must name every journal-
        visible injected family in the chaos vocabulary.  Poison rows
        are accounted by the error-record invariant instead (they are
        per-request records, not fleet incidents).  Families sampled
        live during the run (:meth:`_snapshot_named_families`) count:
        the journal's bounded rings legitimately evict minute-one
        evidence by minute five — the invariant is that every family
        WAS named while its evidence was live, not that a bounded
        ring retains everything forever."""
        from tensorflowonspark_tpu import forensics
        from tensorflowonspark_tpu.telemetry import journal as jm

        events = [e.to_dict() for e in jm.get_journal().events()]
        for e in extra_events or []:
            if e not in events:
                events.append(e)
        export = os.path.join(self.workdir, "journal_export.json")
        with open(export, "w") as f:
            json.dump({"events": events}, f)
        report = forensics.explain([export])
        named = {
            forensics.FAULT_MAP[ev["kind"]]
            for ev in report["timeline"]
            if ev["kind"] in forensics.FAULT_MAP
        }
        named |= self._families_seen
        want = {
            f["kind"] for f in self.report["faults"]
            if f["kind"] not in ("poison_rows", "leak_lease")
        }
        # leak_lease is named via its reaping (lease_reaped)
        if any(f["kind"] == "leak_lease" for f in self.report["faults"]):
            want.add("leak_lease")
        missing = want - named
        if missing:
            raise InvariantViolation(
                "forensics explain failed to name injected fault "
                "families {0} (named: {1})".format(
                    sorted(missing), sorted(named)
                )
            )
        return {"named": sorted(named), "report_window_sec":
                report.get("window_sec")}

    # -- run ------------------------------------------------------------

    def _serving_faults_fired(self, router):
        """Have all in-band serving faults landed?  (The index-based
        triggers need enough traffic to reach their counters; waves
        keep flowing past the time budget until they do.)"""
        from tensorflowonspark_tpu.telemetry import journal as jm

        def eng_sum(key):
            return sum(
                int(r.engine.stats.get(key, 0))
                for r in router.replicas
            )

        return (
            eng_sum("prefill_worker_deaths") >= 1
            and eng_sum("prefill_watchdog_fires") >= 1
            and eng_sum("watchdog_fires") >= 1
            and len(jm.get_journal().events(kind="lease_reaped")) >= 1
            and router.stats.get("quarantined", 0) >= 1
            and router.stats.get("replica_deaths", 0) >= 1
        )

    def run(self):
        import threading

        from tensorflowonspark_tpu.telemetry import ledger as ledger_mod
        from tensorflowonspark_tpu.testing import chaos

        t_start = time.monotonic()
        ledger = ledger_mod.get_ledger()
        ledger.enabled_override = True

        serving_plan = self._serving_plan()
        self._poison_waves = {1: "bad_dtype", 3: "missing_key"}

        training = None
        gate = None
        storm = None
        trainer = None
        train_err = {}
        router = None
        remediation = None
        if self.include_training:
            spec = self._training_spec()
            full_plan = chaos.ChaosPlan.combined(
                slow_executor=spec["slow_executor"],
                kill_leader=spec["kill_leader"],
                corrupt_checkpoint=spec["corrupt_checkpoint"],
            )
            full_plan.faults.append(dict(spec["kill"], kind="kill"))
            cluster_plan_path = full_plan.save(
                os.path.join(self.workdir, "train_plan.json")
            )
            training = self._build_cluster(cluster_plan_path)
            engine, cluster, plane, guards = training
            from tensorflowonspark_tpu.telemetry.health import (
                CleanRoundsSensor,
            )

            gate = CleanRoundsSensor(plane, rounds=2)

        serving_plan_path = serving_plan.save(
            os.path.join(self.workdir, "serving_plan.json")
        )
        try:
            router = self._build_fleet(serving_plan_path,
                                       readmit_gate=gate)
            if training is not None:
                engine, cluster, plane, guards = training
                remediation = cluster.start_remediation(
                    router=router, interval=0.25, guardrails=guards,
                    straggler={"sustain": 2, "grow_after": 9999},
                    autoscale=None, page=None, slo_rollback=None,
                )
                storm = self._start_training_storm(cluster, spec)

                def _train():
                    try:
                        parts = [
                            [float(i) for i in range(80)]
                            for _ in range(8)
                        ]
                        cluster.train(
                            parts, num_epochs=2, feed_timeout=120
                        )
                    except Exception as e:  # noqa: BLE001
                        train_err["exc"] = e

                trainer = threading.Thread(target=_train, daemon=True)
                trainer.start()
            # the exactness probe compares ledger rows against the
            # ROUTER's decode wall: zero the ledger only now, after
            # the warmup traffic (which ran outside the router)
            ledger.reset()
            deadline = t_start + self.minutes * 60.0
            hard_cap = deadline + 120.0
            wave = 0
            while True:
                rows, poisons = self._wave_rows(wave)
                out = list(router.serve([dict(r) for r in rows]))
                accounted = {
                    "submitted": len(rows),
                    "returned": len(out),
                    "errors": sum(1 for r in out if "error" in r),
                    "poisoned": len(poisons),
                }
                inv = self._probe(router, ledger, wave, accounted)
                self.report["waves"].append({
                    "wave": wave, "accounting": accounted,
                    "t_sec": round(time.monotonic() - t_start, 3),
                })
                self.report["invariants"] = inv
                wave += 1
                now = time.monotonic()
                fired = self._serving_faults_fired(router)
                if wave >= 5 and fired and now >= deadline:
                    break
                if now >= hard_cap:
                    # the forensics probe below fails loudly on any
                    # fault the load never reached
                    logger.warning(
                        "soak hard cap reached with faults unfired"
                    )
                    break
            if training is not None:
                engine, cluster, plane, guards = training
                if trainer is not None:
                    trainer.join(timeout=180)
                if storm is not None:
                    storm.join(timeout=60)
                if "exc" in train_err:
                    raise train_err["exc"]
                self._await_remediation(remediation)
                extra = cluster.journal()["events"]
            else:
                extra = None
            self.report["router_stats"] = {
                k: v for k, v in router.stats.items()
                if isinstance(v, (int, float, str))
            }
            self.report["invariants"]["forensics"] = (
                self._forensics_probe(extra_events=extra)
            )
            self.report["passed"] = True
            self.report["wall_sec"] = round(
                time.monotonic() - t_start, 3
            )
            return self.report
        finally:
            os.environ.pop(chaos.TFOS_CHAOS_PLAN, None)
            if router is not None:
                try:
                    router.close()
                except Exception:
                    logger.exception("router close failed")
            if training is not None:
                engine, cluster, plane, guards = training
                try:
                    cluster.shutdown(grace_secs=1, timeout=60)
                except Exception:
                    logger.exception("cluster shutdown failed")
                engine.stop()
            ledger.enabled_override = None
            if self.report_path:
                with open(self.report_path, "w") as f:
                    json.dump(self.report, f, indent=2, default=str)
                logger.info("soak report written to %s",
                            self.report_path)

    def _start_training_storm(self, cluster, spec):
        """Driver-side timed faults (the e2e protocol): the leader-
        death SIGNAL at its offset, and a REAL corrupted export pushed
        through the CheckpointWatcher validation pipeline."""
        import threading

        from tensorflowonspark_tpu import hot_swap, telemetry
        from tensorflowonspark_tpu.testing import chaos

        t0 = time.monotonic()
        sched = sorted(
            (s["at_sec"], k) for k, s in spec.items()
            if "at_sec" in s
        )

        def _storm():
            for at_sec, kind in sched:
                delay = t0 + at_sec - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                if kind == "kill_leader":
                    telemetry.get_tracer().mark(
                        "leader_failover", trace="hier",
                        severity="page",
                        window=spec["kill_leader"]["at_window"],
                        injected=True,
                    )
                elif kind == "corrupt_checkpoint":
                    root = os.path.join(self.workdir, "exports")
                    step_dir = os.path.join(root, "7")
                    os.makedirs(step_dir, exist_ok=True)
                    with open(os.path.join(
                        step_dir, "manifest.json"
                    ), "w") as f:
                        f.write('{"complete": true}')
                    chaos.corrupt_checkpoint(
                        step_dir,
                        spec["corrupt_checkpoint"]["corrupt_kind"],
                    )
                    hot_swap.CheckpointWatcher(
                        root, background=False
                    ).poll()

        t = threading.Thread(target=_storm, daemon=True)
        t.start()
        return t

    def _await_remediation(self, remediation, timeout=30.0):
        """Give the policy engine its grace window to land the
        decisions the storm forces, then record them."""
        deadline = time.monotonic() + timeout
        want = {"elastic_shrink"}
        while time.monotonic() < deadline:
            executed = {
                d["action"] for d in remediation.decisions
                if d["executed"]
            }
            if want <= executed:
                break
            time.sleep(0.25)
        self.report["remediation_decisions"] = [
            {"action": d["action"], "policy": d["policy"],
             "executed": d["executed"]}
            for d in remediation.decisions
        ]


def _soak_train_fn(args, ctx):
    """Executor-side feed loop publishing the real per-executor
    telemetry the health plane scrapes, with the chaos hooks wrapping
    the feed and the step counter (slow_executor lands in feed_wait;
    a plan ``kill`` SIGKILLs the compute process at its step)."""
    import time as _t

    import numpy as np

    from tensorflowonspark_tpu import telemetry, tensorboard
    from tensorflowonspark_tpu.testing import chaos as _chaos

    reg = telemetry.get_registry()
    h_step = reg.histogram("train.step_sec")
    h_feed = reg.histogram("train.feed_wait_sec")
    steps = reg.counter("train.steps")
    feed = ctx.get_data_feed(train_mode=True)
    delay = _chaos.slow_feed_fn(ctx)
    if delay is not None:
        feed = _chaos.SlowFeed(feed, delay)
    kill = _chaos.step_fault_fn(ctx)
    n = 0
    while not feed.should_stop():
        t0 = _t.perf_counter()
        rows = feed.next_batch(4)
        h_feed.observe(_t.perf_counter() - t0)
        if not rows:
            continue
        t1 = _t.perf_counter()
        float(np.sum(np.asarray(rows, dtype=np.float64)))
        _t.sleep(0.004)
        h_step.observe(_t.perf_counter() - t1)
        steps.inc()
        n += 1
        if kill is not None:
            kill(n)
        tensorboard.profile_step()


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m tensorflowonspark_tpu.testing.soak",
        description="all-faults soak over a live training + serving "
                    "stack (module docstring)",
    )
    p.add_argument("--minutes", type=float, default=5.0,
                   help="load budget in minutes (default 5)")
    p.add_argument("--seed", type=int, default=0,
                   help="schedule/prompt seed (default 0)")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--fast", action="store_true",
                   help="serving-plane only (no training cluster): "
                        "the deterministic tier-1 lane")
    p.add_argument("--report", default="soak_report.json",
                   help="JSON report path (default soak_report.json)")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    runner = SoakRunner(
        minutes=args.minutes, seed=args.seed,
        include_training=not args.fast, replicas=args.replicas,
        report_path=args.report,
    )
    try:
        report = runner.run()
    except InvariantViolation as e:
        logger.error("SOAK FAILED: %s", e)
        runner.report["violation"] = str(e)
        if args.report:
            with open(args.report, "w") as f:
                json.dump(runner.report, f, indent=2, default=str)
        return 1
    print(json.dumps({
        "passed": report["passed"],
        "waves": len(report["waves"]),
        "faults_injected": len(report["faults"]),
        "wall_sec": report.get("wall_sec"),
        "report": args.report,
    }, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
