"""Gradient compression codecs for the PS/DP communication plane.

The async-PS wire (``parallel/ps.py``) ships every gradient and every
parameter reply as raw float32 across the device-host tunnel and the
TCP fabric — the measured bottleneck of the async path (STATUS.md:
``async_ps_tpu`` 1.6 steps/s vs sync 118.7, "per-step device->host
grad transfer over the tunnel").  This module attacks the *bytes* axis:

- :class:`Int8Codec` — per-tensor symmetric int8 quantization (4x
  fewer wire bytes than float32).  Lossy; pair with
  :class:`ErrorFeedback` so the quantization error is accumulated
  client-side and re-injected into the next step's gradient (the
  EF-SGD construction: the *running sum* of what crossed the wire
  tracks the running sum of the true gradients, which preserves
  convergence where naive quantization stalls).
- :class:`TopKCodec` — magnitude top-k sparsification; wire format is
  (indices, values) pairs.  Much higher compression (k/n of the
  values + index overhead); always run it under error feedback, the
  dropped (n-k) coordinates are *all* error.
- :class:`NoneCodec` — identity, so codec choice is uniform plumbing.

Codecs are numpy-only and deterministic: the PS server decodes with the
same arithmetic the client used to compute its residual, so the two
sides agree bit-for-bit on what crossed the wire (the delta-reply path
in ``parallel/ps.py`` relies on this to keep the server's per-connection
client view drift-free).

Wire integration: ``encode`` returns ``(parts, meta)`` where ``parts``
is a list of C-contiguous numpy arrays (the payloads laid on the
socket) and ``meta`` is a small JSON-able dict; ``decode(parts, meta)``
reconstructs the dense array.  ``parallel/ps.py`` frames these per
tensor (see ``send_msg``'s codec path).
"""

import numpy as np

__all__ = [
    "CODECS",
    "Codec",
    "ErrorFeedback",
    "Int8Codec",
    "NoneCodec",
    "TopKCodec",
    "dtype_str",
    "encoded_nbytes",
    "get_codec",
    "resolve_dtype",
]


def dtype_str(dt):
    """Wire-safe dtype spelling.  ``dtype.str`` round-trips for every
    builtin numpy dtype, but extension dtypes (``ml_dtypes.bfloat16``,
    the gradient dtype of bf16 training) stringify as an opaque void
    (``'<V2'``) that ``np.dtype()`` resolves to raw bytes — a silent
    corruption, not an error.  For those the registered NAME
    (``'bfloat16'``) is the round-trippable spelling."""
    dt = np.dtype(dt)
    s = dt.str
    try:
        if np.dtype(s) == dt:
            return s
    except TypeError:
        pass
    return dt.name


def resolve_dtype(s):
    """Inverse of :func:`dtype_str` (``np.dtype`` accepts both the
    ``.str`` and the registered-name spellings)."""
    return np.dtype(str(s))


class Codec(object):
    """Base codec: ``encode(arr) -> (parts, meta)``, ``decode`` inverts.

    ``parts`` arrays must be C-contiguous (they go straight onto the
    socket as memoryviews); ``meta`` must be JSON-able.
    """

    name = None

    def encode(self, arr):
        raise NotImplementedError

    def decode(self, parts, meta):
        raise NotImplementedError

    def spec(self):
        """JSON-able constructor spec, ``[name, kwargs]`` — what the
        client advertises when negotiating a reply codec."""
        return [self.name, {}]


class NoneCodec(Codec):
    """Identity codec: one part, the array itself."""

    name = "none"

    def encode(self, arr):
        arr = np.ascontiguousarray(arr)
        return [arr], {"dtype": dtype_str(arr.dtype), "shape": list(arr.shape)}

    def decode(self, parts, meta):
        return parts[0]


class Int8Codec(Codec):
    """Per-tensor symmetric int8 quantization.

    ``q = round(x / scale)`` with ``scale = max|x| / 127`` — zero maps
    to zero exactly (gradients are zero-heavy) and the dynamic range
    adapts per tensor per message.  float32 → int8 is a 4x wire-byte
    reduction; the scale rides in the JSON meta.
    """

    name = "int8"

    def encode(self, arr):
        arr = np.ascontiguousarray(arr)
        dtype = arr.dtype
        f = arr.astype(np.float32, copy=False)
        amax = float(np.max(np.abs(f))) if f.size else 0.0
        scale = amax / 127.0 if amax > 0 else 1.0
        q = np.clip(np.rint(f / scale), -127, 127).astype(np.int8)
        return [q], {
            "dtype": dtype_str(dtype),
            "shape": list(arr.shape),
            "scale": scale,
        }

    def decode(self, parts, meta):
        q = parts[0].reshape(meta["shape"])
        out = q.astype(np.float32) * np.float32(meta["scale"])
        return out.astype(resolve_dtype(meta["dtype"]), copy=False)


class TopKCodec(Codec):
    """Magnitude top-k sparsification: ship the k largest-|x| entries
    as (flat indices, values); the receiver scatters into zeros.

    Args:
      ratio: fraction of entries kept (``k = ceil(ratio * n)``, min 1).
      min_size: tensors with fewer elements ship dense (index overhead
        would exceed the savings on tiny biases).
    """

    name = "topk"

    def __init__(self, ratio=0.01, min_size=1024):
        if not 0.0 < ratio <= 1.0:
            raise ValueError("topk ratio must be in (0, 1], got %r" % ratio)
        self.ratio = float(ratio)
        self.min_size = int(min_size)

    def spec(self):
        return [self.name, {"ratio": self.ratio, "min_size": self.min_size}]

    def encode(self, arr):
        arr = np.ascontiguousarray(arr)
        dtype = arr.dtype
        flat = arr.reshape(-1).astype(np.float32, copy=False)
        n = flat.size
        if n <= self.min_size:
            dense = np.ascontiguousarray(arr)
            return [dense], {
                "dtype": dtype_str(dtype),
                "shape": list(arr.shape),
                "dense": True,
            }
        k = max(1, int(np.ceil(self.ratio * n)))
        # argpartition is O(n); indices sorted afterwards so the wire
        # format is canonical (equal inputs -> equal bytes)
        idx = np.argpartition(np.abs(flat), n - k)[n - k:]
        idx = np.sort(idx).astype(np.int64 if n > np.iinfo(np.int32).max
                                  else np.int32)
        vals = np.ascontiguousarray(flat[idx])
        idx = np.ascontiguousarray(idx)
        return [idx, vals], {
            "dtype": dtype_str(dtype),
            "shape": list(arr.shape),
            "k": int(k),
        }

    def decode(self, parts, meta):
        shape = meta["shape"]
        dtype = resolve_dtype(meta["dtype"])
        if meta.get("dense"):
            return parts[0].reshape(shape)
        idx, vals = parts
        out = np.zeros(int(np.prod(shape)) if shape else 1, np.float32)
        out[idx] = vals
        return out.reshape(shape).astype(dtype, copy=False)


CODECS = {c.name: c for c in (NoneCodec, Int8Codec, TopKCodec)}


def get_codec(spec):
    """Resolve a codec spec: an instance passes through; a name or a
    ``(name, kwargs)`` pair constructs from :data:`CODECS` (named specs
    only — never deserialized code, the same hardening rule as the PS
    optimizers)."""
    if spec is None:
        return None
    if isinstance(spec, Codec):
        return spec
    if isinstance(spec, str):
        name, kwargs = spec, {}
    else:
        name, kwargs = spec[0], (spec[1] if len(spec) > 1 else None) or {}
    if name not in CODECS:
        raise ValueError(
            "unknown codec {0!r}; supported: {1}".format(name, sorted(CODECS))
        )
    return CODECS[name](**kwargs)


def encoded_nbytes(parts):
    """Payload bytes a parts list lays on the wire (headers excluded)."""
    return sum(int(p.nbytes) for p in parts)


class ErrorFeedback(object):
    """Client-side error feedback around a lossy codec.

    Per tensor name, the residual ``r`` accumulates what compression
    dropped; each step encodes ``g + r`` and keeps the new remainder:

        e = encode(g + r);  r' = (g + r) - decode(e)

    so the sum of decoded messages telescopes to the sum of true
    gradients — quantization error is *delayed*, never lost (the
    memory-compensated SGD construction; convergence-parity is tested
    on a quadratic bowl in ``tests/test_compress.py`` and end-to-end
    against sync SGD in ``tests/test_ps.py``).

    Thread-safety: each name's residual is read and written by exactly
    one caller at a time (the PS client's shard workers partition the
    name space), which is the only discipline required.
    """

    def __init__(self, codec):
        self.codec = get_codec(codec)
        if self.codec is None or isinstance(self.codec, NoneCodec):
            raise ValueError("error feedback requires a lossy codec")
        self._residual = {}

    @property
    def name(self):
        return self.codec.name

    def spec(self):
        return self.codec.spec()

    def encode_named(self, name, arr):
        """Encode ``arr`` under the accumulated residual for ``name``."""
        arr = np.asarray(arr)
        f = arr.astype(np.float32, copy=True)
        r = self._residual.get(name)
        if r is not None and r.shape == f.shape:
            f += r
        parts, meta = self.codec.encode(f)
        approx = self.codec.decode(
            [p.copy() for p in parts], meta
        ).astype(np.float32, copy=False)
        # the residual MUST stay float32: a bf16 residual would round
        # away exactly the small corrections error feedback exists to
        # carry (tested in tests/test_compress.py::TestBfloat16)
        self._residual[name] = f - approx
        # the receiver reconstructs in the original dtype
        meta = dict(meta, dtype=dtype_str(arr.dtype))
        return parts, meta

    def decode(self, parts, meta):
        return self.codec.decode(parts, meta)

    def reset(self):
        self._residual.clear()
