"""Zero-downtime rolling deploys across a serving fleet.

The single-engine hot-swap plane (PR 8) already gives one replica a
zero-drop weight swap: quiesce between chunks, install, canary,
probation window, automatic rollback, typed quarantine.  A fleet
deploy is that transaction **one replica at a time behind router
drain**, gated on each replica's post-swap health:

1. **drain** — the router stops dispatching to the target replica
   (its state flips to ``draining``; siblings absorb the traffic) and
   waits for its assigned requests to complete;
2. **swap** — the new generation is resolved for THIS replica: either
   in-process ``params`` or a published ``step_dir`` walked through
   the full hot-swap validation pipeline
   (:func:`~tensorflowonspark_tpu.hot_swap.validate_checkpoint` —
   manifest / load / tree-shape-dtype vs the replica's own param
   census / optional canary) — then queued via the engine's
   ``request_swap``; the idle replica's lifecycle pass applies it
   between heartbeats;
3. **gate** — the replica re-admits to routing and must prove the new
   generation healthy: ``gate="commit"`` (default) waits for the
   engine's probation window to close (``rollback_window`` clean
   requests → ``swap_commit``), ``gate="applied"`` accepts the
   post-install canary alone (deploys against an idle fleet);
4. **next replica** — in order, the FIRST replica is the canary.

Any failure — validation rejection, install refusal, post-install
canary rollback, probation rollback, or a phase timeout — **halts the
rollout fleet-wide**: no further replica is touched (a canary burn
leaves every sibling on the old generation — the acceptance e2e), the
offending step is quarantined so no watcher re-offers it, and the
halt is a ``page``-severity journal event (``deploy_halted``).

The machine is **stepped by the router's scheduling loop**
(:meth:`FleetRouter._deploy_step`) — single-threaded, deterministic,
and always interleaved with live traffic, which is what "zero
downtime" means.  See docs/serving.md "Fleet routing & rolling
deploys".
"""

import logging
import time

from tensorflowonspark_tpu import telemetry

logger = logging.getLogger(__name__)


class DeployHalted(Exception):
    """Raised by :meth:`RollingDeploy.raise_if_halted` for callers
    that want the halt as an exception rather than a status."""


class RollingDeploy(object):
    """One rolling-deploy transaction (see module docstring).

    Exactly one weight source:

    Args:
      params: in-process new-generation params (``request_swap``
        shape — tests, benches, trainer-to-server handoff).
      step: generation tag for ``params`` (default: each engine's
        ``weight_generation + 1``).
      step_dir: a published step-export directory
        (``publish_for_serving`` layout) validated per replica
        through the PR 8 pipeline before it may install.
      gate: ``"commit"`` (probation window must close under live
        traffic) or ``"applied"`` (post-install canary alone).
        ``"commit"`` needs requests FLOWING — a replica proves its
        new generation on real completions; deploying against an
        idle fleet with the commit gate runs into ``phase_timeout``
        by design (no evidence of health, no rollout).  Use
        ``"applied"`` for idle-fleet deploys.
      order: replica-id order (default: ascending live ids; the
        first is the canary).
      phase_timeout: seconds a single phase may take before the
        rollout halts (``timeout:<phase>``).
      refuse_grace: seconds a consumed-but-unapplied swap request may
        dangle before it counts as an install refusal (the engine
        quarantined it without a stats transition).
      clock: monotonic override (tests).
    """

    def __init__(self, params=None, step=None, step_dir=None, *,
                 gate="commit", order=None, phase_timeout=120.0,
                 refuse_grace=5.0, clock=None):
        if (params is None) == (step_dir is None):
            raise ValueError(
                "pass exactly one of params= (in-process weights) or "
                "step_dir= (published step export)"
            )
        if gate not in ("commit", "applied"):
            raise ValueError(
                "gate must be 'commit' or 'applied', got %r" % (gate,)
            )
        self.params = params
        self.step = step
        self.step_dir = step_dir
        self.gate = gate
        self.order = list(order) if order is not None else None
        self.phase_timeout = float(phase_timeout)
        self.refuse_grace = float(refuse_grace)
        self._clock = clock if clock is not None else time.monotonic
        self._tracer = telemetry.get_tracer()
        self._i = 0              # index into the replica order
        self._phase = "start"
        self._phase_t0 = None
        self._base = None        # engine stats snapshot at swap issue
        self._refuse_t0 = None
        self.finished = False
        self.status = {
            "state": "running", "phase": "start", "replica": None,
            "target_step": step if step is not None else (
                "dir:%s" % step_dir if step_dir else None
            ),
            "gate": gate, "replicas_done": [], "halted": None,
            "generations": {},
        }

    # -- bookkeeping -----------------------------------------------------

    def raise_if_halted(self):
        if self.status["state"] == "halted":
            raise DeployHalted(str(self.status["halted"]))

    def _enter(self, phase, rid):
        self._phase = phase
        self._phase_t0 = self._clock()
        self.status["phase"] = phase
        self.status["replica"] = rid
        self._refuse_t0 = None

    def _generations(self, router):
        return {
            r.replica_id: int(r.stats.get("weight_generation", 0))
            for r in router.replicas
        }

    def _halt(self, router, rid, kind, message):
        self.status.update({
            "state": "halted", "halted": {
                "replica": rid, "kind": str(kind),
                "message": str(message),
            },
            "generations": self._generations(router),
        })
        self.finished = True
        # the halted replica returns to routing on whatever generation
        # it actually serves (old if the swap never landed; the engine
        # already rolled itself back otherwise)
        if rid is not None and router.replicas[rid].alive:
            router.replica_set.readmit(rid)
        if self.step_dir is not None and kind != "timeout":
            from tensorflowonspark_tpu import hot_swap

            hot_swap.quarantine(self.step_dir, kind, message)
        fids, traces = ([], []) if rid is None else (
            router.outstanding_of(rid)
        )
        self._tracer.mark(
            "deploy_halted", trace="deploy", severity="page",
            replica=rid, kind=str(kind),
            canary=(rid == self._order0),
            replicas_done=len(self.status["replicas_done"]),
            # the requests in flight on the halted replica (ISSUE 14
            # satellite: fleet actions name the requests they touch)
            request_ids=fids, trace_ids=traces,
        )
        logger.warning(
            "rolling deploy HALTED at replica %s (%s): %s — %d of "
            "%d replicas deployed", rid, kind, message,
            len(self.status["replicas_done"]), len(self._order_list),
        )
        return True

    def _done(self, router):
        self.status.update({
            "state": "done", "replica": None, "phase": "done",
            "generations": self._generations(router),
        })
        self.finished = True
        self._tracer.mark(
            "deploy_done", trace="deploy",
            replicas=len(self.status["replicas_done"]),
        )
        logger.info(
            "rolling deploy done: %d replica(s) on the new "
            "generation", len(self.status["replicas_done"]),
        )
        return True

    # -- the machine -----------------------------------------------------

    def step_machine(self, router):
        """Advance one step; returns True when the deploy finished
        (done or halted).  Called from the router's scheduling loop —
        never blocks, never raises (faults become halts)."""
        try:
            return self._step(router)
        except Exception as e:  # noqa: BLE001 - faults halt, not crash
            rid = self.status.get("replica")
            logger.warning("rolling deploy step failed", exc_info=True)
            return self._halt(router, rid, "deploy_error", e)

    def _step(self, router):
        if self.finished:
            return True
        if self._phase == "start":
            self._order_list = (
                self.order if self.order is not None
                else [r.replica_id for r in router.replicas if r.alive]
            )
            if not self._order_list:
                return self._halt(
                    router, None, "no_replicas",
                    "no live replica to deploy to",
                )
            self._order0 = self._order_list[0]
            self._tracer.mark(
                "deploy_start", trace="deploy",
                replicas=len(self._order_list),
                canary=self._order0, gate=self.gate,
                step=self.status["target_step"],
            )
            self._mark_drain(router, self._order_list[0])
            self._enter("drain", self._order_list[0])
            router.replica_set.drain(self._order_list[0])
            return False
        rid = self._order_list[self._i]
        replica = router.replicas[rid]
        if not replica.alive:
            # died mid-deploy: skip it (the router already
            # re-dispatched its work); the rollout continues
            return self._advance(router, rid, swapped=False)
        if self._clock() - self._phase_t0 > self.phase_timeout:
            return self._halt(
                router, rid, "timeout",
                "phase {0!r} exceeded {1:.0f}s".format(
                    self._phase, self.phase_timeout
                ),
            )
        if self._phase == "drain":
            if router._assigned_count(rid):
                return False  # in-flight work still completing
            return self._issue_swap(router, rid, replica)
        if self._phase == "await_apply":
            return self._check_apply(router, rid, replica)
        if self._phase == "gate":
            return self._check_gate(router, rid, replica)
        raise RuntimeError("unknown deploy phase %r" % (self._phase,))

    def _issue_swap(self, router, rid, replica):
        eng = replica.engine
        # baseline BEFORE the request goes in: an idle replica's
        # lifecycle pass can apply the swap within one heartbeat —
        # snapshotting after would fold the applied swap into the
        # baseline and misread it as an install refusal
        self._base = {
            "swaps": eng.stats["swaps"],
            "rollbacks": eng.stats["rollbacks"],
            "swap_commits": eng.stats["swap_commits"],
        }
        if self.step_dir is not None:
            from tensorflowonspark_tpu import hot_swap

            expect = None
            spec = getattr(eng.decoder, "param_spec", None)
            if callable(spec):
                expect = spec()
            step = self.step
            if step is None:
                from tensorflowonspark_tpu import checkpoint as ckpt

                manifest = ckpt.read_manifest(self.step_dir) or {}
                step = manifest.get(
                    "step", eng.stats["weight_generation"] + 1
                )
            try:
                w = hot_swap.validate_checkpoint(
                    self.step_dir, step, expect=expect
                )
            except hot_swap.CheckpointRejected as e:
                return self._halt(router, rid, e.kind, e)
            eng.request_swap(
                w.params, step=w.step, draft_params=w.draft_params
            )
        else:
            eng.request_swap(self.params, step=self.step)
        self._enter("await_apply", rid)
        return False

    def _check_apply(self, router, rid, replica):
        eng = replica.engine
        if eng.stats["rollbacks"] > self._base["rollbacks"]:
            return self._halt(
                router, rid, "canary_failed",
                "post-install canary rolled replica {0} back".format(
                    rid
                ),
            )
        if eng.stats["swaps"] > self._base["swaps"]:
            # installed: back into routing; prove health under traffic
            router.replica_set.readmit(rid)
            self._tracer.mark(
                "deploy_replica_swapped", trace="deploy", replica=rid,
                generation=eng.stats["weight_generation"],
            )
            if self.gate == "applied":
                return self._advance(router, rid, swapped=True)
            self._enter("gate", rid)
            return False
        if eng._swap_request is None:
            # consumed without a swap/rollback transition: the engine
            # refused the install (shape quarantine).  Grace-period
            # guarded — the scheduler may be mid-transaction.
            now = self._clock()
            if self._refuse_t0 is None:
                self._refuse_t0 = now
            elif now - self._refuse_t0 > self.refuse_grace:
                return self._halt(
                    router, rid, "install_refused",
                    "replica {0} refused the install (no swap "
                    "transition within {1:.1f}s)".format(
                        rid, self.refuse_grace
                    ),
                )
        else:
            self._refuse_t0 = None
        return False

    def _check_gate(self, router, rid, replica):
        eng = replica.engine
        if eng.stats["rollbacks"] > self._base["rollbacks"]:
            return self._halt(
                router, rid, "probation_rollback",
                "replica {0} rolled back inside its probation "
                "window".format(rid),
            )
        if eng.stats["swap_commits"] > self._base["swap_commits"]:
            return self._advance(router, rid, swapped=True)
        return False

    def _advance(self, router, rid, swapped):
        if swapped:
            self.status["replicas_done"].append(rid)
            self._tracer.mark(
                "deploy_replica_done", trace="deploy", replica=rid,
            )
        self.status["generations"] = self._generations(router)
        self._i += 1
        if self._i >= len(self._order_list):
            return self._done(router)
        nxt = self._order_list[self._i]
        self._mark_drain(router, nxt)
        self._enter("drain", nxt)
        router.replica_set.drain(nxt)
        return False

    def _mark_drain(self, router, rid):
        """Journal the drain with the requests it strands in flight
        (ISSUE 14 satellite: deploy events name the requests/traces
        they touch, so forensics timelines connect fleet actions to
        request stories)."""
        fids, traces = router.outstanding_of(rid)
        self._tracer.mark(
            "deploy_drain", trace="deploy", replica=rid,
            request_ids=fids, trace_ids=traces,
        )
