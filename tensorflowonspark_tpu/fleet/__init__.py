"""Fleet serving plane: N engine replicas behind one router.

The serving stack below this package is ONE very good engine
(:class:`~tensorflowonspark_tpu.serving_engine.ServingEngine`:
admission control, deadlines, watchdog recovery, prefix cache, live
hot-swap).  This package is the plane ONE LEVEL ABOVE it — in the
spirit of TF-Replicator's replica-set abstraction, scale comes from
the orchestration layer, not new per-engine code paths:

- :mod:`~tensorflowonspark_tpu.fleet.replica` — :class:`ReplicaSet`
  owns N engine replicas (in-process ``ServingEngine`` workers on CPU
  for tests; the same duck-typed seam fits executor-resident engines
  attached over the reservation wire) with per-replica lifecycle
  (spawn, drain, evict, re-admit) and a cheap ``load()`` snapshot;
- :mod:`~tensorflowonspark_tpu.fleet.router` — :class:`FleetRouter`:
  a bounded fleet admission queue, pluggable dispatch policies
  (least-loaded, prefix-affinity over block-granular prompt
  fingerprints, weighted round-robin), fleet-level shed/degrade that
  spills to a sibling replica before any single engine sheds, and
  committed-token-safe re-dispatch on replica death;
- :mod:`~tensorflowonspark_tpu.fleet.deploy` — :class:`RollingDeploy`:
  zero-downtime rolling weight deploys, one replica at a time behind
  router drain, gated on post-swap health, with a fleet-wide halt
  when the canary replica burns.

See docs/serving.md "Fleet routing & rolling deploys".
"""

from tensorflowonspark_tpu.fleet.deploy import (  # noqa: F401
    DeployHalted,
    RollingDeploy,
)
from tensorflowonspark_tpu.fleet.replica import (  # noqa: F401
    Replica,
    ReplicaKilled,
    ReplicaSet,
)
from tensorflowonspark_tpu.fleet.router import (  # noqa: F401
    DISPATCH_POLICIES,
    FleetRouter,
    predict_rows_fleet,
)
