"""Fleet router: bounded admission + pluggable dispatch over replicas.

One level above the engine's admission plane (PR 4), the router is the
fleet's: a bounded **fleet admission queue** with the same
``block | reject | degrade`` shedding vocabulary, except that pressure
first **spills to a sibling replica** — a single engine only ever sees
traffic the router already sized to its slots + queue bound, so no
engine-level shed fires while a sibling has room.

Dispatch policies (:data:`DISPATCH_POLICIES`, pluggable by callable):

- ``least_loaded`` — fewest router-assigned requests per weight, fed
  by the replicas' lock-light ``load()`` snapshots (the same fields
  ``/status`` exposes per engine);
- ``prefix_affinity`` — block-granular prompt fingerprints
  (:func:`~tensorflowonspark_tpu.prefix_cache.fingerprint` — the
  radix cache's own key math) routed by rendezvous hashing, so a
  shared prefix consistently lands on the replica whose radix cache
  already holds it; under imbalance (target backlog more than
  ``imbalance`` ahead of the least loaded) it falls back to
  least-loaded (an ``affinity_spill``);
- ``weighted_rr`` — deterministic smooth weighted round-robin;
- ``random`` — seeded uniform pick (the bench's affinity baseline).

**Replica death** re-dispatches committed-token-safe: the dead
replica's wreckage (finished-but-unemitted rows, per-request committed
tokens — see ``Replica._wreckage``) re-enters the fleet queue with the
dead replica in each request's excluded set; greedy continuations from
``prompt + committed`` are token-identical to an undisturbed run (the
same invariant the engine watchdog's recovery pins down).  A **slow**
replica is routed around (latency-EWMA vs the fleet median), kept on
probe traffic, and re-admitted after N clean probe rounds.  Every
action is a typed journal event (``replica_dead`` / ``fleet_redispatch``
/ ``replica_evicted`` / ``replica_readmitted`` / ``fleet_shed`` —
tracer marks auto-bridge into the PR 11 journal).

Rolling deploys (fleet/deploy.py) run as a state machine stepped by
the router's scheduling loop — drain one replica, hot-swap it, gate on
its post-swap health, re-admit, next.

See docs/serving.md "Fleet routing & rolling deploys".
"""

import collections
import itertools
import logging
import queue as queue_mod
import threading
import time

import numpy as np

from tensorflowonspark_tpu import serving_engine, telemetry
from tensorflowonspark_tpu.fleet.replica import ReplicaSet
from tensorflowonspark_tpu.prefix_cache import fingerprint
from tensorflowonspark_tpu.telemetry import ledger as ledger_mod

logger = logging.getLogger(__name__)

#: internal row column carrying each request's (possibly resumed)
#: token budget into the replica engines — added to the engine-level
#: input mapping unless the caller already mapped a budget column
FLEET_BUDGET_COL = "__fleet_max_new__"

#: internal row column carrying each request's fleet-minted TRACE id
#: into the replica engines (mapped to ``serving_engine.TRACE_INPUT``
#: unless the caller already mapped a trace column): the engine's
#: ``admission → queue_wait → prefill → decode_chunk×N → emit`` span
#: chain then joins the router's trace, and a re-dispatch after
#: ``kill_replica`` CONTINUES the same trace on the surviving replica
#: — ``telemetry.merge_traces`` renders one connected, causally
#: ordered story per request across replicas/processes (ISSUE 14).
FLEET_TRACE_COL = "__fleet_trace__"

#: per-process router sequence: trace ids are ``flt<router>-req<fid>``
#: so rows in the process-wide usage ledger never collide across
#: routers/jobs
_ROUTER_SEQ = itertools.count(1)

#: error-record kinds that re-raise under ``on_error="raise"`` (the
#: replica engines always run in record mode; the router restores
#: fail-fast semantics for genuine request faults).  Policy records
#: (shed / deadline / drained / replica_lost) never raise.
_RAISE_KINDS = frozenset({
    "missing_input", "bad_dtype", "bad_shape", "empty_prompt",
    "too_long", "bad_budget", "bad_deadline", "admit", "predict",
})


def _mix(fp, rid):
    """Deterministic 64-bit rendezvous score for (fingerprint,
    replica) — stable across processes (no salted ``hash``)."""
    x = (int(fp) ^ (int(rid) * 0x9E3779B97F4A7C15)) & 0xFFFFFFFFFFFFFFFF
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & 0xFFFFFFFFFFFFFFFF
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


def _median(vals):
    vals = sorted(vals)
    n = len(vals)
    if not n:
        return None
    return vals[n // 2] if n % 2 else 0.5 * (
        vals[n // 2 - 1] + vals[n // 2]
    )


# ----------------------------------------------------------------------
# dispatch policies
# ----------------------------------------------------------------------


def _least_loaded(router, req, candidates):
    return min(
        candidates,
        key=lambda r: (
            router._assigned_count(r.replica_id)
            / router._weight(r.replica_id),
            r.replica_id,
        ),
    )


def _weighted_rr(router, req, candidates):
    """Smooth weighted round-robin (the nginx algorithm):
    deterministic, proportional to weights, no bursts."""
    cw = router._rr_current
    total = 0.0
    for r in candidates:
        w = router._weight(r.replica_id)
        cw[r.replica_id] = cw.get(r.replica_id, 0.0) + w
        total += w
    best = max(candidates, key=lambda r: (cw[r.replica_id], -r.replica_id))
    cw[best.replica_id] -= total
    return best


def _random(router, req, candidates):
    return candidates[router._rng.randint(len(candidates))]


def _prefix_affinity(router, req, candidates):
    """Rendezvous-hash the prompt fingerprint over every ROUTABLE
    replica (stable while membership is stable — one replica's death
    only remaps its own keys), then dispatch there unless its backlog
    runs more than ``imbalance`` ahead of the least-loaded candidate
    (or it has no room / is excluded) — then spill to least-loaded."""
    fp = req["fingerprint"]
    if fp is None:
        return _least_loaded(router, req, candidates)
    routable = [
        r for r in router.replicas
        if r.alive and r.state == "live"
    ] or candidates
    target = max(
        routable, key=lambda r: _mix(fp, r.replica_id)
    )
    floor = min(
        router._assigned_count(r.replica_id) for r in candidates
    )
    if (target in candidates
            and router._assigned_count(target.replica_id) - floor
            <= router.imbalance):
        router.stats["affinity_hits"] += 1
        router._m["affinity_hits"].inc()
        return target
    router.stats["affinity_spills"] += 1
    with router._pressure_lock:
        router._spill_times.append(router._clock())
    return _least_loaded(router, req, candidates)


#: name -> policy callable ``(router, req, candidates) -> Replica``;
#: FleetRouter also accepts a bare callable of the same shape
DISPATCH_POLICIES = {
    "least_loaded": _least_loaded,
    "prefix_affinity": _prefix_affinity,
    "weighted_rr": _weighted_rr,
    "random": _random,
}


class FleetRouter(object):
    """Route a request stream over a :class:`ReplicaSet` (see module
    docstring).  :meth:`serve` mirrors the engine contract: dict rows
    in, output rows/typed records out, in fleet input order.

    Args:
      predict: generation predictor (``serving_builder`` — replicas
        beyond the first come from ``predict.make_replica()``); may be
        None when ``replica_set`` is given.
      input_mapping: ``{column: input_name}`` — the USER mapping;
        the router adds its internal budget column for the replica
        engines unless a budget column is already mapped.
      output_mapping: optional ``{output_name: column}`` rename,
        applied router-side (replica engines emit raw outputs).
      replicas: replica count (or pass a prebuilt ``replica_set``
        whose engines were built with :meth:`engine_input_mapping`).
      num_slots / chunk / replica_queue_depth / engine_opts / devices
        / predict_factory / poll_sec: forwarded to
        :class:`ReplicaSet` / :class:`Replica`.
      policy: FLEET admission policy — ``block`` (backpressure the
        source), ``reject`` (typed shed records past the fleet queue
        bound), ``degrade`` (shrink token budgets against the fleet
        backlog) — pressure spills across replicas first; a single
        engine never sheds while a sibling has room.
      dispatch: dispatch-policy name (:data:`DISPATCH_POLICIES`) or a
        callable ``(router, req, candidates) -> Replica``.
      queue_depth: fleet admission queue bound (default: the summed
        replica capacity — so total in-system tops out at ~2x what
        the replicas can hold, the engine's own 2x-slots spirit).
      degrade_floor: minimum per-request budget under ``degrade``.
      on_error: ``"record"`` (typed records, the fleet default) or
        ``"raise"`` (request faults re-raise naming the fleet index).
      replica_weights: optional {replica_id: weight} for
        ``weighted_rr`` / ``least_loaded``.
      imbalance: affinity fallback threshold (default
        ``max(2, num_slots)`` assigned requests ahead of the least
        loaded).
      affinity_width: fingerprint width in tokens (default the
        canonical :data:`~tensorflowonspark_tpu.prefix_cache.
        FINGERPRINT_TOKENS`).
      slow_factor / min_slow_sec / suspect_rounds / probe_every /
        readmit_rounds: straggler policy — a live replica whose
        completion-latency EWMA exceeds ``max(min_slow_sec,
        slow_factor * fleet median)`` for ``suspect_rounds``
        consecutive completions is routed around; it then receives
        one probe request every ``probe_every`` dispatches and
        re-admits after ``readmit_rounds`` consecutive clean probes.
      stats: optional dict filled with fleet counters.
      clock / seed / poll_sec: determinism knobs.
    """

    def __init__(self, predict, input_mapping, output_mapping=None, *,
                 replicas=2, num_slots=4, chunk=None,
                 replica_queue_depth=None, engine_opts=None,
                 devices=None, predict_factory=None, replica_set=None,
                 policy="block", dispatch="least_loaded",
                 queue_depth=None, degrade_floor=1, on_error="record",
                 replica_weights=None, imbalance=None,
                 affinity_width=None, slow_factor=4.0,
                 min_slow_sec=0.05, suspect_rounds=2, probe_every=8,
                 readmit_rounds=3, readmit_gate=None, stats=None,
                 clock=None, seed=0, poll_sec=0.05,
                 pressure_window=30.0):
        if policy not in serving_engine.POLICIES:
            raise ValueError(
                "fleet policy must be one of {0}, got {1!r}".format(
                    serving_engine.POLICIES, policy
                )
            )
        if on_error not in serving_engine.ON_ERROR:
            raise ValueError(
                "on_error must be one of {0}, got {1!r}".format(
                    serving_engine.ON_ERROR, on_error
                )
            )
        if callable(dispatch):
            self._dispatch_policy = dispatch
            self.dispatch_name = getattr(
                dispatch, "__name__", "custom"
            )
        else:
            if dispatch not in DISPATCH_POLICIES:
                raise ValueError(
                    "dispatch must be a callable or one of {0}, got "
                    "{1!r}".format(
                        sorted(DISPATCH_POLICIES), dispatch
                    )
                )
            self._dispatch_policy = DISPATCH_POLICIES[dispatch]
            self.dispatch_name = dispatch
        self.user_mapping = dict(input_mapping)
        self.output_mapping = output_mapping
        self.user_budget_col = next(
            (c for c in input_mapping
             if input_mapping[c] == serving_engine.BUDGET_INPUT), None
        )
        self.budget_col = self.user_budget_col or FLEET_BUDGET_COL
        self.user_trace_col = next(
            (c for c in input_mapping
             if input_mapping[c] == serving_engine.TRACE_INPUT), None
        )
        self.trace_col = self.user_trace_col or FLEET_TRACE_COL
        self.tenant_col = next(
            (c for c in input_mapping
             if input_mapping[c] == serving_engine.TENANT_INPUT), None
        )
        self._trace_prefix = "flt%d" % next(_ROUTER_SEQ)
        self._ledger = ledger_mod.get_ledger()
        self.policy = policy
        self.on_error = on_error
        self.degrade_floor = max(1, int(degrade_floor))
        if replica_set is None:
            replica_set = ReplicaSet(
                predict, replicas,
                self.engine_input_mapping(input_mapping),
                num_slots=num_slots, chunk=chunk,
                queue_depth=replica_queue_depth,
                engine_opts=engine_opts, devices=devices,
                predict_factory=predict_factory,
            )
        self.replica_set = replica_set.start()
        self.replicas = replica_set.replicas
        self._completions = replica_set.completions
        eng0 = self.replicas[0].engine
        self.prompt_col = eng0.prompt_col
        self.max_new = int(eng0.max_new)
        self._eos_id = eng0.eos_id
        # the user-facing generated_len rule (engine _emit_len, minus
        # the router's internal budget column)
        self._user_emit_len = (
            self._eos_id is not None
            or self.user_budget_col is not None
            or policy == "degrade"
        )
        self.queue_depth = (
            max(1, int(queue_depth)) if queue_depth is not None
            else sum(r.capacity() for r in self.replicas)
        )
        # affinity stickiness: fall back to least-loaded only when the
        # target runs a full replica-capacity ahead of the least
        # loaded (the per-replica ROOM bound already backstops
        # overload — a tighter default would degrade affinity to
        # least-loaded under every burst and forfeit the cache hits)
        self.imbalance = (
            int(eng0.num_slots) + int(eng0.queue_depth)
            if imbalance is None else max(0, int(imbalance))
        )
        self.affinity_width = affinity_width
        self.slow_factor = float(slow_factor)
        self.min_slow_sec = float(min_slow_sec)
        self.suspect_rounds = max(1, int(suspect_rounds))
        self.probe_every = max(1, int(probe_every))
        self.readmit_rounds = max(1, int(readmit_rounds))
        #: optional quality gate on re-admission (a
        #: :class:`~tensorflowonspark_tpu.telemetry.health.
        #: CleanRoundsSensor`): a replica with enough clean probe
        #: rounds still waits until the HEALTH PLANE has seen N
        #: consecutive clean rounds fleet-wide — quality-gated, not
        #: timer-gated (ROADMAP 3 residual)
        self.readmit_gate = readmit_gate
        self._gate_blocked = {}   # rid -> True while gate holds it
        self._weights = dict(replica_weights or {})
        self._rr_current = {}
        self._rng = np.random.RandomState(int(seed))
        self._clock = clock if clock is not None else time.monotonic
        self._poll = float(poll_sec)
        # scheduling state
        self._queue = collections.deque()   # fids awaiting dispatch
        self._reqs = {}                     # fid -> request record
        self._assigned = collections.defaultdict(set)  # rid -> fids
        self._finished = {}
        self._emit_next = 0
        self._n_in = 0
        self._exhausted = False
        self._dispatch_count = 0
        self._lat_ewma = {}
        self._suspect = collections.defaultdict(int)
        self._clean = collections.defaultdict(int)
        self._deploy = None
        self.deploy_history = []
        self.stats = stats if stats is not None else {}
        self.stats.update({
            "latency_sec": {}, "done_at": {}, "dispatched": 0,
            "completed": 0, "errors": 0, "shed": 0, "expired": 0,
            "degraded": 0, "drained": 0, "redispatched": 0,
            "replica_deaths": 0, "quarantined": 0, "affinity_hits": 0,
            "affinity_spills": 0, "evicted": 0, "readmitted": 0,
            "scaled_up": 0, "scaled_down": 0,
            "replicas": len(self.replicas),
            "dispatch_policy": self.dispatch_name,
            "fleet_policy": policy,
            # fleet request id -> minted trace id (ISSUE 14): how a
            # caller (or test) pulls the merged trace of a specific
            # request after the run
            "trace_ids": {},
        })
        self._tracer = telemetry.get_tracer()
        reg = telemetry.get_registry()
        self._m = {
            name: reg.counter("fleet." + name)
            for name in (
                "dispatched", "redispatched", "completed", "shed",
                "affinity_hits", "replica_deaths", "evictions",
                "readmissions",
            )
        }
        self._m_live = reg.gauge("fleet.live_replicas")
        self._m_live.set(len(self.replicas))
        self._m_spawned = reg.counter("fleet.replicas_spawned")
        self._m_retired = reg.counter("fleet.replicas_retired")
        self._t0 = self._clock()
        # windowed admission-pressure statistic (ISSUE 16 satellite):
        # occupancy samples + shed/spill event times over the last
        # ``pressure_window`` seconds, so autoscaling decisions and
        # operators (/status) read the same number
        self.pressure_window = max(1.0, float(pressure_window))
        self._occupancy_samples = collections.deque()  # (t, occupancy)
        self._shed_times = collections.deque()
        self._spill_times = collections.deque()
        # pressure() is read off-thread (remediation sensors, /status
        # scrapes) while the serve pass appends — guard the deques
        self._pressure_lock = threading.Lock()
        # /status provider (weakref-bound like the engine's: a
        # finished router must never pin its replicas' decoders)
        import weakref

        from tensorflowonspark_tpu.telemetry import health as _health

        _ref = weakref.ref(self)

        def _fleet_status():
            rt = _ref()
            return (
                {"finished": True} if rt is None
                else rt.health_status()
            )

        _health.register_status_provider("fleet", _fleet_status)

    # -- small helpers ---------------------------------------------------

    def _weight(self, rid):
        return float(self._weights.get(rid, 1.0)) or 1.0

    def _assigned_count(self, rid):
        return len(self._assigned[rid])

    def outstanding_of(self, rid):
        """``(request_ids, trace_ids)`` currently assigned to replica
        ``rid`` — what fleet-action journal events attach so the
        forensics timeline connects the action to the requests it
        touched (ISSUE 14 satellite)."""
        fids = sorted(self._assigned[rid])
        return fids, [self.stats["trace_ids"].get(f) for f in fids]

    def _note_pressure(self):
        """One admission-pressure sample per serve pass (bounded by
        the window — trimmed on both sample and read)."""
        now = self._clock()
        with self._pressure_lock:
            self._occupancy_samples.append(
                (now, len(self._queue) / float(self.queue_depth))
            )
            horizon = now - self.pressure_window
            for dq in (self._occupancy_samples, self._shed_times,
                       self._spill_times):
                while dq and (dq[0][0]
                              if dq is self._occupancy_samples
                              else dq[0]) < horizon:
                    dq.popleft()

    def pressure(self):
        """The windowed admission-pressure statistic (ISSUE 16
        satellite): queue occupancy (now / mean / peak over the last
        ``pressure_window`` seconds) plus shed and affinity-spill
        rates over the same window.  Rides ``/status`` (fleet
        provider) so the autoscaling policy and an operator read the
        SAME number; also the sensor behind the remediation engine's
        spawn/retire decisions."""
        now = self._clock()
        horizon = now - self.pressure_window
        with self._pressure_lock:
            occ = [v for (t, v) in self._occupancy_samples
                   if t >= horizon]
            sheds = sum(1 for t in self._shed_times if t >= horizon)
            spills = sum(1 for t in self._spill_times if t >= horizon)
        occ_now = len(self._queue) / float(self.queue_depth)
        return {
            "window_sec": self.pressure_window,
            "occupancy": round(occ_now, 4),
            "occupancy_mean": round(
                sum(occ) / len(occ), 4
            ) if occ else round(occ_now, 4),
            "occupancy_peak": round(max(occ), 4) if occ else round(
                occ_now, 4
            ),
            "queued": len(self._queue),
            "queue_depth": self.queue_depth,
            "shed_per_sec": round(sheds / self.pressure_window, 4),
            "spill_per_sec": round(spills / self.pressure_window, 4),
            "free_slots": sum(
                max(0, self._room(r)) for r in self.replicas
                if r.alive and r.state == "live"
            ),
        }

    def health_status(self):
        """Fleet summary for ``/status``: routing policy, per-replica
        load snapshots, and the deploy state."""
        return {
            "pressure": self.pressure(),
            "replicas": len(self.replicas),
            "live": sum(
                1 for r in self.replicas
                if r.alive and r.state == "live"
            ),
            "dispatch": self.dispatch_name,
            "policy": self.policy,
            "queued": len(self._queue),
            "queue_depth": self.queue_depth,
            "outstanding": sum(
                len(v) for v in self._assigned.values()
            ),
            "completed": self.stats["completed"],
            "shed": self.stats["shed"],
            "replica_deaths": self.stats["replica_deaths"],
            "deploy": (
                self._deploy.status if self._deploy is not None
                else (self.deploy_history[-1]
                      if self.deploy_history else None)
            ),
            "loads": self.replica_set.load(),
            # per-replica cost rows (ISSUE 14): what each replica
            # burned and produced so far — decode chip-seconds,
            # tokens emitted, prefix tokens saved
            "costs": {
                r.replica_id: {
                    "state": r.state,
                    "chip_sec": round(float(
                        r.stats.get("decode_wall_sec", 0.0)
                    ), 6),
                    "tokens_out": int(r.stats.get("tokens_out", 0)),
                    "completed": int(r.stats.get("completed", 0)),
                    "prefix_tokens_saved": int(
                        r.stats.get("prefix_tokens_saved", 0)
                    ),
                }
                for r in self.replicas
            },
        }

    def load(self):
        """Fleet-level load: summed free slots / queue depths over
        live replicas plus the router's own backlog."""
        live = [r.load() for r in self.replicas if r.alive]
        return {
            "replicas": len(self.replicas),
            "live": len(live),
            "free_slots": sum(s["free_slots"] for s in live),
            "in_flight": sum(s["in_flight"] for s in live),
            "queued": (
                sum(s["queued"] for s in live) + len(self._queue)
            ),
            "queue_depth": self.queue_depth,
        }

    def engine_input_mapping(self, input_mapping=None):
        """The ENGINE-level mapping the replicas must be built with:
        the user mapping plus the router's internal budget column
        (resumed re-dispatches carry reduced budgets through it) and
        its internal trace column (the fleet-minted request trace id
        every engine span then rides — ISSUE 14)."""
        m = dict(input_mapping or self.user_mapping)
        if not any(v == serving_engine.BUDGET_INPUT
                   for v in m.values()):
            m[FLEET_BUDGET_COL] = serving_engine.BUDGET_INPUT
        if not any(v == serving_engine.TRACE_INPUT
                   for v in m.values()):
            m[FLEET_TRACE_COL] = serving_engine.TRACE_INPUT
        return m

    # -- admission -------------------------------------------------------

    def _shed(self, fid, rid, why):
        self.stats["shed"] += 1
        self._m["shed"].inc()
        with self._pressure_lock:
            self._shed_times.append(self._clock())
        # the mark rides the REQUEST's trace and names it in attrs
        # (ISSUE 14 satellite: fleet actions connect to the requests
        # they touched, not just a generic trace="fleet")
        self._tracer.mark(
            "fleet_shed", trace=rid, severity="warn",
            request_index=fid, trace_id=rid,
            queue_depth=self.queue_depth,
        )
        self._ledger.close(rid, tokens_out=0)
        self._finished[fid] = serving_engine.error_record(
            "shed", fid, why
        )

    def _rid_of(self, fid, row):
        """The request's fleet trace id: minted here unless the caller
        mapped its own :data:`~tensorflowonspark_tpu.serving_engine.
        TRACE_INPUT` column with a usable value."""
        if self.user_trace_col is not None and isinstance(row, dict):
            v = row.get(self.user_trace_col)
            if isinstance(v, str) and v:
                return v
        return "%s-req%d" % (self._trace_prefix, fid)

    def _admit(self, row):
        fid = self._n_in
        self._n_in += 1
        rid = self._rid_of(fid, row)
        self.stats["trace_ids"][fid] = rid
        if self.policy == "reject":
            # spill-before-shed: free replica room is admission
            # capacity too (the refill runs before dispatch, so
            # counting only queue_depth would shed requests a sibling
            # replica was about to take — the engine _refill's rule,
            # fleet-wide)
            cap = self.queue_depth + sum(
                max(0, self._room(r)) for r in self.replicas
                if r.alive and r.state == "live"
            )
            if len(self._queue) >= cap:
                self._shed(
                    fid, rid,
                    "request {0} shed: fleet admission queue full "
                    "({1} waiting, depth {2}, policy 'reject')".format(
                        fid, len(self._queue), self.queue_depth
                    ),
                )
                return
        budget = self.max_new
        if self.user_budget_col is not None:
            try:
                budget = max(
                    1, min(int(row[self.user_budget_col]), self.max_new)
                )
            except (KeyError, TypeError, ValueError):
                pass  # the engine's validation names the bad column
        if self.policy == "degrade":
            backlog = len(self._queue)
            if backlog > self.queue_depth:
                shrunk = max(
                    self.degrade_floor,
                    (budget * self.queue_depth) // backlog,
                )
                if shrunk < budget:
                    budget = shrunk
                    self.stats["degraded"] += 1
        prompt = None
        fp = None
        try:
            prompt = np.asarray(row[self.prompt_col], np.int32).ravel()
            fp = fingerprint(
                prompt, self.affinity_width
            ) if self.affinity_width else fingerprint(prompt)
        except Exception:  # noqa: BLE001 - validation is the engine's
            pass
        tenant = None
        if self.tenant_col is not None:
            v = row.get(self.tenant_col) if isinstance(row, dict) else None
            if isinstance(v, str) and v:
                tenant = v  # junk values: the engine names the error
        self._reqs[fid] = {
            "row": row, "prompt": prompt, "budget": budget,
            "committed": [], "excluded": set(), "replica": None,
            "fingerprint": fp, "submit": self._clock(),
            "sent_at": None, "redispatches": 0,
            "rid": rid, serving_engine.TENANT_INPUT: tenant,
        }
        # open the cost row at FLEET admission with the user-facing
        # prompt size: a later re-dispatch re-admits prompt+committed
        # engine-side, and the ledger's set-if-unset keeps this value
        self._ledger.open(
            rid, tenant=tenant,
            tokens_in=int(prompt.shape[0]) if prompt is not None else None,
        )
        self._tracer.mark(
            "fleet_admission", trace=rid, request_index=fid,
            trace_id=rid,
        )
        self._queue.append(fid)

    def _room(self, replica):
        return replica.capacity() - self._assigned_count(
            replica.replica_id
        )

    def _pull(self, it):
        """Source pull per fleet admission policy (the engine's
        vocabulary, one level up — see class docstring)."""
        if self._exhausted:
            return
        if self.policy == "block":
            # backpressure: pull no faster than the fleet can place —
            # at most the summed free room of routable replicas.  Per
            # PASS the pull is bounded by the live replica count: a
            # slow (paced) source would otherwise hold the control
            # loop inside next(it) accumulating an artificial burst,
            # stalling completions and skewing dispatch
            live = [
                r for r in self.replicas
                if r.alive and r.state == "live"
            ]
            room = sum(max(0, self._room(r)) for r in live)
            budget = max(1, len(live))
            while budget and len(self._queue) < room:
                try:
                    row = next(it)
                except StopIteration:
                    self._exhausted = True
                    return
                self._admit(row)
                budget -= 1
            return
        # reject/degrade: every available request has arrived — drain
        # the source; _admit sheds or shrinks against the backlog
        while True:
            try:
                row = next(it)
            except StopIteration:
                self._exhausted = True
                return
            self._admit(row)

    # -- dispatch --------------------------------------------------------

    def _candidates(self, req):
        live = [
            r for r in self.replicas
            if r.alive and r.state == "live"
            and r.replica_id not in req["excluded"]
        ]
        cands = [r for r in live if self._room(r) > 0]
        if cands or live:
            return cands
        # every live replica excluded or none left live: fall back to
        # routed-around replicas (serve slow rather than drop), then
        # clear the excluded set (a re-dispatch loop must not wedge on
        # a fully-excluded fleet)
        around = [
            r for r in self.replicas
            if r.alive and r.state == "routed_around"
            and r.replica_id not in req["excluded"]
            and self._room(r) > 0
        ]
        if around:
            return around
        retry = [
            r for r in self.replicas
            if r.alive and r.state in ("live", "routed_around")
            and self._room(r) > 0
        ]
        if retry:
            req["excluded"].clear()
        return retry

    def _probe_target(self, req):
        """Every ``probe_every``-th dispatch goes to a routed-around
        replica (lowest id with room) so its recovery is observable —
        the re-admission signal."""
        if self._dispatch_count % self.probe_every:
            return None
        for r in self.replicas:
            if (r.alive and r.state == "routed_around"
                    and r.replica_id not in req["excluded"]
                    and self._room(r) > 0):
                return r
        return None

    def _dispatch(self):
        while self._queue:
            fid = self._queue[0]
            req = self._reqs[fid]
            if req["committed"] and len(req["committed"]) >= req["budget"]:
                # the dead replica already committed the full budget —
                # nothing left to decode
                self._queue.popleft()
                self._finalize_resumed_complete(fid, req)
                continue
            if not any(
                r.alive and r.state in ("live", "routed_around")
                for r in self.replicas
            ):
                self._queue.popleft()
                self.stats["errors"] += 1
                self._ledger.close(
                    req["rid"], tokens_out=len(req["committed"])
                )
                self._finished[fid] = serving_engine.error_record(
                    "replica_lost", fid,
                    "request {0}: no live replica remains in the "
                    "fleet".format(fid),
                    tokens_done=len(req["committed"]),
                    partial=req["committed"],
                )
                self._reqs.pop(fid, None)
                continue
            target = self._probe_target(req)
            if target is None:
                cands = self._candidates(req)
                if not cands:
                    return  # all routable replicas full: wait
                target = self._dispatch_policy(self, req, cands)
            self._queue.popleft()
            self._send(fid, req, target)

    def _send(self, fid, req, replica):
        rid = replica.replica_id
        row = dict(req["row"])
        committed = req["committed"]
        if committed:
            row[self.prompt_col] = np.concatenate([
                req["prompt"],
                np.asarray(committed, np.int32),
            ])
        row[self.budget_col] = req["budget"] - len(committed)
        # the fleet trace id rides the row into the replica engine:
        # its whole span chain joins this request's trace, and a
        # re-dispatch CONTINUES the same trace on the next replica
        row[self.trace_col] = req["rid"]
        req["replica"] = rid
        req["sent_at"] = self._clock()
        self._assigned[rid].add(fid)
        self._dispatch_count += 1
        self.stats["dispatched"] += 1
        self._m["dispatched"].inc()
        if self._tracer.enabled:
            self._tracer.add(
                "fleet_dispatch", time.perf_counter(), 0.0,
                trace=req["rid"], replica=rid, request_index=fid,
                resumed_tokens=len(committed),
            )
        replica.dispatch(fid, row)

    # -- completion / death handling -------------------------------------

    def _collect(self):
        block = bool(self._queue or self._reqs)
        try:
            ev = self._completions.get(
                timeout=self._poll if block else 0.0
            )
        except queue_mod.Empty:
            return
        while True:
            self._handle(ev)
            try:
                ev = self._completions.get_nowait()
            except queue_mod.Empty:
                return

    def _handle(self, ev):
        kind = ev[0]
        if kind == "done":
            _, rid, fid, out = ev
            self._assigned[rid].discard(fid)
            req = self._reqs.pop(fid, None)
            if req is None:
                return
            self._observe_latency(rid, req)
            self._finalize(fid, req, out, rid)
        elif kind == "dead":
            _, rid, wreck = ev
            self._on_death(rid, wreck)
        elif kind == "quarantine":
            _, rid, wreck = ev
            self._on_quarantine(rid, wreck)
        # "stopped" needs no action (clean close)

    def _on_death(self, rid, wreck):
        replica = self.replicas[rid]
        n_redisp = len(wreck["committed"]) + len(wreck["queued"])
        self.stats["replica_deaths"] += 1
        self._m["replica_deaths"].inc()
        self._m_live.set(
            sum(1 for r in self.replicas if r.alive)
        )
        # the affected requests ride the mark's attrs (ISSUE 14
        # satellite): the journal/forensics timeline can connect this
        # fleet action to the requests it touched
        touched = sorted(
            set(wreck["committed"]) | set(wreck["queued"])
            | set(wreck["finished"])
        )
        self._tracer.mark(
            "replica_dead", trace="fleet", severity="page",
            replica=rid, error=str(replica.error),
            finished=len(wreck["finished"]), redispatching=n_redisp,
            request_ids=touched,
            trace_ids=[
                self.stats["trace_ids"].get(f) for f in touched
            ],
        )
        logger.warning(
            "fleet: replica %d died (%s); delivering %d finished "
            "row(s), re-dispatching %d request(s)", rid,
            replica.error, len(wreck["finished"]), n_redisp,
        )
        self._requeue_wreckage(rid, wreck)

    def _on_quarantine(self, rid, wreck):
        """A replica contained a DEVICE error: quarantine it via the
        evict verb (probe traffic only while it rebuilds and proves
        itself) and continue its in-flight requests
        committed-token-safe on a survivor — each request's merged
        trace carries straight on, the same re-dispatch invariant the
        death path pins."""
        replica = self.replicas[rid]
        self.replica_set.evict(rid)
        self._suspect[rid] = 0
        self._clean[rid] = 0
        self.stats["quarantined"] += 1
        self.stats["evicted"] += 1
        self._m["evictions"].inc()
        n_redisp = len(wreck["committed"]) + len(wreck["queued"])
        touched = sorted(
            set(wreck["committed"]) | set(wreck["queued"])
            | set(wreck["finished"])
        )
        self._tracer.mark(
            "replica_quarantined", trace="fleet", severity="page",
            replica=rid, error=str(replica.error),
            finished=len(wreck["finished"]), redispatching=n_redisp,
            request_ids=touched,
            trace_ids=[
                self.stats["trace_ids"].get(f) for f in touched
            ],
        )
        logger.warning(
            "fleet: replica %d quarantined on device error (%s); "
            "delivering %d finished row(s), re-dispatching %d "
            "request(s) on survivors", rid, replica.error,
            len(wreck["finished"]), n_redisp,
        )
        self._requeue_wreckage(rid, wreck)

    def _requeue_wreckage(self, rid, wreck):
        """Deliver a wrecked replica's finished rows and re-dispatch
        the rest (committed-token-safe) — shared by the death and
        quarantine paths."""
        # finished-but-unemitted rows are real results — deliver
        for fid, out in sorted(wreck["finished"].items()):
            self._assigned[rid].discard(fid)
            req = self._reqs.pop(fid, None)
            if req is not None:
                self._finalize(fid, req, out, rid)
        # in-flight work re-dispatches from its committed tokens,
        # queued work from scratch — wrecked replica excluded
        resumed = []
        for fid, committed in wreck["committed"].items():
            req = self._reqs.get(fid)
            if req is None:
                continue
            req["committed"] = req["committed"] + [
                int(t) for t in committed
            ]
            resumed.append(fid)
        for fid in wreck["queued"]:
            if fid in self._reqs:
                resumed.append(fid)
        # anything the router still counts against the dead replica
        # but the wreckage missed (defensive) re-dispatches too
        for fid in sorted(self._assigned.pop(rid, set())):
            if fid in self._reqs and fid not in resumed:
                resumed.append(fid)
        for fid in sorted(set(resumed)):
            req = self._reqs[fid]
            req["excluded"].add(rid)
            req["replica"] = None
            req["redispatches"] += 1
            self.stats["redispatched"] += 1
            self._m["redispatched"].inc()
            self._ledger.redispatch(req["rid"])
            # the mark rides the request's OWN trace (the re-dispatch
            # is one hop of that request's story), naming it in attrs
            self._tracer.mark(
                "fleet_redispatch", trace=req["rid"], severity="warn",
                request_index=fid, trace_id=req["rid"],
                from_replica=rid,
                tokens_committed=len(req["committed"]),
            )
        self._queue.extendleft(sorted(set(resumed), reverse=True))

    # -- straggler policy ------------------------------------------------

    def _observe_latency(self, rid, req):
        if req["sent_at"] is None:
            return
        lat = self._clock() - req["sent_at"]
        prev = self._lat_ewma.get(rid)
        self._lat_ewma[rid] = (
            lat if prev is None else 0.5 * prev + 0.5 * lat
        )
        replica = self.replicas[rid]
        others = [
            v for r2, v in self._lat_ewma.items()
            if r2 != rid and self.replicas[r2].alive
        ]
        med = _median(others)
        if med is None:
            return
        threshold = max(self.min_slow_sec, self.slow_factor * med)
        if replica.state == "live":
            if self._lat_ewma[rid] > threshold:
                self._suspect[rid] += 1
                if self._suspect[rid] >= self.suspect_rounds:
                    self.replica_set.evict(rid)
                    self._suspect[rid] = 0
                    self._clean[rid] = 0
                    self.stats["evicted"] += 1
                    self._m["evictions"].inc()
                    outstanding = sorted(self._assigned[rid])
                    self._tracer.mark(
                        "replica_evicted", trace="fleet",
                        severity="warn", replica=rid,
                        ewma_sec=round(self._lat_ewma[rid], 4),
                        fleet_median_sec=round(med, 4),
                        request_ids=outstanding,
                        trace_ids=[
                            self.stats["trace_ids"].get(f)
                            for f in outstanding
                        ],
                    )
                    logger.warning(
                        "fleet: routing around slow replica %d "
                        "(ewma %.3fs vs fleet median %.3fs)",
                        rid, self._lat_ewma[rid], med,
                    )
            else:
                self._suspect[rid] = 0
        elif replica.state == "routed_around":
            if lat <= threshold:
                self._clean[rid] += 1
                if self._clean[rid] >= self.readmit_rounds:
                    gate = self.readmit_gate
                    if gate is not None:
                        gate.poll()
                        if not gate.ready():
                            # quality gate holds the re-admission:
                            # enough clean PROBE rounds, but the
                            # health plane has not yet seen N clean
                            # rounds fleet-wide.  Journal once per
                            # blocked streak; keep probing.
                            if not self._gate_blocked.get(rid):
                                self._gate_blocked[rid] = True
                                self._tracer.mark(
                                    "readmit_gated", trace="fleet",
                                    severity="warn", replica=rid,
                                    clean_probe_rounds=self._clean[
                                        rid],
                                    clean_health_rounds=gate.streak,
                                    required_rounds=gate.rounds,
                                )
                                logger.info(
                                    "fleet: re-admission of replica "
                                    "%d gated on health plane (%d/%d "
                                    "clean rounds)", rid, gate.streak,
                                    gate.rounds,
                                )
                            return
                    if self._gate_blocked.pop(rid, None):
                        self._tracer.mark(
                            "readmit_cleared", trace="fleet",
                            replica=rid,
                            clean_health_rounds=(
                                gate.streak if gate is not None
                                else None
                            ),
                        )
                    self.replica_set.readmit(rid)
                    self._clean[rid] = 0
                    self._lat_ewma[rid] = lat
                    self.stats["readmitted"] += 1
                    self._m["readmissions"].inc()
                    self._tracer.mark(
                        "replica_readmitted", trace="fleet",
                        replica=rid,
                    )
                    logger.info(
                        "fleet: re-admitted replica %d after %d "
                        "clean probe round(s)", rid,
                        self.readmit_rounds,
                    )
            else:
                self._clean[rid] = 0

    # -- finalize --------------------------------------------------------

    def _finalize_resumed_complete(self, fid, req):
        """A re-dispatched request whose committed tokens already
        cover its budget: synthesize the final row without decoding
        anything (the tokens were committed pre-death)."""
        fill = self._eos_id if self._eos_id is not None else 0
        arr = np.full((self.max_new,), fill, np.int32)
        toks = req["committed"][:self.max_new]
        arr[:len(toks)] = toks
        out = {"generated": arr,
               "generated_len": np.int32(min(req["budget"],
                                             len(toks)))}
        req["committed"] = []
        self._reqs.pop(fid, None)
        self._finalize(fid, req, out, None)

    def _finalize(self, fid, req, out, rid):
        committed = req["committed"]
        if "error" in out:
            rec = dict(out["error"])
            rec["request_index"] = fid
            if rid is not None:
                rec["replica"] = rid
            if committed:
                rec["partial"] = committed + list(rec.get("partial", []))
                rec["tokens_done"] = len(rec["partial"])
            if self.on_error == "raise" and rec["kind"] in _RAISE_KINDS:
                raise serving_engine.RequestError(
                    "fleet request {0} failed on replica {1}: "
                    "{2}".format(fid, rid, rec["message"]),
                    kind=rec["kind"], request_index=fid,
                )
            if rec["kind"] in ("deadline",):
                self.stats["expired"] += 1
            elif rec["kind"] in ("drained",):
                self.stats["drained"] += 1
            else:
                self.stats["errors"] += 1
            self._ledger.close(
                req["rid"], tokens_out=rec.get("tokens_done", 0),
                latency_sec=self._clock() - req["submit"],
            )
            self._finished[fid] = {"error": rec}
            return
        if committed:
            # reassemble: committed prefix + the resumed continuation
            # (token-identical to an undisturbed greedy run — the
            # watchdog-recovery invariant, fleet-wide)
            gen = np.asarray(out["generated"], np.int32).ravel()
            merged = np.concatenate([
                np.asarray(committed, np.int32), gen
            ])[:self.max_new]
            fill = self._eos_id if self._eos_id is not None else 0
            if merged.shape[0] < self.max_new:
                merged = np.concatenate([
                    merged,
                    np.full((self.max_new - merged.shape[0],), fill,
                            np.int32),
                ])
            out = dict(out, generated=merged)
            if "generated_len" in out:
                out["generated_len"] = np.int32(
                    len(committed) + int(out["generated_len"])
                )
        # the AUTHORITATIVE emitted-token count for the cost row: the
        # merged committed+continuation length (the replica engine's
        # earlier close only saw its own continuation) — per-tenant
        # token totals then match the emitted outputs exactly
        if "generated_len" in out:
            tokens_out = int(out["generated_len"])
        else:
            tokens_out = len(committed) + self.max_new
        if not self._user_emit_len:
            out.pop("generated_len", None)
        out = serving_engine.apply_output_mapping(
            out, self.output_mapping
        )
        now = self._clock()
        self.stats["completed"] += 1
        self.stats["latency_sec"][fid] = now - req["submit"]
        self.stats["done_at"][fid] = now - self._t0
        self._m["completed"].inc()
        self._ledger.close(
            req["rid"], tokens_out=tokens_out,
            latency_sec=now - req["submit"],
        )
        self._finished[fid] = out

    def _drain_ready(self):
        while self._emit_next in self._finished:
            yield self._finished.pop(self._emit_next)
            self._emit_next += 1

    # -- remediation verbs (ISSUE 16) ------------------------------------

    def deploy_active(self):
        """True while a rolling deploy is mid-step — the remediation
        engine's conflict rule reads this (never fight a deploy)."""
        return self._deploy is not None and not self._deploy.finished

    def set_policy(self, policy):
        """Flip the fleet admission policy at runtime (``_pull`` and
        ``_admit`` consult ``self.policy`` every pass, so the flip
        takes effect on the next serve pass).  The remediation
        engine's degrade-on-page actuator; returns the PRIOR policy
        so the caller can restore it on resolve."""
        if policy not in serving_engine.POLICIES:
            raise ValueError(
                "fleet policy must be one of {0}, got {1!r}".format(
                    serving_engine.POLICIES, policy
                )
            )
        prior, self.policy = self.policy, policy
        self.stats["fleet_policy"] = policy
        if policy != prior:
            self._tracer.mark(
                "fleet_policy_changed", trace="fleet",
                policy=policy, prior=prior,
            )
        return prior

    def scale_up(self):
        """Spawn one replica (ReplicaSet.spawn) and route to it
        immediately — the autoscaling / capacity-restore actuator.
        Returns the new replica id."""
        r = self.replica_set.spawn()
        self.stats["replicas"] = len(self.replicas)
        self.stats["scaled_up"] += 1
        self._m_spawned.inc()
        self._m_live.set(sum(
            1 for x in self.replicas
            if x.alive and x.state == "live"
        ))
        self._tracer.mark(
            "replica_spawned", trace="fleet",
            replica_id=r.replica_id, replicas=len(self.replicas),
        )
        return r.replica_id

    def scale_down(self, replica_id=None):
        """Retire one live replica: drain it (no new traffic; its
        in-flight work completes and the collect path drains it back
        to the queue on close) and close it.  Picks the least-loaded
        live replica when ``replica_id`` is None; refuses to retire
        the last live replica.  Returns the retired id, or None when
        nothing is retirable."""
        live = [
            r for r in self.replicas
            if r.alive and r.state == "live"
        ]
        if len(live) <= 1:
            return None
        if replica_id is None:
            r = min(
                live, key=lambda x: (
                    self._assigned_count(x.replica_id), x.replica_id
                )
            )
        else:
            r = self.replicas[replica_id]
            if not (r.alive and r.state == "live"):
                return None
        rid = r.replica_id
        fids, trace_ids = self.outstanding_of(rid)
        self.replica_set.drain(rid)
        # the STOP sentinel queues BEHIND any dispatched rows: the
        # worker finishes in-flight work ("done" completions flow
        # normally), then posts "stopped" and exits
        r.close()
        self.stats["scaled_down"] += 1
        self._m_retired.inc()
        self._m_live.set(sum(
            1 for x in self.replicas
            if x.alive and x.state == "live"
        ))
        self._tracer.mark(
            "replica_retired", trace="fleet", severity="warn",
            replica_id=rid, request_ids=fids, trace_ids=trace_ids,
        )
        return rid

    # -- rolling deploys -------------------------------------------------

    def start_rolling_deploy(self, params=None, step=None,
                             step_dir=None, **opts):
        """Arm a zero-downtime rolling deploy, advanced by the serve
        loop one state-machine step per pass (fleet/deploy.py).
        Returns the :class:`~tensorflowonspark_tpu.fleet.deploy.
        RollingDeploy` (poll ``.status``)."""
        from tensorflowonspark_tpu.fleet.deploy import RollingDeploy

        if self._deploy is not None and not self._deploy.finished:
            raise RuntimeError(
                "a rolling deploy is already in progress "
                "({0})".format(self._deploy.status)
            )
        self._deploy = RollingDeploy(
            params=params, step=step, step_dir=step_dir, **opts
        )
        return self._deploy

    def _deploy_step(self):
        if self._deploy is None:
            return
        if self._deploy.step_machine(self):
            self.deploy_history.append(self._deploy.status)
            self._deploy = None

    # -- the routing loop ------------------------------------------------

    def serve(self, rows):
        """Route ``rows`` over the fleet; yields output rows / typed
        records in fleet input order.  Replicas keep running after the
        stream ends (warm caches, pending deploys) — close them via
        :meth:`close` / the :func:`predict_rows_fleet` wrapper.

        ``serve`` is re-entrant: each call opens a fresh stream over
        the same warm fleet (the soak harness serves load in waves,
        probing invariants between streams)."""
        it = iter(rows)
        self._exhausted = False
        while True:
            self._deploy_step()
            self._pull(it)
            self._note_pressure()
            self._dispatch()
            self._collect()
            for r in self._drain_ready():
                yield r
            if (self._exhausted and not self._reqs
                    and not self._queue):
                if self._deploy is not None:
                    # a deploy armed mid-stream finishes against idle
                    # replicas before the generator returns
                    while self._deploy is not None:
                        self._deploy_step()
                        time.sleep(self._poll / 5.0)
                for r in self._drain_ready():
                    yield r
                self._roll_up_stats()
                return

    def _roll_up_stats(self):
        per = {}
        for r in self.replicas:
            per[r.replica_id] = dict(r.stats)
            per[r.replica_id]["state"] = r.state
        self.stats["per_replica"] = per
        for key in ("admitted", "prefix_hits", "prefix_tokens_saved",
                    "swaps", "swap_commits", "rollbacks",
                    "swap_requeued", "watchdog_fires", "tokens_out"):
            self.stats[key] = sum(
                int(s.get(key, 0)) for s in per.values()
            )
        # fleet decode wall time: summed per-replica (each replica owns
        # its chip — the ledger's chip-second rows sum back to this)
        self.stats["decode_wall_sec"] = sum(
            float(s.get("decode_wall_sec", 0.0)) for s in per.values()
        )

    def close(self, timeout=30.0):
        self.replica_set.close(timeout=timeout)


def predict_rows_fleet(predict, rows, input_mapping,
                       output_mapping=None, num_slots=4, *, replicas,
                       stats=None, on_error="raise", queue_depth=None,
                       policy="block", watchdog_timeout=None,
                       default_deadline=None,
                       replica_policy="least_loaded",
                       fleet_queue_depth=None, chunk=None,
                       devices=None):
    """The fleet twin of ``predict_rows(schedule="continuous")``
    (serving.py routes here when ``replicas > 1``): N in-process
    engine replicas behind a :class:`FleetRouter`.  Same contract —
    dict rows in, rows/typed records out in input order — with the
    engine-level overload knobs applied per replica and the admission
    policy applied FLEET-level (spill before shed)."""
    engine_opts = {}
    if watchdog_timeout is not None:
        engine_opts["watchdog_timeout"] = watchdog_timeout
    if default_deadline is not None:
        engine_opts["default_deadline"] = default_deadline
    router = FleetRouter(
        predict, input_mapping, output_mapping,
        replicas=int(replicas), num_slots=num_slots, chunk=chunk,
        replica_queue_depth=queue_depth, engine_opts=engine_opts,
        policy=policy, dispatch=replica_policy,
        queue_depth=fleet_queue_depth, on_error=on_error,
        stats=stats, devices=devices,
    )
    try:
        for r in router.serve(rows):
            yield r
    finally:
        router.close()
