"""Replica set: N serving engines with per-replica lifecycle.

A :class:`Replica` is one continuous-batching engine
(:class:`~tensorflowonspark_tpu.serving_engine.ServingEngine`) plus
the plumbing that makes it routable: a bounded feed queue, a worker
thread driving the engine's scheduling loop, submit/emit bookkeeping
that pairs fleet request ids with the engine's input-order output
stream, and post-mortem wreckage collection so the router can
re-dispatch a dead replica's work from its committed tokens.

A :class:`ReplicaSet` owns N of them.  For tests and single-host
deployments the replicas are in-process ``ServingEngine`` workers
(each with its OWN :class:`~tensorflowonspark_tpu.models.transformer.
SlotDecoder` and its own radix prefix cache — ``serving_builder``
predictors expose ``make_replica()`` exactly for this); for executor
fleets the same duck-typed seam (``engine_factory``) fits an
executor-resident engine proxied over the reservation wire — the
router only ever touches ``dispatch`` / ``load`` / the completion
queue, never the engine internals.

The replica feed uses the engine's **source heartbeat protocol**
(:meth:`ServingEngine._pull_one`): between arrivals the feed yields
``None`` so an idle engine still runs its lifecycle pass (hot-swap
requests land on drained replicas — what rolling deploys need) and a
busy engine never blocks decode waiting on the queue.
"""

import logging
import queue as queue_mod
import threading

from tensorflowonspark_tpu import serving_engine

logger = logging.getLogger(__name__)

#: feed-queue sentinel: the replica finishes in-flight work and exits
_STOP = object()

#: replica lifecycle states (router-managed; see fleet/router.py):
#: ``live`` receives traffic, ``routed_around`` only probe traffic (a
#: slow replica working off its backlog), ``draining`` none (a rolling
#: deploy quiescing it), ``dead`` is terminal for the in-process shape
#: (an executor fleet would respawn through the supervisor).
STATES = ("live", "routed_around", "draining", "dead")


class ReplicaKilled(RuntimeError):
    """A chaos ``kill_replica`` fault fired inside this replica's
    decode dispatch — the in-process stand-in for a replica
    process/chip death mid-decode (testing/chaos.py)."""


class ReplicaDeviceError(RuntimeError):
    """A DEVICE error inside this replica's engine (an XLA runtime
    fault on a mesh-sharded program, or the chaos ``device_error``
    stand-in).  Unlike :class:`ReplicaKilled` — terminal — the
    replica's host-side scheduler survived: it posts its wreckage for
    committed-token-safe re-dispatch, QUARANTINES (the router's evict
    verb routes around it), rebuilds its engine (the predictor caches
    the compiled decoder, so this is cheap), and serves probe traffic
    until clean rounds re-admit it."""


#: exception type names treated as device errors when they surface
#: inside a replica's serve loop (jaxlib's runtime error classes are
#: matched by NAME so the containment works without importing jaxlib
#: internals)
_DEVICE_ERROR_NAMES = ("XlaRuntimeError", "JaxRuntimeError",
                      "InternalError")

#: engine stats keys that must stay CUMULATIVE across a quarantine
#: rebuild (a fresh engine resets the shared stats dict in place; the
#: ledger invariant — per-request rows summing to the fleet's decode
#: wall — needs the pre-quarantine spend preserved)
_CUMULATIVE_STATS = (
    "admitted", "completed", "chunks", "errors", "shed", "expired",
    "degraded", "watchdog_fires", "recovered", "request_wire_bytes",
    "prefix_hits", "prefix_tokens_saved", "evictions",
    "pressure_evictions", "swaps", "swap_requeued", "drained",
    "decode_wall_sec", "tokens_out", "prefill_wall_sec",
    "prefill_watchdog_fires", "prefill_worker_deaths",
    "prefill_restarts", "leases_reaped",
)


def _is_device_error(exc):
    """Does ``exc`` look like a device/runtime fault (quarantinable)
    rather than a scheduler bug or chaos kill (terminal)?"""
    if isinstance(exc, ReplicaDeviceError):
        return True
    return type(exc).__name__ in _DEVICE_ERROR_NAMES


class Replica(object):
    """One routable serving engine (see module docstring).

    Args:
      replica_id: stable int id (chaos plans and journal events name
        replicas by it).
      predict: this replica's OWN generation predictor (fresh jitted
        programs + radix cache — see ``serving_builder`` /
        ``make_replica``).
      input_mapping: the ENGINE-level mapping (the router builds it:
        user mapping + its internal budget column).
      completions: the router's shared completion queue; the worker
        posts ``("done", rid, fid, row)``, ``("dead", rid, wreck)``
        and ``("stopped", rid)`` tuples.
      num_slots / chunk / queue_depth / engine_opts: forwarded to
        :class:`ServingEngine` (policy is always ``block`` — fleet
        admission sheds BEFORE any single engine would, so the engine
        itself never rejects).
      engine_factory: override building the engine (the executor-
        resident seam; default builds an in-process ServingEngine).
      fault_fn: chunk-dispatch fault hook (chaos ``kill_replica`` /
        ``slow_replica``); defaults to the plan's
        :func:`~tensorflowonspark_tpu.testing.chaos.replica_fault_fn`.
      device: optional ``jax.Device`` the worker pins as default
        (benches spread replicas over virtual CPU devices; real
        fleets give each replica its own chip by construction).
      poll_sec: idle feed-poll interval (the heartbeat cadence — also
        how often an IDLE replica runs its lifecycle pass).
    """

    def __init__(self, replica_id, predict, input_mapping, completions,
                 *, num_slots=4, chunk=None, queue_depth=None,
                 engine_opts=None, engine_factory=None, fault_fn=None,
                 device=None, poll_sec=0.02):
        self.replica_id = int(replica_id)
        self.predict = predict
        self.state = "live"
        self.error = None
        if device is not None and getattr(predict, "mesh", None) is not None:
            # a TP-sharded predictor owns its device placement: its
            # committed mesh shardings (weights, KV pool) span several
            # devices, and pinning a single default device would fight
            # GSPMD.  The router above neither knows nor cares — the
            # replica surface is unchanged.
            device = None
        self.device = device
        self._poll_sec = float(poll_sec)
        self._completions = completions
        self._q = queue_mod.Queue()
        self._submitted = []   # fleet id per engine input index
        self._emitted = 0
        self.stats = {}
        if fault_fn is None:
            from tensorflowonspark_tpu.testing import chaos

            fault_fn = chaos.replica_fault_fn(self.replica_id)
        opts = dict(engine_opts or {})
        if fault_fn is not None:
            opts["wedge_fn"] = fault_fn
        if engine_factory is None:
            engine_factory = serving_engine.ServingEngine
        # construction knobs kept so a quarantined replica can rebuild
        # its engine in place (_rebuild_engine)
        self._engine_factory = engine_factory
        self._input_mapping = input_mapping
        self._num_slots = num_slots
        self._chunk = chunk
        self._queue_depth = queue_depth
        self._opts = opts
        self.engine = self._build_engine()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="fleet-replica-%d" % self.replica_id,
        )

    def _build_engine(self):
        """Build this replica's engine, under its default-device
        context when pinned (decoder state — slot caches, weights —
        must live on the replica's device; the context is thread-local
        so construction and serving both enter it explicitly)."""
        def build():
            return self._engine_factory(
                self.predict, self._input_mapping, None,
                self._num_slots, chunk=self._chunk,
                queue_depth=self._queue_depth, policy="block",
                on_error="record", stats=self.stats, **self._opts
            )

        if self.device is not None:
            import jax

            with jax.default_device(self.device):
                return build()
        return build()

    def _rebuild_engine(self):
        """Rebuild the engine after a quarantined device error.  The
        predictor caches its SlotDecoder, so the rebuilt engine reuses
        the compiled programs; the decoder's slots reset (freeing the
        quarantined incarnation's pages), the submit/emit pairing
        restarts with the fresh engine's input numbering, and the
        counters a fresh engine zeroes in the shared stats dict are
        restored cumulatively (the fleet ledger invariant — rows
        summing to decode wall — spans incarnations)."""
        prior = {
            k: v for k, v in self.stats.items()
            if k in _CUMULATIVE_STATS
            and isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        dec = getattr(self.engine, "decoder", None)
        reset = getattr(dec, "reset", None)
        if reset is not None:
            try:
                reset()
            except Exception:  # noqa: BLE001 - a broken decoder must
                logger.warning(  # not stop the quarantine rebuild
                    "replica %d: decoder reset failed during "
                    "quarantine rebuild", self.replica_id,
                    exc_info=True,
                )
        self._submitted = []
        self._emitted = 0
        self.engine = self._build_engine()
        for k, v in prior.items():
            cur = self.stats.get(k)
            if isinstance(cur, (int, float)) and not isinstance(
                    cur, bool):
                self.stats[k] = cur + v

    # -- lifecycle ------------------------------------------------------

    def start(self):
        if self._thread.ident is None:  # idempotent
            self._thread.start()
        return self

    def close(self):
        """Ask the worker to finish in-flight work and exit (the
        engine drains its slots, then the feed's STOP ends it)."""
        self._q.put(_STOP)

    def join(self, timeout=30.0):
        self._thread.join(timeout=timeout)
        return not self._thread.is_alive()

    @property
    def alive(self):
        return self.state != "dead"

    # -- routing surface ------------------------------------------------

    def dispatch(self, fid, row):
        """Hand one prepared engine row to this replica's feed."""
        self._q.put((int(fid), row))

    def load(self):
        """The router's placement signal: the engine's lock-light
        :meth:`~tensorflowonspark_tpu.serving_engine.ServingEngine.
        load` snapshot plus the rows parked in the replica feed that
        the engine has not pulled yet."""
        snap = self.engine.load()
        snap["queued"] += self._q.qsize()
        snap["replica"] = self.replica_id
        snap["state"] = self.state
        return snap

    def capacity(self):
        """Requests this replica can hold (slots + engine queue bound)
        — the router never assigns beyond it (spill-before-shed)."""
        return int(self.engine.num_slots) + int(self.engine.queue_depth)

    # -- the worker -----------------------------------------------------

    def _source(self):
        """The engine feed: rows as they arrive, ``None`` heartbeats
        between arrivals (never blocking a busy engine — decode chunks
        keep their cadence), blocking ``poll_sec`` at a time when the
        engine is idle so an idle replica still runs its lifecycle
        pass (pending hot-swaps apply)."""
        while True:
            try:
                # _slot_req read from the engine's own scheduler
                # thread (the source runs inside serve()) — safe
                if self.engine._slot_req:
                    item = self._q.get_nowait()
                else:
                    item = self._q.get(timeout=self._poll_sec)
            except queue_mod.Empty:
                yield None
                continue
            if item is _STOP:
                return
            fid, row = item
            self._submitted.append(fid)
            yield row

    def _run(self):
        while True:
            serve = self.engine.serve(self._source())
            if self.device is not None:
                import jax

                with jax.default_device(self.device):
                    status = self._drive(serve)
            else:
                status = self._drive(serve)
            if status != "quarantine":
                return
            # contained device error: rebuild the engine in place and
            # keep serving (probe traffic while routed around; full
            # traffic again once clean rounds re-admit the replica)
            try:
                self._rebuild_engine()
            except BaseException as e:  # noqa: BLE001 - rebuild
                self.state = "dead"   # failure IS a death
                self.error = e
                logger.warning(
                    "fleet replica %d: quarantine rebuild failed, "
                    "replica is dead: %s", self.replica_id, e,
                )
                self._completions.put((
                    "dead", self.replica_id,
                    {"finished": {}, "committed": {}, "queued": []},
                ))
                return

    def _drive(self, serve):
        try:
            for out in serve:
                fid = self._submitted[self._emitted]
                self._emitted += 1
                self._completions.put(
                    ("done", self.replica_id, fid, out)
                )
        except BaseException as e:  # noqa: BLE001 - death is a message
            if _is_device_error(e):
                # the host-side scheduler survived a device fault:
                # quarantine instead of dying — wreckage still posts
                # (the router re-dispatches it on a survivor), but the
                # replica will rebuild and serve probe traffic
                self.state = "routed_around"
                self.error = e
                logger.warning(
                    "fleet replica %d quarantined on device error: %s",
                    self.replica_id, e,
                )
                self._completions.put(
                    ("quarantine", self.replica_id, self._wreckage())
                )
                return "quarantine"
            self.state = "dead"
            self.error = e
            logger.warning(
                "fleet replica %d died: %s", self.replica_id, e
            )
            self._completions.put(
                ("dead", self.replica_id, self._wreckage())
            )
            return "dead"
        self._completions.put(("stopped", self.replica_id))
        return "stopped"

    def _wreckage(self):
        """Post-mortem accounting a dead replica owes the router
        (host-side scheduler state survives the death of the decode
        dispatch, like a driver outliving its device):

        - ``finished``: fleet id -> output row — requests the engine
          COMPLETED but had not emitted yet (held in its reorder
          buffer behind an earlier request); their tokens are real,
          the router delivers them as-is;
        - ``committed``: fleet id -> committed token list — requests
          in flight (or engine-queued after a prior requeue) at
          death; the router re-dispatches each from these tokens
          (greedy continuations are token-identical — the same
          invariant the engine's own watchdog recovery pins);
        - ``queued``: fleet ids never pulled from the feed (plus any
          the engine consumed but finished nowhere) — re-dispatched
          from scratch.
        """
        eng = self.engine
        finished = {}
        committed = {}
        queued = []
        accounted = set()
        for idx, row in eng._finished.items():
            if idx < len(self._submitted):
                finished[self._submitted[idx]] = row
                accounted.add(idx)
        for req in list(eng._slot_req.values()) + list(eng._pending):
            idx = req["idx"]
            if idx < len(self._submitted):
                committed[self._submitted[idx]] = [
                    t for t in (req["out"] or []) if isinstance(t, int)
                ]
                accounted.add(idx)
            # the chip/page-seconds this request accrued HERE flush to
            # its ledger row now (the engine's terminal points never
            # run for a dead replica's in-flight work): the spend was
            # real, and the surviving replica's row continues it —
            # per-request rows keep summing to the fleet's measured
            # decode wall time (ISSUE 14 acceptance)
            try:
                eng._ledger_settle(req, close=False)
            except Exception as e:  # noqa: BLE001
                # accounting must never break wreckage collection —
                # but a broken ledger should not stay invisible either
                # (surfaced by the ISSUE 15 tfoslint sweep)
                logger.debug(
                    "wreckage ledger flush failed for %r: %s",
                    req.get("rid"), e,
                )
        saw_stop = False
        while True:
            try:
                item = self._q.get_nowait()
            except queue_mod.Empty:
                break
            if item is _STOP:
                saw_stop = True
            else:
                queued.append(item[0])
        if saw_stop:
            # a close() raced the fault: keep the stop order so a
            # quarantined replica's rebuilt loop still honors it
            self._q.put(_STOP)
        # engine indices consumed but accounted nowhere (lost between
        # pull and admit) re-dispatch from scratch
        for idx in range(self._emitted, len(self._submitted)):
            if idx not in accounted:
                queued.append(self._submitted[idx])
        return {
            "finished": finished, "committed": committed,
            "queued": queued,
        }


class ReplicaSet(object):
    """N replicas of one generation predictor (see module docstring).

    Args:
      predict: a generation predictor (``serving_builder(mode=
        "generate")``).  Replica 0 serves it directly; replicas 1..N-1
        are built from ``predict.make_replica()`` (their own jitted
        programs + radix caches).  Pass ``predict_factory`` instead to
        control construction (tests with fake decoders).
      n: replica count.
      input_mapping: engine-level mapping (see :class:`Replica`).
      completions: the router's completion queue (built here when the
        set is used standalone).
      devices: ``"spread"`` pins replica ``i`` to
        ``jax.devices()[i % len]`` (benches on the virtual CPU mesh);
        None leaves placement to jax (real fleets: one chip per
        replica by construction).
      num_slots / chunk / queue_depth / engine_opts / poll_sec:
        per-replica engine knobs, forwarded to :class:`Replica`.
    """

    def __init__(self, predict, n, input_mapping, *, completions=None,
                 predict_factory=None, num_slots=4, chunk=None,
                 queue_depth=None, engine_opts=None, devices=None,
                 poll_sec=0.02):
        n = int(n)
        if n < 1:
            raise ValueError("need at least one replica, got %d" % n)
        self.completions = (
            completions if completions is not None else queue_mod.Queue()
        )
        devs = None
        if devices == "spread":
            import jax

            devs = jax.devices()
        # construction knobs kept for spawn(): the autoscaling verb
        # (ISSUE 16) builds late replicas exactly like the initial set
        self._predict = predict
        self._predict_factory = predict_factory
        self._input_mapping = input_mapping
        self._num_slots = num_slots
        self._chunk = chunk
        self._queue_depth = queue_depth
        self._engine_opts = engine_opts
        self._devs = devs
        self._poll_sec = poll_sec
        predicts = []
        for i in range(n):
            if predict_factory is not None:
                predicts.append(predict_factory())
            elif i == 0:
                predicts.append(predict)
            else:
                predicts.append(self._replica_predict(n))
        self.replicas = [
            self._build(i, predicts[i]) for i in range(n)
        ]

    def _replica_predict(self, n):
        factory = getattr(self._predict, "make_replica", None)
        if factory is None:
            raise ValueError(
                "fleet serving with {0} replicas needs a "
                "predictor exposing make_replica() (transformer."
                "serving_builder generation predictors do) — "
                "each replica must own its decoder; this "
                "predictor has none".format(n)
            )
        return factory()

    def _build(self, rid, predict):
        devs = self._devs
        return Replica(
            rid, predict, self._input_mapping, self.completions,
            num_slots=self._num_slots, chunk=self._chunk,
            queue_depth=self._queue_depth,
            engine_opts=self._engine_opts,
            device=devs[rid % len(devs)] if devs else None,
            poll_sec=self._poll_sec,
        )

    def spawn(self):
        """Build, append, and START one more replica (its id is the
        next list index — the router shares this list, so the new
        replica is routable the moment this returns).  The autoscale /
        capacity-restore actuator (ISSUE 16); construction mirrors the
        initial set (``predict_factory`` when given, else
        ``predict.make_replica()``)."""
        rid = len(self.replicas)
        if self._predict_factory is not None:
            predict = self._predict_factory()
        else:
            predict = self._replica_predict(rid + 1)
        r = self._build(rid, predict)
        self.replicas.append(r)
        return r.start()

    def __len__(self):
        return len(self.replicas)

    def __iter__(self):
        return iter(self.replicas)

    def __getitem__(self, rid):
        return self.replicas[rid]

    def start(self):
        for r in self.replicas:
            r.start()
        return self

    def live(self):
        """Replicas currently accepting routed traffic."""
        return [r for r in self.replicas if r.state == "live"]

    def load(self):
        """Per-replica load snapshots, the ``/status`` fleet view."""
        return [r.load() for r in self.replicas]

    # per-replica lifecycle verbs (the router drives these; they are
    # also the operator surface)
    def drain(self, rid):
        """Stop routing to ``rid`` (rolling deploys quiesce through
        this); in-flight work finishes normally."""
        if self.replicas[rid].state != "dead":
            self.replicas[rid].state = "draining"

    def evict(self, rid):
        """Route around ``rid`` (a straggler working off its backlog
        still completes what it holds, and receives probe traffic)."""
        if self.replicas[rid].state != "dead":
            self.replicas[rid].state = "routed_around"

    def readmit(self, rid):
        """Return ``rid`` to full routing."""
        if self.replicas[rid].state != "dead":
            self.replicas[rid].state = "live"

    def close(self, join=True, timeout=30.0):
        for r in self.replicas:
            if r.alive:
                r.close()
        if join:
            for r in self.replicas:
                r.join(timeout=timeout)
