"""ML-Pipeline adapter: Estimator/Model over the cluster API.

Re-designed from the reference's ``pipeline.py`` (reference:
tensorflowonspark/pipeline.py): a Spark-ML-style ``Estimator`` whose
``fit`` runs distributed training through the cluster API and returns a
``Model`` whose ``transform`` runs per-executor batch inference with a
cached predictor.  Surface parity:

- the 18 ``Has*`` param mixins with get/set accessors
  (reference: pipeline.py:49-293);
- ``Namespace`` + ``TFParams.merge_args_params`` layering pipeline
  params over the user's argparse args (reference: pipeline.py:296-348);
- ``TFEstimator(train_fn, tf_args, export_fn)._fit`` =
  ``cluster.run → cluster.train → cluster.shutdown → TFModel``
  (reference: pipeline.py:392-432);
- ``TFModel._transform`` = per-executor singleton predictor + batched
  prediction (reference: pipeline.py:460-489,492-496,596-642).

TPU redesign notes: datasets are engine-agnostic — a list of dict rows,
a list of row partitions, or a pyspark DataFrame (converted via
:mod:`tensorflowonspark_tpu.data.spark_io` when pyspark is present).
The predictor contract replaces SavedModel signature lookup
(reference: pipeline.py:519-529,559-564): a serving export carries a
``model_ref`` builder in its metadata (see
:mod:`tensorflowonspark_tpu.serving`), so ``signature_def_key`` /
``tag_set`` survive as optional metadata selectors rather than graph
queries.
"""

import copy
import logging

logger = logging.getLogger(__name__)


class Namespace(object):
    """Dict/argparse-interchangeable attribute bag
    (reference: pipeline.py:296-341)."""

    def __init__(self, d=None, **kwargs):
        if d is None:
            pass
        elif isinstance(d, dict):
            self.__dict__.update(d)
        elif hasattr(d, "__dict__"):
            self.__dict__.update(d.__dict__)
        else:
            raise ValueError(
                "Namespace expects a dict or an argparse Namespace, got "
                "{0!r}".format(type(d))
            )
        self.__dict__.update(kwargs)

    def __contains__(self, key):
        return key in self.__dict__

    def __iter__(self):
        return iter(self.__dict__)

    def __eq__(self, other):
        return isinstance(other, Namespace) and vars(self) == vars(other)

    def __repr__(self):
        return "Namespace({0})".format(self.__dict__)


# ----------------------------------------------------------------------
# Param machinery — a light stand-in for pyspark.ml.param that works
# without Spark (the reference required a live SparkML runtime,
# pipeline.py:25-27); the accessor surface is identical.
# ----------------------------------------------------------------------


class Param(object):
    def __init__(self, name, doc, default=None):
        self.name = name
        self.doc = doc
        self.default = default


def _mixin(param_name, doc, default=None, cap=None):
    """Build a Has<Cap> mixin class with get/set accessors
    (reference: pipeline.py:49-293 defines these by hand)."""
    cap = cap or "".join(w.capitalize() for w in param_name.split("_"))
    param = Param(param_name, doc, default)

    def setter(self, value):
        self._paramMap[param_name] = value
        return self

    def getter(self):
        return self._paramMap.get(param_name, param.default)

    cls = type(
        "Has" + cap,
        (object,),
        {
            param_name: param,
            "set" + cap: setter,
            "get" + cap: getter,
        },
    )
    return cls


HasBatchSize = _mixin("batch_size", "number of records per batch", 128)
HasClusterSize = _mixin("cluster_size", "number of nodes in the cluster", 1)
HasEpochs = _mixin("epochs", "number of epochs of training data", 1)
HasExportDir = _mixin("export_dir", "directory to export the serving model")
HasGraceSecs = _mixin(
    "grace_secs", "seconds to wait after feed end before shutdown", 30
)
HasInputMapping = _mixin(
    "input_mapping", "mapping of input columns to predictor inputs"
)
HasInputMode = _mixin(
    "input_mode", "input mode (InputMode.SPARK | InputMode.TENSORFLOW)"
)
HasMasterNode = _mixin(
    "master_node", "job name of the chief/master node", None
)
HasModelDir = _mixin("model_dir", "directory for checkpoints/events")
HasNumPS = _mixin("num_ps", "number of parameter-server nodes", 0, cap="NumPS")
HasOnError = _mixin(
    "on_error",
    "per-request inference failure policy: 'raise' fails the job "
    "naming the poisoned request; 'record' isolates it as a typed "
    "error record (serving_engine.error_record) at its row position",
    "raise",
)
HasOutputMapping = _mixin(
    "output_mapping", "mapping of predictor outputs to output columns"
)
# serving schedule for TFModel.transform: "static" fixed-size batches
# or "continuous" slot-level in-flight batching for generation exports
# (batch_size then counts KV-cache slots — docs/serving.md)
HasSchedule = _mixin(
    "schedule",
    "inference batching schedule: 'static' | 'continuous'",
    "static",
)
# serving lifecycle (docs/serving.md "Live weight swap & rollback"):
# a step-numbered serving-export root (checkpoint.publish_for_serving
# layout) each executor's continuous engine watches during the
# transform — newly published checkpoints are validated (manifest/
# shape/dtype + canary; corrupt ones quarantined with a typed reason)
# and hot-swapped between decode chunks with zero dropped requests
HasCheckpointDir = _mixin(
    "checkpoint_dir",
    "step-numbered serving-export root to watch for validated live "
    "weight hot-swaps during continuous-schedule transforms",
    cap="CheckpointDir",
)
# deployment-time model_config overrides laid over the export metadata
# before the predictor builds (serving.load_predictor config_overrides)
# — the pipeline surface for the cross-request reuse knobs:
# prefix_cache/prefix_block/prefix_mem_mb, draft_config/draft_len,
# chunk_size, speculative (docs/serving.md "Prefix cache & speculative
# decoding")
HasModelConfig = _mixin(
    "model_config",
    "dict of model_config keys laid over the serving export's "
    "metadata at load time (prefix cache, draft model, chunk sizing)",
)
# cost attribution (docs/observability.md "Cost attribution & usage
# ledger"): the DataFrame column carrying each row's TENANT key.  The
# transform maps it onto the reserved "tenant" serving input, so the
# usage ledger attributes tokens / chip-seconds / page-seconds to the
# tenant (validated at admission on both schedules: non-string or
# empty values become typed bad_tenant errors naming the row)
HasTenantCol = _mixin(
    "tenant_col",
    "input column carrying the per-request tenant key for the usage "
    "ledger (mapped to the reserved 'tenant' serving input)",
    cap="TenantCol",
)
# the narrow-dtype data plane's widening stage (docs/data_plane.md):
# a JSON-able dict of data.preprocess.make_preprocess kwargs.  On
# TFModel it is fused in front of the predictor on device
# (serving.with_preprocess); on TFEstimator it rides the merged args
# so train_fns can build SyncTrainer(device_preprocess=args.preprocess)
HasPreprocess = _mixin(
    "preprocess",
    "on-device preprocess spec (data.preprocess.make_preprocess "
    "kwargs dict) — cast/scale/normalize narrow wire dtypes in HBM "
    "instead of on the host",
)
# the reference's HasProtocol chose TF's RPC fabric ('grpc'|'rdma',
# reference: pipeline.py:189-199) — N/A on TPU, where XLA owns the
# collective transport; the param survives as an ICI/DCN placement hint
HasProtocol = _mixin(
    "protocol", "collective transport hint: 'ici' | 'dcn'", "ici"
)
HasReservationTimeout = _mixin(
    "reservation_timeout", "startup barrier timeout (secs)", 600
)
HasFeedTimeout = _mixin("feed_timeout", "data feed timeout (secs)", 600)
HasSignatureDefKey = _mixin(
    "signature_def_key", "serving signature selector in export metadata"
)
HasTagSet = _mixin("tag_set", "serving export variant tag", "serve")
HasTensorboard = _mixin(
    "tensorboard", "launch TensorBoard on chief/worker:0", False
)
HasTFRecordDir = _mixin(
    "tfrecord_dir", "directory of TFRecords to feed in TENSORFLOW mode",
    cap="TFRecordDir",
)


class TFParams(object):
    """Base for param holders (reference: pipeline.py:343-348)."""

    def __init__(self):
        self._paramMap = {}
        self.args = None

    def merge_args_params(self):
        """Return a copy of ``self.args`` with every set param laid over
        it (reference: pipeline.py:343-348)."""
        args = Namespace(copy.deepcopy(vars(self.args))) if self.args else Namespace()
        for name, value in self._paramMap.items():
            setattr(args, name, value)
        # fill defaults for params never set explicitly
        for klass in type(self).__mro__:
            for attr, p in vars(klass).items():
                if isinstance(p, Param) and not hasattr(args, p.name):
                    setattr(args, p.name, p.default)
        return args

    def _copy_params(self, other):
        other._paramMap = dict(self._paramMap)
        other.args = self.args
        return other


_ESTIMATOR_MIXINS = (
    HasBatchSize,
    HasClusterSize,
    HasEpochs,
    HasExportDir,
    HasGraceSecs,
    HasInputMapping,
    HasInputMode,
    HasMasterNode,
    HasModelDir,
    HasNumPS,
    HasPreprocess,
    HasProtocol,
    HasReservationTimeout,
    HasFeedTimeout,
    HasTensorboard,
    HasTFRecordDir,
)

_MODEL_MIXINS = (
    HasBatchSize,
    HasCheckpointDir,
    HasExportDir,
    HasInputMapping,
    HasModelConfig,
    HasModelDir,
    HasOnError,
    HasOutputMapping,
    HasPreprocess,
    HasSchedule,
    HasSignatureDefKey,
    HasTagSet,
    HasTenantCol,
)


# ----------------------------------------------------------------------
# dataset plumbing
# ----------------------------------------------------------------------


def _is_spark_dataframe(dataset):
    return type(dataset).__module__.startswith("pyspark")


class Partitions(object):
    """Explicit marker for pre-partitioned input: wrap a list of row
    lists so flat datasets of *list-typed rows* are never misread as
    partitions (``TFEstimator(...).fit(Partitions([[row, ...], ...]))``)."""

    def __init__(self, partitions):
        self.partitions = [list(p) for p in partitions]


def _to_partitions(dataset, num_partitions, columns=None):
    """Normalize a dataset to a list of row partitions.

    Accepts a list of dict rows, a :class:`Partitions` wrapper, a list
    of partitions (list of lists *of dict/tuple rows* — a flat dataset
    of list-typed rows splits like any other flat dataset), or a
    pyspark DataFrame (gated).  ``columns`` restricts/sorts dict
    rows into tuples — the driver-side twin of the reference's
    ``df.select(sorted(input_mapping))`` (reference: pipeline.py:411-413).
    """
    if _is_spark_dataframe(dataset):
        from tensorflowonspark_tpu.data import spark_io

        dataset = spark_io.dataframe_to_rows(dataset)
    if isinstance(dataset, Partitions):
        partitions = dataset.partitions  # already materialized by ctor
        rows = None
    else:
        rows = list(dataset)
    if rows is not None and _looks_partitioned(rows):
        # unambiguously partitioned: a list of row-lists of dict/tuple
        # rows (list-typed or scalar *rows* stay on the flat path below;
        # wrap in Partitions to force this branch)
        partitions = [list(p) for p in rows]
    elif rows is not None:
        num_partitions = max(1, num_partitions)
        partitions = [rows[i::num_partitions] for i in range(num_partitions)]
        partitions = [p for p in partitions if p] or [[]]
    if columns:
        partitions = [
            [_select(row, columns) for row in part] for part in partitions
        ]
    return partitions


def _looks_partitioned(rows):
    """True when ``rows`` is a list of row-lists of dict/tuple rows.
    Empty partitions are skipped when probing (an empty *first*
    partition must not reclassify the dataset as flat)."""
    if not rows or not all(isinstance(p, list) for p in rows):
        return False
    for p in rows:
        if p:
            return isinstance(p[0], (dict, tuple))
    return True  # all partitions empty: treat as (vacuously) partitioned


def _select(row, columns):
    if isinstance(row, dict):
        return tuple(row[c] for c in columns)
    return tuple(row)


# ----------------------------------------------------------------------
# Estimator
# ----------------------------------------------------------------------


class TFEstimator(TFParams, *_ESTIMATOR_MIXINS):
    """Distributed-training estimator (reference: pipeline.py:351-432).

    Args:
      train_fn: the user's ``main_fun(args, ctx)``.
      tf_args: argparse Namespace / dict of user args (merged with set
        params at fit time, reference: pipeline.py:403-408).
      export_fn: optional chief-side export hook
        ``export_fn(args, ctx)`` run after ``train_fn`` returns
        (reference carried an export_fn for TF1 graphs,
        pipeline.py:362-368; TF2-style apps export inside train_fn).
      engine: an Engine / SparkContext / int (forwarded to
        ``cluster.run``); defaults to ``cluster_size`` local executor
        processes.
    """

    def __init__(self, train_fn, tf_args=None, export_fn=None, engine=None):
        super(TFEstimator, self).__init__()
        self.train_fn = train_fn
        self.export_fn = export_fn
        self.engine = engine
        self.args = Namespace(tf_args) if not isinstance(
            tf_args, Namespace
        ) else tf_args

    def fit(self, dataset):
        return self._fit(dataset)

    def _fit(self, dataset):
        from tensorflowonspark_tpu.cluster import cluster as tfcluster

        args = self.merge_args_params()
        logger.info("fit: merged args: %s", args)

        input_mode = args.input_mode
        if input_mode is None:
            input_mode = tfcluster.InputMode.SPARK
        engine = self.engine if self.engine is not None else args.cluster_size

        train_fn = self.train_fn
        if self.export_fn is not None:
            export_fn = self.export_fn

            def train_fn(a, ctx, _inner=self.train_fn):  # noqa: F811
                result = _inner(a, ctx)
                # exactly-one-exporter: the dedicated chief when one
                # exists, else worker:0 (reference: compat.py:10-17;
                # same XOR as the tensorboard-node rule, node.py)
                has_chief = any(
                    j in ctx.cluster_spec for j in ("chief", "master")
                )
                is_exporter = (
                    ctx.job_name in ("chief", "master")
                    if has_chief
                    else (ctx.job_name == "worker" and ctx.task_index == 0)
                )
                if is_exporter:
                    export_fn(a, ctx)
                return result

        cluster = tfcluster.run(
            engine,
            train_fn,
            args,
            num_executors=args.cluster_size,
            num_ps=args.num_ps,
            tensorboard=args.tensorboard,
            input_mode=input_mode,
            log_dir=args.model_dir,
            master_node=args.master_node,
            reservation_timeout=args.reservation_timeout,
        )
        if input_mode == tfcluster.InputMode.SPARK:
            input_cols = (
                sorted(args.input_mapping) if args.input_mapping else None
            )
            if (
                _is_spark_dataframe(dataset)
                and hasattr(dataset, "select")  # DataFrame, not a bare RDD
                and cluster.engine.is_native_dataset(dataset)
            ):
                # feed the DataFrame's RDD in place — the reference's
                # path (``df.select(sorted(cols)).rdd`` →
                # ``cluster.train``, reference: pipeline.py:411-413);
                # rows never transit the driver.  Row shape matches the
                # driver-materialized path: sorted-column tuples with an
                # input_mapping, dict rows without.
                if input_cols:
                    fed = dataset.select(*input_cols).rdd.map(tuple)
                else:
                    fed = dataset.rdd.map(lambda r: r.asDict())
            else:
                fed = _to_partitions(
                    dataset, args.cluster_size, columns=input_cols
                )
            cluster.train(fed, args.epochs, feed_timeout=args.feed_timeout)
        cluster.shutdown(grace_secs=args.grace_secs)

        model = TFModel(args)
        self._copy_params(model)
        model.args = args
        return model


# ----------------------------------------------------------------------
# Model
# ----------------------------------------------------------------------

#: per-executor-process predictor singleton (reference: pipeline.py:492-496
#: kept ``global pred_fn`` keyed by args in the python worker)
_TRANSFORM_STATE = {"key": None, "predict": None}


def _run_model_iter(rows, args, predictor_builder=None):
    """Per-partition inference body (reference: pipeline.py:596-642
    ``_run_model_tf2``); runs inside an executor process.  Yields
    output dict-rows as they are produced (the lazy Spark path streams
    them straight into the result RDD without materializing the
    partition)."""
    import json as _json

    from tensorflowonspark_tpu import serving

    preprocess = getattr(args, "preprocess", None)
    model_config = getattr(args, "model_config", None)
    key = (
        args.export_dir,
        args.signature_def_key,
        args.tag_set,
        serving._builder_key(predictor_builder),
        serving._preprocess_key(preprocess),
        _json.dumps(model_config, sort_keys=True, default=str)
        if model_config else None,
    )
    if _TRANSFORM_STATE["key"] != key:
        logger.info("loading predictor for %s", key)
        _TRANSFORM_STATE["predict"] = serving.load_predictor(
            args.export_dir, builder=predictor_builder,
            preprocess=preprocess, config_overrides=model_config,
        )
        _TRANSFORM_STATE["key"] = key
    predict = _TRANSFORM_STATE["predict"]

    # setTenantCol: fold the tenant column into the input mapping as
    # the reserved "tenant" serving input — the usage ledger then
    # attributes each row's resources to its tenant (ISSUE 14)
    input_mapping = dict(args.input_mapping or {})
    tenant_col = getattr(args, "tenant_col", None)
    if tenant_col:
        input_mapping[tenant_col] = serving.TENANT_INPUT

    return serving.predict_rows(
        predict,
        rows,
        input_mapping=input_mapping,
        output_mapping=args.output_mapping,
        batch_size=args.batch_size,
        # setSchedule("continuous"): slot-level in-flight batching for
        # generation exports — batch_size counts KV slots, and the
        # prefix-cache / speculative knobs (setModelConfig) apply
        schedule=getattr(args, "schedule", None) or "static",
        # poison isolation (setOnError("record")): a bad row becomes a
        # typed error record at its position instead of failing the
        # partition — when transforming to a typed DataFrame, include
        # an "error" column in the output schema to surface them
        on_error=getattr(args, "on_error", None) or "raise",
        # setCheckpointDir: each executor's continuous engine watches
        # this publish_for_serving root and hot-swaps validated new
        # weight generations mid-transform (zero dropped requests;
        # docs/serving.md "Live weight swap & rollback")
        checkpoint_dir=getattr(args, "checkpoint_dir", None) or None,
    )


def _run_model(rows, args, predictor_builder=None):
    return list(_run_model_iter(rows, args, predictor_builder))


def _py_value(v):
    """numpy output -> Spark-compatible python value (scalars via
    ``.item()``; arrays flattened to 1-D lists — the reference's Scala
    path likewise emits each output tensor as one flat ArrayType
    column per row, TFModel.scala:294-335)."""
    import numpy as np

    if isinstance(v, np.ndarray):
        return v.ravel().tolist()
    if isinstance(v, np.generic):
        return v.item()
    return v


def _infer_output_type(v):
    """numpy output value -> interchange type string for the derived
    DataFrame schema."""
    import numpy as np

    a = np.asarray(v)
    kind = a.dtype.kind
    if kind == "f":
        base = "float" if a.dtype.itemsize <= 4 else "double"
    elif kind in "iu":
        base = "int" if a.dtype.itemsize <= 4 else "long"
    elif kind == "b":
        base = "boolean"
    else:
        base = "string"
    return "array<{0}>".format(base) if a.ndim >= 1 else base


class TFModel(TFParams, *_MODEL_MIXINS):
    """Batch-inference model (reference: pipeline.py:435-489).

    ``transform`` runs per-executor single-node inference with a cached
    predictor — no cluster startup (reference: pipeline.py:460-489).

    Args:
      tf_args: args/params namespace (export_dir etc.).
      predictor_builder: optional ``builder(params, config) -> predict``
        shipped to executors (overrides the export's ``model_ref``).
      engine: Engine / SparkContext / int; defaults to 1 local executor.
    """

    def __init__(self, tf_args=None, predictor_builder=None, engine=None):
        super(TFModel, self).__init__()
        self.args = Namespace(tf_args) if not isinstance(
            tf_args, Namespace
        ) else tf_args
        self.predictor_builder = predictor_builder
        self.engine = engine

    def transform(self, dataset, num_partitions=None):
        return self._transform(dataset, num_partitions)

    # -- telemetry accessors (ISSUE 7: the pipeline layer's window
    # into the fleet telemetry plane, docs/observability.md) ----------

    def telemetrySnapshot(self):
        """This process's metrics-registry snapshot (plain dicts): the
        serving counters/latency histogram a local transform published.
        Executor-side transforms publish into THEIR processes — pull
        those through the cluster plane (``TFCluster.metrics()``) or a
        ``reservation.Client(addr).get_metrics()``."""
        from tensorflowonspark_tpu import telemetry

        return telemetry.get_registry().snapshot()

    def traceEvents(self):
        """This process's recorded spans as Chrome-trace JSON (load in
        chrome://tracing or Perfetto); same process scope as
        :meth:`telemetrySnapshot`."""
        from tensorflowonspark_tpu import telemetry

        return telemetry.get_tracer().export_chrome()

    def _transform(self, dataset, num_partitions=None):
        from tensorflowonspark_tpu.engine import Engine, LocalEngine, SparkEngine

        args = self.merge_args_params()
        if not args.export_dir:
            raise ValueError("export_dir must be set before transform()")
        if not args.input_mapping:
            raise ValueError("input_mapping must be set before transform()")

        engine = self.engine
        owns_engine = False
        if engine is None:
            engine = LocalEngine(1)
            owns_engine = True
        elif isinstance(engine, int):
            engine = LocalEngine(engine)
            owns_engine = True
        elif not isinstance(engine, Engine) and hasattr(engine, "parallelize"):
            engine = SparkEngine(engine)

        if engine.is_native_dataset(dataset):
            # engine-native dataset: executor-side, LAZY transform
            # returning a typed DataFrame — rows never transit the
            # driver (reference: pipeline.py:460-489 mapPartitions +
            # TFModel.scala:294-335 schema derivation)
            return self._transform_native(engine, dataset, args)

        partitions = _to_partitions(
            dataset, num_partitions or engine.num_executors
        )
        builder = self.predictor_builder

        def _mapfn(iterator, _args=args, _builder=builder):
            return _run_model(list(iterator), _args, _builder)

        try:
            return engine.run_job(_mapfn, partitions, collect=True)
        finally:
            if owns_engine:
                engine.stop()

    def _transform_native(self, engine, dataset, args):
        """Distributed, lazy transform over an engine-native dataset.

        The reference transforms a DataFrame with
        ``df.rdd.mapPartitions(...)`` on the executors, lazily
        (reference: pipeline.py:460-489), and the Scala path derives
        the typed output schema from the model
        (reference: TFModel.scala:294-335).  Matching that contract:

        - rows NEVER transit the driver — the predictor loads (cached)
          in each executor process and the result is a lazily-evaluated
          DataFrame with the input's partitioning;
        - the output schema comes from, in priority order:
          ``args.output_schema`` (interchange list or struct string),
          the export's ``metadata.json`` ``output_schema`` key (write
          it at export time via ``save_for_serving(...,
          output_schema=serving.infer_output_schema(...))``), or — for
          LEGACY exports only — an executor-side one-row probe.  The
          probe is a ``take(1)``-scale job, but ``take(1)`` still
          evaluates the predictor over partition 0's first BATCH and
          discards the results before the real job re-runs it: for a
          generation predictor that is a full compiled decode paid
          twice, which is why metadata is the production path (a
          warning is logged when the probe fires).
        """
        import json as _json
        import os as _os

        from tensorflowonspark_tpu.data import spark_io

        builder = self.predictor_builder
        if _is_spark_dataframe(dataset):
            # ship only the predictor's input columns to the map — the
            # driver-side twin of the reference's
            # ``df.select(sorted(input_mapping))`` (pipeline.py:411-413)
            dataset = dataset.select(*sorted(args.input_mapping))

        def _mapfn(iterator, _args=args, _builder=builder):
            rows = (
                r.asDict(recursive=True) if hasattr(r, "asDict") else dict(r)
                for r in iterator
            )
            for out in _run_model_iter(rows, _args, _builder):
                yield out

        out_rdd = engine.map_partitions_native(_mapfn, dataset)

        schema = getattr(args, "output_schema", None)
        if not schema:
            meta_path = _os.path.join(args.export_dir, "metadata.json")
            if _os.path.exists(meta_path):
                with open(meta_path) as f:
                    schema = _json.load(f).get("output_schema")
        if not schema:
            logger.warning(
                "no output_schema in args or export metadata (%s): "
                "deriving it with a one-row probe job — this "
                "evaluates the predictor over partition 0's first "
                "batch TWICE (probe + real job).  Export with "
                "save_for_serving(..., output_schema=serving."
                "infer_output_schema(...)) to skip the probe.",
                args.export_dir,
            )
            probe = out_rdd.take(1)
            if not probe:
                raise ValueError(
                    "cannot derive an output schema from an empty "
                    "dataset; set args.output_schema or write "
                    "output_schema into the export metadata"
                )
            schema = [
                (name, _infer_output_type(probe[0][name]))
                for name in sorted(probe[0])
            ]
        if isinstance(schema, str):
            from tensorflowonspark_tpu.data import interchange

            schema = interchange.parse_schema(schema)
        schema = [tuple(f) for f in schema]
        spark_schema = spark_io.to_spark_schema(schema)
        cols = [name for name, _ in schema]

        def _to_row(out, _cols=tuple(cols)):
            return tuple(_py_value(out.get(c)) for c in _cols)

        spark = dataset.sparkSession if hasattr(
            dataset, "sparkSession"
        ) else None
        if spark is None:
            from pyspark.sql import SparkSession

            spark = SparkSession.builder.getOrCreate()
        return spark.createDataFrame(
            out_rdd.map(_to_row), schema=spark_schema
        )


#: Aliases matching the new framework's naming alongside reference parity
TPUEstimator = TFEstimator
TPUModel = TFModel
