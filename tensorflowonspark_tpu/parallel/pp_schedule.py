"""Pipeline schedules: GPipe vs 1F1B vs interleaved-1F1B, as explicit
per-tick tables.

No reference analogue (the reference has no pipeline parallelism,
SURVEY.md §2.3); schedules follow the standard literature (GPipe,
PipeDream-flush/1F1B, Megatron interleaved).

Two uses:

- *analysis*: :func:`simulate` produces the tick-by-tick table a
  synchronous SPMD execution follows; :func:`stats` reports idle ticks,
  bubble fraction, and peak in-flight microbatches (the activation-stash
  bound).  This is the "scheduled-ops trace" the pp tests assert on:
  1F1B's stash is O(P) instead of GPipe's O(M), and the interleaved
  variant has measurably fewer idle ticks;
- *execution*: :func:`stage_program` flattens the table into the static
  per-tick (do_fwd, fwd_mb, do_bwd, bwd_mb) arrays the hand-scheduled
  1F1B train step in :mod:`tensorflowonspark_tpu.parallel.pp` scans
  over.

Timing model: unit time per microbatch per stage for forward, one unit
for backward (tf = tb = 1) — the conventional model for bubble-fraction
accounting.  A tick is one unit; a stage executes at most one unit per
tick.
"""

import collections

__all__ = ["simulate", "stats", "stage_program", "analyze_program"]


#: one scheduled unit: kind is "F" or "B", mb the microbatch index,
#: chunk the virtual-stage chunk (0 unless interleaved)
Unit = collections.namedtuple("Unit", ["kind", "mb", "chunk"])


def simulate(num_stages, num_microbatches, schedule="1f1b", interleave=1):
    """Tick-by-tick schedule table.

    Args:
      num_stages: pipeline devices P.
      num_microbatches: microbatches M per step.
      schedule: ``"gpipe"`` (all forwards, flush, all backwards) or
        ``"1f1b"`` (PipeDream-flush: warmup, steady 1F1B, drain).
      interleave: virtual chunks per device v (Megatron interleaved
        schedule); model depth splits into P*v chunks, device d owns
        chunks ``d, d+P, ...``.  Only meaningful with ``"1f1b"``.

    Returns:
      ``table[d][t]`` — a :class:`Unit` or ``None`` (idle) for device
      ``d`` at tick ``t``; all rows share one length (the makespan).
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError("unknown schedule {0!r}".format(schedule))
    if schedule == "gpipe" and interleave != 1:
        raise ValueError("gpipe does not interleave")
    p, m, v = num_stages, num_microbatches, interleave
    num_chunks = p * v  # logical stages
    # chunk c runs on device c % p; chunk order is c=0..num_chunks-1
    done_f = set()  # (chunk, mb) forward completed
    done_b = set()
    # completion tick of each unit, for dependency latency (unit latency
    # 1, transfer latency 0 — the ICI permute overlaps the next tick)
    finish = {}

    def f_ready(c, mb, t):
        if c == 0:
            return True
        return ("F", c - 1, mb) in finish and finish[("F", c - 1, mb)] <= t

    def b_ready(c, mb, t):
        if ("F", c, mb) not in finish or finish[("F", c, mb)] > t:
            return False  # cannot run backward before own forward
        if c == num_chunks - 1:
            return True
        return ("B", c + 1, mb) in finish and finish[("B", c + 1, mb)] <= t

    table = [[] for _ in range(p)]
    t = 0
    total_units = 2 * num_chunks * m
    scheduled = 0
    while scheduled < total_units:
        if t > 4 * total_units + 16:  # safety: schedule must terminate
            raise RuntimeError("schedule failed to converge")
        for d in range(p):
            unit = _pick(
                d, p, m, v, num_chunks, schedule, done_f, done_b,
                f_ready, b_ready, t,
            )
            table[d].append(unit)
            if unit is not None:
                key = (unit.kind, _abs_chunk(unit, d, p), unit.mb)
                finish[key] = t + 1
                (done_f if unit.kind == "F" else done_b).add(key[1:])
                scheduled += 1
        t += 1
    return table


def _abs_chunk(unit, device, p):
    return unit.chunk * p + device


def _unit_orders(p, m, v):
    """Fixed per-device unit orders (Megatron's chunk-cycling pattern):
    forwards cycle chunks per group of ``p`` microbatches; backwards
    mirror with the chunk order reversed.  v==1 degenerates to plain
    microbatch order."""
    groups = []
    mb = 0
    while mb < m:
        groups.append(range(mb, min(mb + p, m)))
        mb += p
    fwd = [
        (c_i, mb) for g in groups for c_i in range(v) for mb in g
    ]
    bwd = [
        (c_i, mb)
        for g in groups
        for c_i in reversed(range(v))
        for mb in g
    ]
    return fwd, bwd


def _pick(d, p, m, v, num_chunks, schedule, done_f, done_b, f_ready,
          b_ready, t):
    """Choose device ``d``'s unit for tick ``t`` (or None).  Units
    execute strictly in the fixed order — out-of-order running would
    either deadlock the interleaved schedule or (for 1F1B) inflate the
    activation stash past its O(P) bound."""
    my_chunks = [c * p + d for c in range(v)]
    fwd_order, bwd_order = _unit_orders(p, m, v)
    fwd_done = sum((c, mb) in done_f for c in my_chunks for mb in range(m))
    bwd_done = sum((c, mb) in done_b for c in my_chunks for mb in range(m))

    def next_f():
        for c_i, mb in fwd_order:
            c = my_chunks[c_i]
            if (c, mb) in done_f:
                continue
            if f_ready(c, mb, t):
                return Unit("F", mb, c_i)
            return None  # strictly in-order

    def next_b():
        for c_i, mb in bwd_order:
            c = my_chunks[c_i]
            if (c, mb) in done_b:
                continue
            if (c, mb) in done_f and b_ready(c, mb, t):
                return Unit("B", mb, c_i)
            return None  # strictly in-order

    if schedule == "gpipe":
        # strict phases: all forwards first, then all backwards
        if fwd_done < v * m:
            return next_f()
        return next_b()

    # 1f1b: cap in-flight forwards at the warmup depth, prefer backward
    # once the cap is reached (PipeDream-flush)
    in_flight = fwd_done - bwd_done
    # v==1: stage d holds at most p-d in-flight (classic 1F1B);
    # interleaved: Megatron's warmup count 2(p-d-1) + (v-1)p, +1 for
    # the steady-state forward in flight
    warmup_cap = 2 * (p - d - 1) + (v - 1) * p + 1 if v > 1 else (p - d)
    if in_flight >= warmup_cap or fwd_done >= v * m:
        unit = next_b()
        if unit is not None:
            return unit
        # backward blocked on a not-yet-run LATER-chunk forward: that
        # forward must proceed or the schedule deadlocks (the cap
        # still bounds the stash at warmup_cap + v - 1)
        return next_f() if v > 1 else None
    unit = next_f()
    if unit is not None:
        return unit
    return next_b()


def stats(table, unit_time=1.0):
    """Schedule metrics: makespan, per-device idle time, bubble
    fraction, and peak in-flight microbatches (= activation-stash slots
    a real execution needs).

    Args:
      unit_time: wall time of one scheduled unit.  For an interleaved
        schedule at FIXED model depth each chunk is ``1/v`` of the
        model, so pass ``1/v`` to compare wall-clock against a
        non-interleaved schedule of the same model.
    """
    p = len(table)
    makespan = len(table[0]) * unit_time
    idle = [
        sum(1 for u in row if u is None) * unit_time for row in table
    ]
    busy = [makespan - i for i in idle]
    bubble = sum(idle) / float(p * makespan)
    peak = []
    for row in table:
        live = 0
        worst = 0
        for u in row:
            if u is None:
                continue
            live += 1 if u.kind == "F" else -1
            worst = max(worst, live)
        peak.append(worst)
    return {
        "makespan": makespan,
        "idle_ticks": idle,
        "busy_ticks": busy,
        "bubble_fraction": round(bubble, 4),
        "peak_in_flight": peak,
    }


def stage_program(num_stages, num_microbatches, schedule="1f1b",
                  interleave=1):
    """Flatten a schedule into per-tick static arrays for the SPMD
    execution in pp.py.

    Returns dict of numpy int arrays, each ``[T, P]``:
      ``do_f``/``f_mb`` — whether/which microbatch device d forwards at
      tick t; ``do_b``/``b_mb`` — same for backward; ``f_chunk`` /
      ``b_chunk`` — the device-local virtual-stage chunk of each unit
      (all zero unless ``interleave > 1``).
    """
    import numpy as np

    table = simulate(
        num_stages, num_microbatches, schedule, interleave=interleave
    )
    p = num_stages
    t_len = len(table[0])
    do_f = np.zeros((t_len, p), np.int32)
    f_mb = np.zeros((t_len, p), np.int32)
    f_chunk = np.zeros((t_len, p), np.int32)
    do_b = np.zeros((t_len, p), np.int32)
    b_mb = np.zeros((t_len, p), np.int32)
    b_chunk = np.zeros((t_len, p), np.int32)
    for d in range(p):
        for t, u in enumerate(table[d]):
            if u is None:
                continue
            if u.kind == "F":
                do_f[t, d] = 1
                f_mb[t, d] = u.mb
                f_chunk[t, d] = u.chunk
            else:
                do_b[t, d] = 1
                b_mb[t, d] = u.mb
                b_chunk[t, d] = u.chunk
    return {
        "do_f": do_f, "f_mb": f_mb, "f_chunk": f_chunk,
        "do_b": do_b, "b_mb": b_mb, "b_chunk": b_chunk,
    }


def _handoff_depth_ok(table, p, v, kind, depth):
    """Check a handoff-buffer geometry of ``depth`` slots per (device,
    chunk), indexed ``mb % depth``, against the executor's timing: a
    unit consumes its incoming slot at the START of its tick; a
    producer's send LANDS at the end of its tick (a ppermute result is
    visible the next tick)."""
    num_chunks = p * v
    buf = {}  # (device, chunk, mb % depth) -> mb pending
    for t in range(len(table[0])):
        for d in range(p):
            u = table[d][t]
            if u is None or u.kind != kind:
                continue
            a = u.chunk * p + d
            edge = 0 if kind == "F" else num_chunks - 1
            if a != edge:  # chunk 0 injects / last chunk owns the loss
                key = (d, u.chunk, u.mb % depth)
                if buf.get(key) != u.mb:
                    return False
                del buf[key]
        for d in range(p):
            u = table[d][t]
            if u is None or u.kind != kind:
                continue
            a = u.chunk * p + d
            if kind == "F" and a != num_chunks - 1:
                key = ((a + 1) % p, (a + 1) // p, u.mb % depth)
            elif kind == "B" and a != 0:
                key = ((a - 1) % p, (a - 1) // p, u.mb % depth)
            else:
                continue
            if key in buf:
                return False  # overwrite of an unconsumed slot
            buf[key] = u.mb
    return not buf  # everything produced was consumed


def analyze_program(table, num_stages, interleave=1):
    """Static safety analysis of a (possibly interleaved) 1F1B table
    for the SPMD executor's buffer geometry.

    Returns ``{"stash_slots", "fwd_slots", "bwd_slots"}`` — the minimal
    per-chunk depths for the activation stash and the two ppermute
    handoff buffers (all modularly indexed by microbatch).  Classic
    1F1B (v=1) needs single-slot handoffs; the interleaved schedule's
    chunk cycling keeps up to two forwards of one chunk in flight.
    Raises ``RuntimeError`` when no depth works (a schedule bug, not a
    user error).
    """
    p, v = num_stages, interleave
    t_len = len(table[0])
    m = 1 + max(
        (u.mb for row in table for u in row if u is not None), default=0
    )

    def min_depth(kind):
        for depth in range(1, m + 1):
            if _handoff_depth_ok(table, p, v, kind, depth):
                return depth
        raise RuntimeError(
            "no {0}-handoff depth <= {1} microbatches works for this "
            "schedule".format(kind, m)
        )

    # stash occupancy: F stashes its input, B releases it
    alive = collections.defaultdict(set)  # (device, chunk) -> live mbs
    snapshots = []
    for t in range(t_len):
        for d in range(p):
            u = table[d][t]
            if u is None:
                continue
            key = (d, u.chunk)
            if u.kind == "F":
                alive[key].add(u.mb)
            else:
                alive[key].discard(u.mb)
            snapshots.append(frozenset(alive[key]))
    if any(alive.values()):
        raise RuntimeError("schedule left stashed activations unconsumed")
    max_alive = max((len(s) for s in snapshots), default=1)
    stash = m
    for slots in range(max(1, max_alive), m + 1):
        if all(
            len({mb % slots for mb in s}) == len(s) for s in snapshots
        ):
            stash = slots
            break
    return {
        "stash_slots": stash,
        "fwd_slots": min_depth("F"),
        "bwd_slots": min_depth("B"),
    }
