"""Synchronous data parallelism — the MultiWorkerMirroredStrategy
equivalent (reference behavior: user code built MWMS from the TF_CONFIG
the framework exported, reference: tensorflowonspark/TFSparkNode.py:354-362
and examples/mnist/keras/mnist_spark.py:11).

TPU-native design: one jitted train step over a named mesh.  The batch is
sharded over the data axes, parameters are placed per the strategy's rules
(replicated for DP, sharded for FSDP/TP), and XLA inserts the gradient
``psum`` over ICI — there is no hand-written allreduce.

Also solves the reference's uneven-partition problem ("90% of steps"
trick, reference: examples/mnist/keras/mnist_spark.py:58-65) with a
principled global stop: every host contributes a has-data flag each step
and the loop stops when ANY host is exhausted, so no host ever blocks in
a collective that its peers never enter (SURVEY.md §7 'Hard parts').
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np

from tensorflowonspark_tpu.parallel import sharding as sh
from tensorflowonspark_tpu.parallel.mesh import build_mesh

logger = logging.getLogger(__name__)


@jax.tree_util.register_pytree_node_class
class TrainState(object):
    """Minimal training state: ``(step, params, opt_state, model_state)``.

    A deliberate re-design of what the reference delegated to
    ``tf.train.Checkpoint``/Keras internals — a plain pytree that jit,
    donation, and orbax checkpointing all understand natively.
    ``model_state`` carries non-trained collections (BatchNorm running
    stats); ``{}`` for purely functional models.
    """

    def __init__(self, step, params, opt_state, model_state=None):
        self.step = step
        self.params = params
        self.opt_state = opt_state
        self.model_state = {} if model_state is None else model_state

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state, self.model_state), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def replace(self, **kw):
        return TrainState(
            kw.get("step", self.step),
            kw.get("params", self.params),
            kw.get("opt_state", self.opt_state),
            kw.get("model_state", self.model_state),
        )


class SyncTrainer(object):
    """Builds and runs the jitted synchronous train step.

    Args:
      loss_fn: ``loss_fn(params, batch, rng) -> loss`` or
        ``-> (loss, aux_dict)`` (with ``has_aux=True``); with
        ``has_model_state=True`` the signature becomes
        ``loss_fn(params, model_state, batch, rng) ->
        (loss, (aux_dict, new_model_state))`` — the BatchNorm contract.
      optimizer: an optax ``GradientTransformation``.
      mesh: a mesh from :func:`build_mesh` (default: all devices on
        ``data``).
      rules: logical→mesh sharding rules (default DP: params replicated).
      annotations: optional logical-axis pytree for the params (see
        :func:`tensorflowonspark_tpu.parallel.sharding.param_specs`).
      device_preprocess: optional on-device batch preprocess — a
        callable ``fn(batch)`` / ``fn(batch, rng)`` or a
        :func:`~tensorflowonspark_tpu.data.preprocess.make_preprocess`
        kwargs dict — traced INTO the jitted train step (and the fused
        multi-step scan body), so narrow wire dtypes (uint8 pixels)
        cross host→HBM narrow and widen in HBM (docs/data_plane.md).
        An rng-taking preprocess (random flip/crop) gets a key split
        from the step rng.  Numerics parity with the host-side float
        path is tested in tests/test_preprocess.py.
    """

    def __init__(
        self,
        loss_fn,
        optimizer,
        mesh=None,
        rules=sh.RULES_DP,
        annotations=None,
        has_aux=False,
        has_model_state=False,
        data_axes=("data", "fsdp"),
        device_preprocess=None,
    ):
        from tensorflowonspark_tpu.data import preprocess as pp_mod

        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh if mesh is not None else build_mesh()
        self.rules = rules
        self.annotations = annotations
        self.has_aux = has_aux
        self.has_model_state = has_model_state
        self.data_axes = data_axes
        self.device_preprocess = pp_mod.resolve_preprocess(
            device_preprocess
        )
        self._pre_takes_rng = (
            self.device_preprocess is not None
            and pp_mod.takes_rng(self.device_preprocess)
        )
        self._step_fn = self._build_step()
        self._eval_fn = None
        self._multi_fn = None

    # -- state ---------------------------------------------------------

    def create_state(self, params, model_state=None):
        """Shard params per the rules and build the optimizer state with
        matching sharding (optax states mirror the param tree)."""
        params = sh.shard_params(params, self.rules, self.mesh, self.annotations)
        opt_state = jax.jit(self.optimizer.init)(params)
        opt_state = sh.canonicalize_on_mesh(opt_state, self.mesh)
        step = jax.device_put(jnp.zeros((), jnp.int32), sh.replicated(self.mesh))
        if model_state is not None:
            model_state = jax.tree.map(
                lambda x: jax.device_put(x, sh.replicated(self.mesh)),
                model_state,
            )
        return TrainState(step, params, opt_state, model_state)

    # -- steps ---------------------------------------------------------

    def _build_step(self):
        loss_fn, optimizer = self.loss_fn, self.optimizer
        has_aux, has_model_state = self.has_aux, self.has_model_state
        pre, pre_rng = self.device_preprocess, self._pre_takes_rng

        def train_step(state, batch, rng):
            # on-device preprocess, fused in front of the step: the
            # narrow-dtype batch widens in HBM, not on the host.  An
            # rng-bearing preprocess (augmentation) consumes a split of
            # the step key — the loss rng chain changes ONLY when such
            # a preprocess is installed.
            if pre is not None:
                if pre_rng:
                    rng, k = jax.random.split(rng)
                    batch = pre(batch, k)
                else:
                    batch = pre(batch)

            def _loss(p):
                if has_model_state:
                    return loss_fn(p, state.model_state, batch, rng)
                out = loss_fn(p, batch, rng)
                if has_aux:
                    return out
                return out, {}

            (loss, aux), grads = jax.value_and_grad(_loss, has_aux=True)(
                state.params
            )
            if has_model_state:
                metrics, model_state = aux
                metrics = dict(metrics)
            else:
                metrics, model_state = dict(aux), state.model_state
            updates, opt_state = optimizer.update(
                grads, state.opt_state, state.params
            )
            import optax

            params = optax.apply_updates(state.params, updates)
            metrics["loss"] = loss
            return (
                TrainState(state.step + 1, params, opt_state, model_state),
                metrics,
            )

        # Input shardings come from the committed inputs (state placed by
        # create_state, batch by shard_batch); donation recycles the old
        # state's HBM.
        return jax.jit(train_step, donate_argnums=(0,))

    def step(self, state, batch, rng=None):
        """One synchronous step; ``batch`` is a host-local pytree of
        arrays that gets sharded over the data axes."""
        if rng is None:
            rng = jax.random.PRNGKey(0)
        device_batch = sh.shard_batch(batch, self.mesh, self.data_axes)
        return self._step_fn(state, device_batch, rng)

    def multi_step(self, state, stacked_batch, rngs):
        """Run K fused steps in ONE dispatch (`lax.scan` over the
        leading axis) — the steps-per-execution technique: host→device
        round trips amortize K×, which dominates when per-step compute
        is a few ms (ResNet/CIFAR-class models).

        Args:
          stacked_batch: pytree with a leading ``[K, ...]`` axis over
            per-step batches (host arrays; sharded here).
          rngs: ``[K, 2]`` stacked PRNG keys.
        Returns ``(state, metrics)`` with metrics stacked ``[K]``.
        """
        device_batch = sh.shard_batch(
            stacked_batch, self.mesh, self.data_axes, leading_dims=1
        )
        return self.multi_step_on_device(state, device_batch, rngs)

    def multi_step_on_device(self, state, device_stacked, rngs):
        """K fused steps on an already device-resident ``[K, ...]``
        stack (the primitive :meth:`multi_step` calls after placing the
        host batch; place yours once with
        :func:`~tensorflowonspark_tpu.parallel.sharding.shard_batch`
        at ``leading_dims=1``).  The benchmarking/high-throughput path:
        no host→device transfer inside the loop."""
        if self._multi_fn is None:
            step_fn = self._step_fn

            def multi(state, batches, rngs):
                def body(s, xs):
                    b, r = xs
                    return step_fn(s, b, r)

                return jax.lax.scan(body, state, (batches, rngs))

            self._multi_fn = jax.jit(multi, donate_argnums=(0,))
        return self._multi_fn(state, device_stacked, rngs)

    def step_on_device(self, state, device_batch, rng):
        """One step on an already device-resident (sharded) batch.

        Pair with :func:`tensorflowonspark_tpu.data.feed.prefetch_to_device`
        (give it :meth:`batch_sharding`) so batch N+1's host→HBM DMA
        overlaps batch N's compute.  When per-step *dispatch* dominates
        (small/fast models), prefer :meth:`multi_step`, which amortizes
        it K× — the structure bench.py uses."""
        return self._step_fn(state, device_batch, rng)

    def batch_sharding(self):
        """The sharding a host batch should be placed with for
        :meth:`step_on_device` (give it to ``prefetch_to_device``)."""
        return sh.batch_sharding(self.mesh, self.data_axes)

    def eval_step(self, state, batch, apply_fn):
        """Jitted forward pass for evaluation/prediction."""
        if self._eval_fn is None:
            self._eval_fn = jax.jit(lambda p, b: apply_fn(p, b))
        device_batch = sh.shard_batch(batch, self.mesh, self.data_axes)
        return self._eval_fn(state.params, device_batch)

    # -- feed-driven training (InputMode.SPARK) ------------------------

    def train_on_feed(
        self,
        state,
        feed,
        batch_size,
        preprocess=None,
        rng=None,
        max_steps=None,
        log_every=100,
        steps_per_execution=1,
        metrics_callback=None,
        columnar=False,
        terminate_on_max_steps=True,
        checkpointer=None,
        checkpoint_every=0,
        step_callback=None,
    ):
        """Run the synchronized feed loop: pull batches from a
        :class:`~tensorflowonspark_tpu.data.feed.DataFeed`, stop globally
        when any host runs dry (see module docstring).

        Args:
          preprocess: ``fn(batch) -> batch pytree``.  In row mode
            ``batch`` is the list of rows; in columnar mode it is the
            stacked-columns pytree from ``feed.next_arrays``.
          steps_per_execution: fuse up to this many steps into one
            :meth:`multi_step` dispatch (per-batch readiness stays
            globally agreed, so every host fuses the same count; a
            partial final group may compile a second program).
          metrics_callback: optional ``fn(step, metrics)`` called after
            each executed group with the (device-resident) metrics of
            its last step — losses are global (psum over the mesh), so
            every host observes identical values.
          columnar: consume via ``feed.next_arrays`` (zero per-row
            Python, ~4x the row path's throughput; requires fixed-shape
            homogeneous numeric rows — ``next_arrays`` raises on object
            rows).  Default False: the row path accepts anything, so
            opting in is an explicit contract with your data.
          terminate_on_max_steps: when the step cap ends training with
            data still in flight, terminate the feed (drain + mark the
            node 'terminating' — the reference's StopFeedHook contract)
            so the feeder's ``queue.join()`` doesn't block until
            feed_timeout.  Pass False for incremental training that
            resumes consuming from the same feed.
          checkpointer: a :class:`~tensorflowonspark_tpu.checkpoint.Checkpointer`
            — THE fault-tolerance resume hook.  At entry, if it holds a
            checkpoint, ``state`` is replaced by the restored latest
            step (so a supervised restart auto-resumes — user code does
            not branch on ``ctx.generation``); every ``checkpoint_every``
            steps and at exit the state is saved durably
            (``wait=True``) and the feed's delivered partitions are
            promoted to committed (``feed.commit_partitions``), fencing
            them from elastic requeue.  See docs/fault_tolerance.md.
          checkpoint_every: step spacing of periodic saves (0 = only the
            final save).
          step_callback: optional ``fn(step)`` called before each
            executed group — the chaos harness's deterministic
            kill-at-step injection point
            (:func:`tensorflowonspark_tpu.testing.chaos.step_fault_fn`).
        Returns the final state.
        """
        if steps_per_execution < 1:
            raise ValueError(
                "steps_per_execution must be >= 1, got {0}".format(
                    steps_per_execution
                )
            )
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        columnar = bool(columnar)
        steps = 0
        if checkpointer is not None and checkpointer.latest_step() is not None:
            state = checkpointer.restore(state)
            # tfoslint: disable=TFOS002(one-time checkpoint-resume sync BEFORE the hot loop starts)
            steps = int(jax.device_get(state.step))
            logger.info("resumed from checkpoint at step %d", steps)
        # fleet telemetry: the training-step trace (feed_wait → h2d →
        # dispatch; the PS legs trace inside PSClient/_GradDrain) plus
        # the step/feed-wait histograms — null-object no-ops when
        # TFOS_TELEMETRY=0 (docs/observability.md)
        from tensorflowonspark_tpu import telemetry

        tracer = telemetry.get_tracer()
        reg = telemetry.get_registry()
        m_steps = reg.counter("train.steps")
        m_step_hist = reg.histogram("train.step_sec")
        m_feed_hist = reg.histogram("train.feed_wait_sec")
        # phase twins of the h2d/dispatch spans: the health plane's
        # straggler detector attributes a slow node to its dominant
        # phase from these per-executor series (telemetry/health.py
        # PHASE_METRICS)
        m_h2d_hist = reg.histogram("train.h2d_sec")
        m_disp_hist = reg.histogram("train.dispatch_sec")
        import time as _time

        stop = False
        while not stop:
            if max_steps is not None and steps >= max_steps:
                break
            limit = steps_per_execution
            if max_steps is not None:
                limit = min(limit, max_steps - steps)
            t_feed0 = _time.perf_counter()
            group, stop = collect_ready_group(
                feed, batch_size, limit, columnar=columnar,
                preprocess=preprocess,
            )
            if stop:
                logger.info("global stop after %d steps", steps)
            subs = []
            for _ in group:
                rng, sub = jax.random.split(rng)
                subs.append(sub)
            if not group:
                break
            feed_wait = _time.perf_counter() - t_feed0
            m_feed_hist.observe(feed_wait)
            tracer.add(
                "feed_wait", t_feed0, feed_wait,
                trace="step%d" % steps, batches=len(group),
            )
            if step_callback is not None:
                step_callback(steps)
            t_step0 = _time.perf_counter()
            trace_id = "step%d" % steps
            if len(group) == 1:
                t_h2d = _time.perf_counter()
                with tracer.span("h2d", trace=trace_id):
                    device_batch = sh.shard_batch(
                        group[0], self.mesh, self.data_axes
                    )
                t_disp = _time.perf_counter()
                m_h2d_hist.observe(t_disp - t_h2d)
                with tracer.span("dispatch", trace=trace_id):
                    state, metrics = self.step_on_device(
                        state, device_batch, subs[0]
                    )
                m_disp_hist.observe(_time.perf_counter() - t_disp)
            else:
                stacked = jax.tree.map(lambda *xs: np.stack(xs), *group)
                t_h2d = _time.perf_counter()
                with tracer.span("h2d", trace=trace_id):
                    device_stacked = sh.shard_batch(
                        stacked, self.mesh, self.data_axes, leading_dims=1
                    )
                t_disp = _time.perf_counter()
                m_h2d_hist.observe(t_disp - t_h2d)
                with tracer.span("dispatch", trace=trace_id):
                    state, metrics = self.multi_step_on_device(
                        state, device_stacked, jnp.stack(subs)
                    )
                m_disp_hist.observe(_time.perf_counter() - t_disp)
                metrics = jax.tree.map(lambda m: m[-1], metrics)
            m_step_hist.observe(
                (_time.perf_counter() - t_step0) / len(group)
            )
            m_steps.inc(len(group))
            steps += len(group)
            # feed the env-var-driven jax.profiler capture, if one is
            # live in this process (tensorboard.start_profile)
            from tensorflowonspark_tpu import tensorboard as _tb

            _tb.profile_step(len(group))
            if metrics_callback is not None:
                metrics_callback(steps, metrics)
            if (
                checkpointer is not None
                and checkpoint_every
                and steps % checkpoint_every < len(group)
            ):
                # durable BEFORE commit: a committed partition must
                # never be lost to a crash between the two
                checkpointer.save(steps, state, wait=True)
                feed.commit_partitions()
            if log_every and (steps % log_every < len(group)):
                logger.info(
                    "step %d loss %.4f", steps, float(metrics["loss"])
                )
        if (
            terminate_on_max_steps
            and max_steps is not None
            and steps >= max_steps
            and not feed.should_stop()
        ):
            # A step cap ended training with data still in flight: the
            # feeder would block on queue.join() until feed_timeout.
            # Terminate the feed — drain leftovers, mark the node
            # 'terminating' so later feed tasks skip (the reference's
            # StopFeedHook contract, reference:
            # examples/mnist/estimator/mnist_spark.py:16-24).
            logger.info("max_steps reached; terminating the feed")
            feed.terminate()
        if checkpointer is not None and checkpointer.latest_step() != steps:
            # final durable save (skipped when a resumed run made no
            # progress — that step already exists on disk)
            checkpointer.save(steps, state, wait=True)
            feed.commit_partitions()
        return state


def collect_ready_group(feed, batch_size, limit, columnar=False,
                        preprocess=None):
    """Collect up to ``limit`` globally-ready batches from a feed.

    The per-batch all-hosts barrier keeps the collected count identical
    on every host, so no straggler enters a collective alone (a batch a
    ready host pulled in the failing round is dropped — the same data
    the reference's '90% of steps' trick dropped).  Shared by
    :meth:`SyncTrainer.train_on_feed` and the hierarchical plane's
    :meth:`~tensorflowonspark_tpu.parallel.hier_ps.HierTrainer.
    train_on_feed` — both tiers stop on the same global agreement.

    Returns ``(group, stopped)``: the ready batches (preprocessed /
    default-stacked) and whether the global stop fired.
    """
    group = []
    stopped = False
    for _ in range(limit):
        if columnar:
            batch, n = feed.next_arrays(batch_size)
            have = n == batch_size and not feed.should_stop()
        else:
            rows = feed.next_batch(batch_size)
            have = (
                bool(rows)
                and len(rows) == batch_size
                and not feed.should_stop()
            )
        if not all_hosts_ready(have):
            if have:
                logger.info("dropping one ready batch at global stop")
            stopped = True
            break
        if columnar:
            group.append(preprocess(batch) if preprocess else batch)
        else:
            group.append(
                preprocess(rows) if preprocess else _default_batch(rows)
            )
    return group, stopped


def _default_batch(rows):
    first = rows[0]
    if isinstance(first, dict):
        return {k: np.asarray([r[k] for r in rows]) for k in first}
    if isinstance(first, (tuple, list)):
        cols = list(zip(*rows))
        return tuple(np.asarray(c) for c in cols)
    return np.asarray(rows)


def all_hosts_ready(local_flag):
    """AND-reduce a boolean across all JAX processes.

    The global-stop primitive: single-process clusters short-circuit;
    multi-host clusters allgather a tiny uint8 over DCN (cost is
    microseconds against a training step).
    """
    if jax.process_count() == 1:
        return bool(local_flag)
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray([1 if local_flag else 0], dtype=np.uint8)
    )
    # tfoslint: disable=TFOS002(the global-stop allgather IS a sync point by contract; microseconds against a step)
    return bool(np.all(flags))
