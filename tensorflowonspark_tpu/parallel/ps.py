"""Asynchronous parameter-server data parallelism.

The reference's async-DP mode delegated everything to TensorFlow's PS
runtime: ``num_ps`` executors ran ``tf.train.Server`` processes that the
framework kept pinned via a control-queue block, with
``ParameterServerStrategy`` in user code (reference:
TFSparkNode.py:409-426, TFCluster.py:186-194,
examples/mnist/estimator/mnist_spark_streaming.py:88).  TPUs have no
native PS runtime, so this module *is* the PS system (SURVEY.md §7
'Hard parts: Async PS on TPU'):

- **ParamServerShard** — a TCP service holding a shard of the model's
  leaves in host memory, applying updates with its own numpy optimizer
  (the parameter-host-over-DCN design: PS traffic rides the data-center
  network while each worker's compute stays on its chips).
- **PSClient** — worker-side: partitions a params pytree across shards
  (size-balanced), then ``push_pull(grads)`` ships gradients and
  returns fresh params in one round trip per shard (DistBelief-style
  async SGD; no barrier between workers, stale gradients by design).
- **run_server(ctx)** — what a ps-role node runs inside ``main_fun``
  (the ``server.join()`` analogue, reference: TFNode.py:120-129): binds
  the clusterspec's ps address and serves until STOP/teardown.
- **AsyncTrainer** — worker-side convenience wrapping grad computation
  (jit on the local chips) + push_pull.

Wire protocol: 4-byte BE header length + JSON header + raw tensor
bytes (no pickle — same hardening rationale as
:mod:`tensorflowonspark_tpu.cluster.reservation`).  Optimizers are
named specs (``("adam", {"learning_rate": 1e-3})``) resolved against
the server's own numpy implementations, never deserialized code.
Leafwise optimizers only (sgd/momentum/adagrad/adam): each shard
updates its leaves independently, which is exact for these rules.

Gradient-plane extensions (docs/communication.md):

- **Codecs** — a tensor entry may carry a ``codec`` name plus per-part
  metadata; payloads are the codec's encoded parts
  (:mod:`tensorflowonspark_tpu.compress`: int8 quantization, top-k
  sparsification) and ``recv_msg`` decodes back to dense arrays.  The
  client compresses gradient pushes (with error feedback); the server,
  once a connection negotiates a reply codec via the ``codec`` op,
  compresses push/pull replies as **deltas** against that connection's
  tracked client view instead of shipping ``dict(self._params)`` dense.
- **Zero-copy sends** — frames go out via ``socket.sendmsg``
  scatter-gather over memoryviews of the C-contiguous payloads; no
  ``tobytes()``/``b"".join`` materialization of the concatenated frame.
"""

import json
import logging
import socket
import struct
import threading
import time

import numpy as np

from tensorflowonspark_tpu import compress as compress_mod

logger = logging.getLogger(__name__)

_MAX_HEADER = 16 * 1024 * 1024


# ----------------------------------------------------------------------
# framing: JSON header + raw tensor payloads
# ----------------------------------------------------------------------


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


#: sendmsg iovec batch bound (Linux IOV_MAX is 1024; stay well under)
_IOV_MAX = 512


def _sendmsg_all(sock, views):
    """Scatter-gather send of a list of memoryviews; returns total
    bytes.  The zero-copy wire path: payload arrays are handed to the
    kernel in place instead of being concatenated into one big
    ``bytes`` (the old path copied every tensor per message).  Falls
    back to ``sendall`` where ``sendmsg`` is unavailable."""
    total = sum(v.nbytes for v in views)
    if not hasattr(sock, "sendmsg"):
        sock.sendall(b"".join(views))
        return total
    pending = [v for v in views if v.nbytes]
    while pending:
        sent = sock.sendmsg(pending[:_IOV_MAX])
        while sent > 0 and pending:
            v = pending[0]
            if sent >= v.nbytes:
                sent -= v.nbytes
                pending.pop(0)
            else:
                pending[0] = v[sent:]
                sent = 0
    return total


def _part_meta(p):
    # dtype_str, not .str: extension dtypes (bfloat16) stringify as an
    # opaque void that np.dtype() resolves to raw bytes
    return {"dtype": compress_mod.dtype_str(p.dtype),
            "shape": list(p.shape), "nbytes": int(p.nbytes)}


def _payload_view(p):
    """Byte view of a contiguous payload array.  Extension dtypes
    (bfloat16) refuse buffer export under their own format code, so
    fall back to a zero-copy uint8 reinterpret of the same memory."""
    try:
        return memoryview(p).cast("B")
    except (ValueError, TypeError):
        return memoryview(p.reshape(-1).view(np.uint8))


def _send_frame(sock, header, entries):
    """Lay one frame on the socket: ``entries`` is a list of
    ``(tensor_meta, [payload arrays])``; returns bytes sent."""
    meta = []
    payloads = []
    for m, parts in entries:
        parts = [np.ascontiguousarray(p) for p in parts]
        if m.get("codec"):
            m = dict(m, parts=[_part_meta(p) for p in parts])
        meta.append(m)
        payloads.extend(parts)
    hb = json.dumps(dict(header, tensors=meta)).encode("utf-8")
    views = [memoryview(struct.pack(">I", len(hb))), memoryview(hb)]
    views.extend(_payload_view(p) for p in payloads)
    return _sendmsg_all(sock, views)


def send_msg(sock, header, tensors=None, codec=None):
    """Send ``header`` (JSON-able dict) plus named numpy ``tensors``.

    With ``codec`` (a :class:`~tensorflowonspark_tpu.compress.Codec` or
    :class:`~tensorflowonspark_tpu.compress.ErrorFeedback`), each
    tensor ships as the codec's encoded parts and the per-tensor meta
    gains the codec header ``recv_msg`` decodes by.  Returns the total
    bytes laid on the wire (header + payloads) — the tunnel-traffic
    accounting the wire tests and bench rows use.
    """
    tensors = tensors or {}
    entries = []
    for name, arr in tensors.items():
        if codec is not None and not isinstance(codec, compress_mod.NoneCodec):
            if hasattr(codec, "encode_named"):  # error-feedback wrapper
                parts, cmeta = codec.encode_named(name, arr)
            else:
                parts, cmeta = codec.encode(np.asarray(arr))
            entries.append(
                ({"name": name, "codec": codec.name, "meta": cmeta}, parts)
            )
        else:
            arr = np.ascontiguousarray(arr)
            entries.append((dict(_part_meta(arr), name=name), [arr]))
    return _send_frame(sock, header, entries)


def _recv_part(sock, m):
    """Receive one payload described by part-meta ``m``; malformed meta
    (nbytes disagreeing with dtype x shape — a corrupt or hostile
    frame) is rejected as ConnectionError before any allocation, the
    same posture as the tfrecord codec's corruption checks."""
    try:
        dtype = compress_mod.resolve_dtype(m["dtype"])
        shape = tuple(int(s) for s in m["shape"])
        nbytes = int(m["nbytes"])
    except (KeyError, TypeError, ValueError) as e:
        raise ConnectionError("bad tensor meta: {0}".format(e))
    expect = int(dtype.itemsize * int(np.prod(shape, dtype=np.int64)))
    if nbytes != expect or nbytes < 0 or any(s < 0 for s in shape):
        raise ConnectionError(
            "tensor meta nbytes {0} inconsistent with dtype/shape "
            "({1} expected)".format(nbytes, expect)
        )
    raw = _recv_exact(sock, nbytes)
    return np.frombuffer(raw, dtype=dtype).reshape(shape)


def recv_msg(sock):
    """Receive one message → ``(header, {name: np.ndarray})``.

    Codec-carrying tensors are decoded to dense arrays here, so every
    consumer (the shard's ``update()``, the client's unshard) sees
    plain numpy regardless of what crossed the wire.  Undecodable or
    inconsistent frames raise ``ConnectionError``.

    The returned header carries ``_recv_nbytes`` — the exact wire
    bytes this frame occupied (length prefix + header + payloads), the
    receive-side twin of ``send_msg``'s return value; anything the
    peer put under that key is overwritten after parse.
    """
    (hlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    if hlen > _MAX_HEADER:
        raise ConnectionError("header length {0} exceeds limit".format(hlen))
    try:
        header = json.loads(_recv_exact(sock, hlen).decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise ConnectionError("undecodable frame header: {0}".format(e))
    if not isinstance(header, dict):
        raise ConnectionError("frame header is not an object")
    nbytes = 4 + hlen
    tensors = {}
    for m in header.get("tensors", ()):
        if m.get("codec"):
            codec = compress_mod.get_codec(str(m["codec"]))
            parts = [_recv_part(sock, pm) for pm in m.get("parts", ())]
            nbytes += sum(int(p.nbytes) for p in parts)
            try:
                tensors[m["name"]] = codec.decode(parts, m.get("meta") or {})
            except (KeyError, TypeError, ValueError, IndexError) as e:
                raise ConnectionError(
                    "codec {0} decode failed: {1}".format(m["codec"], e)
                )
        else:
            part = _recv_part(sock, m)
            nbytes += int(part.nbytes)
            tensors[m["name"]] = part
    header["_recv_nbytes"] = nbytes
    return header, tensors


# ----------------------------------------------------------------------
# server-side numpy optimizers (leafwise; no code deserialization)
# ----------------------------------------------------------------------


class _SGD(object):
    def __init__(self, learning_rate=0.01, momentum=0.0):
        self.lr = learning_rate
        self.momentum = momentum
        self._vel = {}

    def update(self, name, param, grad):
        if self.momentum:
            v = self._vel.get(name)
            v = grad if v is None else self.momentum * v + grad
            self._vel[name] = v
            grad = v
        return param - self.lr * grad


class _Adagrad(object):
    def __init__(self, learning_rate=0.01, eps=1e-10):
        self.lr = learning_rate
        self.eps = eps
        self._acc = {}

    def update(self, name, param, grad):
        acc = self._acc.get(name, np.zeros_like(param)) + grad * grad
        self._acc[name] = acc
        return param - self.lr * grad / (np.sqrt(acc) + self.eps)


class _Adam(object):
    def __init__(self, learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8):
        self.lr = learning_rate
        self.b1, self.b2, self.eps = b1, b2, eps
        self._m, self._v, self._t = {}, {}, {}

    def update(self, name, param, grad):
        t = self._t.get(name, 0) + 1
        m = self.b1 * self._m.get(name, np.zeros_like(param)) + (1 - self.b1) * grad
        v = self.b2 * self._v.get(name, np.zeros_like(param)) + (
            1 - self.b2
        ) * grad * grad
        self._m[name], self._v[name], self._t[name] = m, v, t
        mhat = m / (1 - self.b1**t)
        vhat = v / (1 - self.b2**t)
        return param - self.lr * mhat / (np.sqrt(vhat) + self.eps)


class _Delta(object):
    """Hierarchical-plane server rule: the pod leader ships parameter
    DELTAS (local progress since the last synced base, already the
    product of the pod's own on-device optimizer), and the server folds
    them straight in — ``param + scale * delta``.  ``scale`` < 1 damps
    the mixing when many pods push concurrently."""

    def __init__(self, scale=1.0):
        self.scale = scale

    def update(self, name, param, grad):
        return param + self.scale * grad


OPTIMIZERS = {"sgd": _SGD, "adagrad": _Adagrad, "adam": _Adam,
              "delta": _Delta}


def _build_optimizer(spec):
    name, kwargs = spec
    if name not in OPTIMIZERS:
        raise ValueError(
            "unknown PS optimizer {0!r}; supported: {1}".format(
                name, sorted(OPTIMIZERS)
            )
        )
    return OPTIMIZERS[name](**(kwargs or {}))


# ----------------------------------------------------------------------
# server
# ----------------------------------------------------------------------


class _ReplyCompressor(object):
    """Per-connection compressed-delta reply state.

    Once a connection negotiates a reply codec (the ``codec`` wire op),
    params replies stop shipping ``dict(self._params)`` dense: for each
    tensor the server tracks the *client view* — exactly what the
    client has reconstructed so far — and sends the lossy-encoded delta
    against it.  The view advances by the server's own decode of the
    encoded delta (bit-identical to the client's decode of the same
    bytes), so encoding error never drifts the two sides apart: any
    residual stays inside the next ``params - view`` delta — the
    downlink twin of client-side error feedback.

    First sight of a tensor name (or a shape change after an elastic
    restart) ships dense, establishing the base.
    """

    def __init__(self):
        self.codec = None
        self._view = {}

    def negotiate(self, spec):
        codec = compress_mod.get_codec(spec)
        if codec is not None and isinstance(codec, compress_mod.NoneCodec):
            codec = None
        self.codec = codec
        self._view.clear()

    def entries(self, tensors):
        """Frame entries for a params reply (see ``_send_frame``)."""
        entries = []
        for name, arr in tensors.items():
            arr = np.asarray(arr)
            view = self._view.get(name)
            if view is None or view.shape != arr.shape:
                self._view[name] = arr.astype(np.float32, copy=True)
                dense = np.ascontiguousarray(arr)
                entries.append((dict(_part_meta(dense), name=name), [dense]))
                continue
            delta = arr.astype(np.float32, copy=False) - view
            parts, meta = self.codec.encode(delta)
            approx = self.codec.decode(
                [p.copy() for p in parts], meta
            ).astype(np.float32, copy=False)
            self._view[name] = view + approx
            entries.append(
                (
                    {
                        "name": name,
                        "codec": self.codec.name,
                        "meta": meta,
                        "delta": True,
                        "pdtype": arr.dtype.str,
                    },
                    parts,
                )
            )
        return entries


class ParamServerShard(object):
    """One PS shard: parameter store + optimizer + TCP service.

    Thread-per-connection; updates serialized under a lock (each push is
    one atomic read-modify-write, the async-SGD consistency model).
    """

    def __init__(self):
        self._params = {}
        self._opt = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._sock = None
        self.addr = None
        #: hierarchical-plane window ledger: pod id -> last applied
        #: window sequence.  A push carrying ``pod``/``window`` header
        #: fields is applied AT MOST ONCE per (pod, window): a re-push
        #: after a leader failover (the new leader cannot know whether
        #: its predecessor's in-flight window landed) is answered
        #: idempotently with the live params instead of double-applying
        #: the gradient (tests/test_hier_ps.py asserts via applied_log).
        self.applied_windows = {}
        #: append-only (pod, window) apply log — test observability for
        #: the exactly-once contract; bounded by run length in tests.
        self.applied_log = []

    # -- ops -----------------------------------------------------------

    def _op_init(self, header, tensors):
        with self._lock:
            if self._opt is None:
                self._opt = _build_optimizer(header["optimizer"])
                self._params = {k: v.copy() for k, v in tensors.items()}
                logger.info(
                    "ps shard initialized: %d tensors, optimizer %s",
                    len(tensors),
                    header["optimizer"][0],
                )
            # idempotent: late initializers get the live params
            return {"op": "init_ok"}, dict(self._params)

    def _op_pull(self, header, tensors):
        with self._lock:
            return {"op": "pull_ok"}, dict(self._params)

    def _op_push(self, header, tensors):
        with self._lock:
            if self._opt is None:
                return {"op": "error", "error": "shard not initialized"}, {}
            pod, window = header.get("pod"), header.get("window")
            if pod is not None and window is not None:
                window = int(window)
                if window <= self.applied_windows.get(pod, -1):
                    # duplicate window (leader failover re-push): do NOT
                    # re-apply; reply with live params so the client
                    # still advances
                    return {"op": "push_ok", "dedup": True}, dict(
                        self._params
                    )
                self.applied_windows[pod] = window
                self.applied_log.append((pod, window))
            for name, grad in tensors.items():
                p = self._params.get(name)
                if p is None:
                    return {
                        "op": "error",
                        "error": "unknown tensor {0}".format(name),
                    }, {}
                self._params[name] = self._opt.update(
                    name, p, grad.astype(p.dtype, copy=False)
                )
            # piggyback fresh params: push+pull in one round trip
            return {"op": "push_ok"}, dict(self._params)

    def _op_window(self, header, tensors):
        """Last applied hierarchical window for ``pod`` (-1 when the
        pod never pushed) — what a freshly-elected pod leader resumes
        its sequence from (docs/communication.md)."""
        with self._lock:
            return {
                "op": "window_ok",
                "last": self.applied_windows.get(header.get("pod"), -1),
            }, {}

    # -- service loop --------------------------------------------------

    def start(self, host="", port=0):
        """Bind and serve in background threads; returns ``(host, port)``."""
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.addr = self._sock.getsockname()
        t = threading.Thread(target=self._accept_loop, daemon=True, name="ps-accept")
        t.start()
        return self.addr

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, peer = self._sock.accept()
            except OSError:
                return  # socket closed
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True, name="ps-conn"
            ).start()

    def _serve_conn(self, conn):
        ops = {"init": self._op_init, "pull": self._op_pull,
               "push": self._op_push, "window": self._op_window}
        reply = _ReplyCompressor()
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._stop.is_set():
                try:
                    header, tensors = recv_msg(conn)
                except (ConnectionError, OSError, json.JSONDecodeError):
                    return
                op = header.get("op")
                if op == "stop":
                    send_msg(conn, {"op": "stop_ok"})
                    self.stop()
                    return
                if op == "codec":
                    # per-connection negotiation: subsequent params
                    # replies ship as compressed deltas vs this
                    # connection's tracked client view
                    try:
                        reply.negotiate(header.get("reply"))
                    except (ValueError, TypeError) as e:
                        send_msg(conn, {"op": "error", "error": str(e)})
                        continue
                    send_msg(
                        conn,
                        {
                            "op": "codec_ok",
                            "reply": reply.codec.name if reply.codec else None,
                        },
                    )
                    continue
                handler = ops.get(op)
                if handler is None:
                    send_msg(conn, {"op": "error", "error": "bad op " + repr(op)})
                    continue
                out_header, out_tensors = handler(header, tensors)
                if reply.codec is not None and out_tensors:
                    _send_frame(conn, out_header, reply.entries(out_tensors))
                else:
                    send_msg(conn, out_header, out_tensors)
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def join(self, timeout=None):
        """Block until the shard is stopped (the ``server.join()`` role,
        reference: TFNode.py:120-129)."""
        self._stop.wait(timeout)


def run_server(ctx, host=""):
    """Run this ps node's shard until STOP / process teardown.

    Called from ``main_fun`` when ``ctx.job_name == 'ps'`` — the
    reference-parity usage where user code dispatched ps roles to
    ``server.join()`` (reference: TFNode.py:120-129).  The shard binds
    the port the clusterspec advertises for this ps task, so workers
    find it at ``ctx.cluster_spec['ps'][task_index]``.
    """
    addr = ctx.cluster_spec["ps"][ctx.task_index]
    port = int(addr.rsplit(":", 1)[1])
    shard = ParamServerShard()
    shard.start(host, port)
    logger.info("ps shard %d serving at %s", ctx.task_index, shard.addr)
    shard.join()


# ----------------------------------------------------------------------
# client
# ----------------------------------------------------------------------


def _flatten(params):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    return [np.asarray(x) for x in leaves], treedef


class _PushHandle(object):
    """In-flight push_pull: ``result()`` waits for every shard's reply
    and returns the unsharded params."""

    def __init__(self, client, boxes, events):
        self._client = client
        self._boxes = boxes
        self._events = events

    def result(self):
        return self._client._unshard(
            PSClient._collect(self._boxes, self._events)
        )


class PSClient(object):
    """Worker-side connection to every PS shard.

    Args:
      addresses: list of ``"host:port"`` (``ctx.cluster_spec['ps']``).
      timeout: per-socket timeout (secs).
      codec: optional gradient-push codec spec (``"int8"``,
        ``("topk", {"ratio": 0.01})``, or a
        :class:`~tensorflowonspark_tpu.compress.Codec`) — pushes ship
        compressed; ``init`` params always ship exact.
      error_feedback: wrap a lossy push codec in client-side
        :class:`~tensorflowonspark_tpu.compress.ErrorFeedback`
        (residual accumulation; keep the default unless measuring the
        uncompensated codec).
      reply_codec: optional reply codec spec negotiated with every
        shard (the ``codec`` wire op): params replies then arrive as
        compressed deltas against this client's last-known view instead
        of dense ``dict(params)``.  ``"same"`` reuses ``codec``'s spec.
        Old servers that reject the negotiation fall back to dense
        replies (logged).
    """

    def __init__(self, addresses, timeout=60, codec=None,
                 error_feedback=True, reply_codec=None):
        from tensorflowonspark_tpu.utils.retry import retry_call

        self.addresses = list(addresses)
        self._socks = []
        for a in self.addresses:
            host, _, port = a.rpartition(":")
            # Backoff-with-jitter under a hard deadline (utils/retry.py)
            # — workers race the ps shards' startup (the shard binds in
            # a background compute process after the rendezvous barrier
            # releases), and a whole fleet reconnecting to a restarted
            # shard must not stampede it in lockstep.
            s = retry_call(
                lambda h=host, p=int(port): socket.create_connection(
                    (h, p), timeout=max(1.0, timeout)
                ),
                "connect to ps shard at {0}".format(a),
                exceptions=(OSError,),
                deadline=timeout,
                base=0.2,
                max_delay=2.0,
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks.append(s)
        self._treedef = None
        self._assignment = None  # leaf index -> shard index
        self._shapes = None
        # gradient-push codec (client->server), optionally under error
        # feedback; and the negotiated reply codec (server->client
        # compressed deltas).  Residuals/views are keyed by wire tensor
        # name; each name is only ever touched by its shard's worker
        # thread, so no extra locking is needed.
        push = compress_mod.get_codec(codec)
        if push is not None and isinstance(push, compress_mod.NoneCodec):
            push = None
        if push is not None and error_feedback:
            push = compress_mod.ErrorFeedback(push)
        self._push_codec = push
        if reply_codec == "same":
            reply_codec = push.spec() if push is not None else None
        self._reply_views = [dict() for _ in self._socks]
        self._reply_active = False
        #: wire bytes this client laid on / pulled off each shard
        #: connection (headers + payloads, both directions; one writer
        #: per index) — initialized BEFORE the reply negotiation so its
        #: round trip is accounted too
        self._sent_bytes = [0] * len(self._socks)
        self._recv_bytes = [0] * len(self._socks)
        if reply_codec is not None:
            self._negotiate_reply(reply_codec)
        # fleet telemetry: the wire accounting that used to live only
        # in this object now also publishes into the process registry,
        # and push/pull round trips trace as spans (null singletons /
        # no-op spans when TFOS_TELEMETRY=0 — docs/observability.md)
        from tensorflowonspark_tpu import telemetry as _telemetry

        _reg = _telemetry.get_registry()
        self._m_bytes = _reg.counter("ps.bytes_sent")
        self._m_bytes_recv = _reg.counter("ps.bytes_recv")
        self._m_trips = _reg.counter("ps.round_trips")
        self._m_rt_hist = _reg.histogram("ps.round_trip_sec")
        self._tracer = _telemetry.get_tracer()
        # persistent per-shard request workers: a round trip costs two
        # queue handoffs instead of a thread spawn per shard per step
        # (measured: thread creation dominated small-model step time)
        import queue as _queue

        self._reqs = [_queue.Queue() for _ in self._socks]
        self._workers = []
        self._closed = False
        for i in range(len(self._socks)):
            t = threading.Thread(
                target=self._shard_worker, args=(i,), daemon=True
            )
            t.start()
            self._workers.append(t)

    def _negotiate_reply(self, spec):
        """Negotiate compressed-delta replies on every shard connection
        (runs before the workers start, so the sockets are free)."""
        spec = compress_mod.get_codec(spec).spec()
        ok = True
        for i, s in enumerate(self._socks):
            self._sent_bytes[i] += send_msg(s, {"op": "codec", "reply": spec})
            h, _ = recv_msg(s)
            self._recv_bytes[i] += h.get("_recv_nbytes", 0)
            if h.get("op") != "codec_ok":
                ok = False
        if not ok:
            # mixed/old ensemble: stay on dense replies everywhere
            # rather than tracking per-shard reply formats
            logger.warning(
                "reply codec %s rejected by a shard; dense replies", spec
            )
            for i, s in enumerate(self._socks):
                self._sent_bytes[i] += send_msg(
                    s, {"op": "codec", "reply": None}
                )
                h, _ = recv_msg(s)
                self._recv_bytes[i] += h.get("_recv_nbytes", 0)
        self._reply_active = ok

    @property
    def bytes_sent(self):
        """Total wire bytes laid on the shard connections by the worker
        round trips (headers + payloads, send side)."""
        return sum(self._sent_bytes)

    @property
    def bytes_recv(self):
        """Total wire bytes pulled OFF the shard connections (headers +
        payloads, receive side) — the reply/delta traffic ``bytes_sent``
        never saw.  Compressed delta replies shrink exactly this number
        (unit-tested against known payloads in tests/test_ps.py)."""
        return sum(self._recv_bytes)

    def _apply_reply(self, i, header, tensors):
        """Post-process one shard reply: delta-coded tensors are folded
        into this client's tracked view (float32, the same arithmetic
        the server's ``_ReplyCompressor`` ran on its copy — the two
        stay bit-identical); dense tensors refresh the view."""
        if not self._reply_active:
            return tensors
        view = self._reply_views[i]
        for m in header.get("tensors", ()):
            name = m.get("name")
            if name is None:
                continue
            if m.get("delta"):
                base = view.get(name)
                if base is None:
                    raise RuntimeError(
                        "shard {0} sent a delta for {1} without a dense "
                        "base".format(i, name)
                    )
                fresh = base + tensors[name].astype(np.float32, copy=False)
                view[name] = fresh
                tensors[name] = fresh.astype(
                    np.dtype(str(m.get("pdtype", "<f4"))), copy=False
                )
            else:
                view[name] = tensors[name].astype(np.float32, copy=True)
        return tensors

    def _shard_worker(self, i):
        sock = self._socks[i]
        q = self._reqs[i]
        while True:
            item = q.get()
            if item is None:
                return
            header, tensors, box, ev, codec = item
            try:
                op = header.get("op", "?")
                t0 = time.perf_counter()
                # "push" covers codec encode + the wire send; "pull"
                # the reply wait + decode — the two halves of the
                # training-step trace's PS leg
                with self._tracer.span(
                    "ps.push", trace="ps", shard=i, op=op
                ) as sp:
                    sent = send_msg(sock, header, tensors, codec=codec)
                    sp.set("bytes", sent)
                self._sent_bytes[i] += sent
                self._m_bytes.inc(sent)
                with self._tracer.span(
                    "ps.pull", trace="ps", shard=i, op=op
                ):
                    h, t = recv_msg(sock)
                self._recv_bytes[i] += h.get("_recv_nbytes", 0)
                self._m_bytes_recv.inc(h.get("_recv_nbytes", 0))
                self._m_trips.inc()
                self._m_rt_hist.observe(time.perf_counter() - t0)
                if h.get("op") == "error":
                    box[1] = RuntimeError(
                        "ps shard {0}: {1}".format(i, h["error"])
                    )
                else:
                    box[0] = self._apply_reply(i, h, t)
                    box[2] = h
            except Exception as e:  # noqa: BLE001 - delivered to caller
                box[1] = e
            ev.set()

    # -- sharding ------------------------------------------------------
    #
    # Two granularities (both DistBelief-style):
    # - small leaves go whole to one shard (size-balanced greedy);
    # - a leaf >= _CHUNK_BYTES with enough rows is split row-wise into
    #   one chunk per shard, so its wire bytes cross ALL shard
    #   connections concurrently instead of serializing through one.
    #   Exact for the leafwise numpy optimizers: every rule is
    #   elementwise, so updating row-chunks independently equals
    #   updating the whole leaf.

    _CHUNK_BYTES = 1 << 18  # 256KB: below this, chunking buys nothing

    def _assign(self, leaves):
        """Deterministic chunk plan: per leaf either ``shard_index`` or
        the list of shard indices its row-chunks land on."""
        n = len(self._socks)
        load = [0] * n
        plan = [None] * len(leaves)
        order = sorted(
            range(len(leaves)), key=lambda i: (-leaves[i].nbytes, i)
        )
        for i in order:
            leaf = leaves[i]
            if (
                n > 1
                and leaf.nbytes >= self._CHUNK_BYTES
                and getattr(leaf, "shape", ())
                and leaf.shape[0] >= n
            ):
                plan[i] = list(range(n))
                for s in range(n):
                    load[s] += leaf.nbytes // n
            else:
                shard = min(range(n), key=lambda s: (load[s], s))
                plan[i] = shard
                load[shard] += max(1, leaf.nbytes)
        return plan

    @staticmethod
    def _chunk_bounds(rows, k):
        """np.array_split's boundary rule, kept explicit so push and
        reassembly can never disagree."""
        base, extra = divmod(rows, k)
        bounds = [0]
        for j in range(k):
            bounds.append(bounds[-1] + base + (1 if j < extra else 0))
        return bounds

    def _shard_tensors(self, leaves):
        per_shard = [dict() for _ in self._socks]
        for i, leaf in enumerate(leaves):
            target = self._assignment[i]
            if isinstance(target, list):
                arr = np.asarray(leaf)
                bounds = self._chunk_bounds(arr.shape[0], len(target))
                for j, s in enumerate(target):
                    per_shard[s]["t{0}c{1}".format(i, j)] = arr[
                        bounds[j]:bounds[j + 1]
                    ]
            else:
                per_shard[target]["t{0}".format(i)] = leaf
        return per_shard

    def _unshard(self, replies):
        flat = {}
        for tensors in replies:
            flat.update(tensors)
        import jax

        leaves = []
        for i, target in enumerate(self._assignment):
            if isinstance(target, list):
                leaves.append(
                    np.concatenate(
                        [
                            flat["t{0}c{1}".format(i, j)]
                            for j in range(len(target))
                        ],
                        axis=0,
                    )
                )
            else:
                leaves.append(flat["t{0}".format(i)])
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    # -- round trips ---------------------------------------------------

    def _enqueue_all(self, headers, per_shard_tensors, codec=None):
        """Hand one request per shard to the persistent workers (all
        shards in flight concurrently); returns (boxes, events)."""
        if self._closed:
            # a request enqueued after close() would wait forever (the
            # workers are gone); fail fast instead
            raise RuntimeError("PSClient is closed")
        boxes = []
        events = []
        for i in range(len(self._socks)):
            box = [None, None, None]  # [reply, error, reply header]
            ev = threading.Event()
            boxes.append(box)
            events.append(ev)
            self._reqs[i].put(
                (headers[i], per_shard_tensors[i], box, ev, codec)
            )
        return boxes, events

    @staticmethod
    def _collect(boxes, events):
        for ev in events:
            ev.wait()
        errors = [
            (i, box[1]) for i, box in enumerate(boxes) if box[1] is not None
        ]
        if errors:
            raise RuntimeError(
                "PS round trip failed: "
                + "; ".join("shard {0}: {1}".format(i, e) for i, e in errors)
            )
        return [box[0] for box in boxes]

    def _roundtrip_all(self, headers, per_shard_tensors):
        return self._collect(*self._enqueue_all(headers, per_shard_tensors))

    def init(self, params, optimizer=("sgd", {"learning_rate": 0.01})):
        """Initialize (or join) the PS ensemble; returns the live params.

        Idempotent across workers: the first ``init`` seeds the shards,
        later ones receive the current values — the chief/worker race is
        harmless by construction.
        """
        leaves, self._treedef = _flatten(params)
        self._shapes = [x.shape for x in leaves]
        self._assignment = self._assign(leaves)
        per_shard = self._shard_tensors(leaves)
        headers = [
            {"op": "init", "optimizer": [optimizer[0], optimizer[1] or {}]}
            for _ in self._socks
        ]
        return self._unshard(self._roundtrip_all(headers, per_shard))

    def pull(self):
        """Fetch current params from all shards.  Requires a prior
        :meth:`init` on this client (it defines the pytree structure and
        leaf→shard assignment; init is idempotent, so calling it with a
        params template is the way to *join* a live ensemble)."""
        if self._assignment is None:
            raise RuntimeError(
                "call init(params_template, optimizer) before pull()/"
                "push_pull(): it defines the leaf->shard assignment "
                "(idempotent; the template does not overwrite live params)"
            )
        headers = [{"op": "pull"} for _ in self._socks]
        return self._unshard(self._roundtrip_all(headers, [{}] * len(self._socks)))

    def push_pull(self, grads, header_extra=None):
        """Ship gradients, get fresh params back (one async-SGD step)."""
        return self.push_pull_async(grads, header_extra=header_extra).result()

    def push_pull_async(self, grads, header_extra=None):
        """Enqueue the push on every shard worker and return a handle;
        ``handle.result()`` blocks for the replies and unshards.  The
        pipelined :class:`AsyncTrainer` uses this to overlap the round
        trip with the next gradient computation without an extra relay
        thread (each hop in the wakeup chain costs a context switch —
        measured on the bench model, a pool-thread relay ate the whole
        overlap win).

        ``header_extra`` merges extra JSON-able fields into every
        shard's push header — the hierarchical plane stamps its
        ``pod``/``window`` ledger ids this way so the server can
        dedup leader-failover re-pushes."""
        if self._assignment is None:
            raise RuntimeError(
                "call init(params_template, optimizer) before pull()/"
                "push_pull(): it defines the leaf->shard assignment "
                "(idempotent; the template does not overwrite live params)"
            )
        leaves, _ = _flatten(grads)
        per_shard = self._shard_tensors(leaves)
        headers = [
            dict({"op": "push"}, **(header_extra or {}))
            for _ in self._socks
        ]
        return _PushHandle(
            self,
            *self._enqueue_all(headers, per_shard, codec=self._push_codec)
        )

    def window_floor(self, pod):
        """The highest window sequence EVERY shard has applied for
        ``pod`` (-1 when the pod never pushed) — where a newly-elected
        pod leader resumes its push sequence.  Taking the min over
        shards makes a partially-landed window (some shards applied it
        before the old leader died) get re-pushed everywhere; shards
        that already applied it dedup by the ledger, so each shard
        still applies each window exactly once."""
        headers = [{"op": "window", "pod": pod} for _ in self._socks]
        boxes, events = self._enqueue_all(headers, [{}] * len(self._socks))
        self._collect(boxes, events)
        return min(int((b[2] or {}).get("last", -1)) for b in boxes)

    def _join_workers(self):
        self._closed = True
        for q in self._reqs:
            q.put(None)
        for t in self._workers:
            t.join(timeout=5)
        self._workers = []

    def stop(self):
        """Stop every shard (end of training; the driver's control-queue
        teardown is the backstop, reference: TFCluster.py:186-194)."""
        self._join_workers()  # sockets must have no reader in flight
        for s in self._socks:
            try:
                send_msg(s, {"op": "stop"})
                recv_msg(s)
            except (ConnectionError, OSError):
                pass
        self.close()

    def close(self):
        if self._workers:
            self._join_workers()
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass


# ----------------------------------------------------------------------
# worker-side trainer
# ----------------------------------------------------------------------


class _GradDrain(object):
    """Background device→host gradient drain feeding the
    :class:`_PushHandle` pipeline.

    The dispatch thread hands over *device* gradient trees and keeps
    dispatching; this thread performs the device→host readback (the
    blocking ``device_get`` that used to sit on the training loop's
    critical path — the measured async-PS bottleneck) and enqueues the
    push on the shard workers.  Double-buffered: readback of window
    N+1 overlaps the wire round trip of window N (the previous handle
    is collected only after the next push is in flight).

    ``max_inflight`` is the bounded-staleness window: at most that many
    gradient windows may be queued-or-flying before ``submit`` blocks
    the dispatch thread, so a slow tunnel backpressures training
    instead of accumulating unbounded staleness.
    """

    _STOP = object()

    def __init__(self, client, max_inflight=2):
        import queue as _queue

        self._client = client
        self._slots = threading.Semaphore(max(1, int(max_inflight)))
        self._q = _queue.Queue()
        self._fresh_lock = threading.Lock()
        self._fresh = None
        self._error = None
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ps-grad-drain"
        )
        self._thread.start()

    # test hook: tests assert every readback happens on THIS thread,
    # never on the dispatch thread (the non-blocking contract)
    def _to_host(self, tree):
        import jax

        from tensorflowonspark_tpu import telemetry

        # the measured async-PS bottleneck (BENCH_r05) gets its own
        # span + histogram so the step trace shows where the wall went
        t0 = time.perf_counter()
        out = jax.device_get(tree)
        dur = time.perf_counter() - t0
        telemetry.get_registry().histogram(
            "ps.grad_readback_sec"
        ).observe(dur)
        telemetry.get_tracer().add("grad_readback", t0, dur, trace="ps")
        return out

    def submit(self, device_grads):
        """Hand a device gradient tree to the drain; blocks only when
        the staleness window is full.  Raises any error a previous
        window hit (once)."""
        self._raise_pending()
        # tfoslint: disable=TFOS006(staleness-window semaphore: the _GradDrain thread releases it after the round trip - cross-thread handoff by design)
        self._slots.acquire()
        self._q.put(device_grads)

    def freshest(self):
        """Latest params any landed round trip returned (or None)."""
        self._raise_pending()
        with self._fresh_lock:
            return self._fresh

    def _raise_pending(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def _land(self, handle):
        try:
            fresh = handle.result()
            with self._fresh_lock:
                self._fresh = fresh
        except Exception as e:  # noqa: BLE001 - surfaced on next submit
            if self._error is None:
                self._error = e
        finally:
            self._slots.release()

    def _loop(self):
        prev = None
        while True:
            item = self._q.get()
            if item is self._STOP:
                break
            if isinstance(item, threading.Event):  # flush marker
                if prev is not None:
                    self._land(prev)
                    prev = None
                item.set()
                continue
            try:
                host = self._to_host(item)
                handle = self._client.push_pull_async(host)
            except Exception as e:  # noqa: BLE001 - surfaced on submit
                if self._error is None:
                    self._error = e
                self._slots.release()
                continue
            # collect the PREVIOUS round trip only now: its wire time
            # overlapped this window's device→host readback
            if prev is not None:
                self._land(prev)
            prev = handle
        if prev is not None:
            self._land(prev)

    def flush(self):
        """Block until every submitted window has landed; returns the
        freshest params (or None if nothing ever landed)."""
        ev = threading.Event()
        self._q.put(ev)
        ev.wait()
        self._raise_pending()
        with self._fresh_lock:
            return self._fresh

    def stop(self):
        self._q.put(self._STOP)
        self._thread.join(timeout=10)


class AsyncTrainer(object):
    """Async-PS worker loop: local grads on this node's chips, updates on
    the parameter hosts.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar``.
      ps_addresses: ``ctx.cluster_spec['ps']``.
      optimizer: named spec, e.g. ``("adam", {"learning_rate": 1e-3})``.
      pipeline: overlap the PS round trip with the next gradient
        computation (a background single-slot sender).  The params a
        step trains on are then one round trip staler than fully
        synchronous pulls — exactly the async-PS staleness model, one
        deeper — in exchange for hiding the TCP latency behind compute.
        The reference's between-graph PS mode had the same overlap
        implicitly (TF queued send ops against the next session.run).
      overlap: move the device→host gradient readback off the training
        loop entirely (:class:`_GradDrain`): ``step`` dispatches the
        next gradient computation while a background thread drains the
        previous window's grads and runs the push — the fix for the
        measured "per-step device->host grad transfer" bottleneck.
        Staleness is bounded by ``max_inflight`` windows.
      push_every: accumulate this many steps' gradients ON DEVICE
        (mean) per push — the tunnel sees 1/k the traffic and the PS
        applies the averaged gradient (local accumulation; exact for
        the leafwise optimizers up to the usual async staleness).
      max_inflight: bounded-staleness cap for ``overlap`` mode.
      codec / reply_codec / error_feedback: gradient-plane compression,
        forwarded to :class:`PSClient` (docs/communication.md).
      topology: ``"flat"`` (default — every step crosses the host/TCP
        wire, the DistBelief shape above) or ``"hierarchical"`` — the
        two-tier plane (docs/communication.md "Two-tier gradient
        plane"): per-step gradients aggregate over ICI collectives on
        the mesh and the PS apply runs as a jitted on-device program
        against device-resident shard state (NO host readback on the
        in-pod path); only the pod leader crosses DCN, pushing
        compressed window deltas at ``push_every`` cadence through
        this same wire with ``max_inflight`` bounding staleness.
        Delegates to
        :class:`tensorflowonspark_tpu.parallel.hier_ps.HierTrainer`;
        ``mesh``/``pod_id``/``members``/``member_id``/``leader_fn``
        are forwarded (``pipeline``/``overlap`` do not apply — the
        in-pod path has nothing to overlap, it never leaves the
        device).
    """

    def __init__(self, loss_fn, ps_addresses,
                 optimizer=("sgd", {"learning_rate": 0.01}),
                 pipeline=True, overlap=False, push_every=1,
                 max_inflight=2, codec=None, reply_codec=None,
                 error_feedback=True, topology="flat", mesh=None,
                 pod_id="pod0", members=None, member_id=0,
                 leader_fn=None):
        import jax

        if push_every < 1:
            raise ValueError(
                "push_every must be >= 1, got {0}".format(push_every)
            )
        if topology not in ("flat", "hierarchical"):
            raise ValueError(
                "topology must be 'flat' or 'hierarchical', got "
                "{0!r}".format(topology)
            )
        self.topology = topology
        if topology == "hierarchical":
            # lazy import: hier_ps imports this module for the wire
            from tensorflowonspark_tpu.parallel import hier_ps

            self._hier = hier_ps.HierTrainer(
                loss_fn, ps_addresses, optimizer=optimizer, mesh=mesh,
                push_every=push_every, max_inflight=max_inflight,
                codec=codec, reply_codec=reply_codec,
                error_feedback=error_feedback, pod_id=pod_id,
                members=members, member_id=member_id,
                leader_fn=leader_fn,
            )
            self._client = None
            self.optimizer = optimizer
            self.push_every = int(push_every)
            self.pipeline = False
            self.overlap = False
            self._drain = None
            return
        self._hier = None
        self._client = PSClient(
            ps_addresses, codec=codec, reply_codec=reply_codec,
            error_feedback=error_feedback,
        )
        self.optimizer = optimizer
        self.pipeline = pipeline
        self.overlap = bool(overlap)
        self.push_every = int(push_every)
        self._grad_fn = jax.jit(jax.grad(loss_fn))
        self._acc_fn = jax.jit(
            lambda a, b: jax.tree.map(lambda x, y: x + y, a, b)
        )
        self._inflight = None
        self._accum = None
        self._accum_n = 0
        self._drain = (
            _GradDrain(self.client, max_inflight=max_inflight)
            if self.overlap else None
        )

    @property
    def client(self):
        """The live :class:`PSClient` (wire accounting).  Hierarchical
        topology resolves through the CURRENT leader epoch's link — a
        failover swaps the underlying connection, and a captured
        reference would keep reading the dead epoch's counters."""
        if self._hier is not None:
            return self._hier.client
        return self._client

    def init(self, params):
        if self._hier is not None:
            return self._hier.init(params)
        return self.client.init(params, self.optimizer)

    _mean_cache = None

    def _mean_fn(self, n):
        # cached per window size: a fresh lambda per call would re-jit
        # every accumulation window
        import jax

        if self._mean_cache is None:
            self._mean_cache = {}
        fn = self._mean_cache.get(n)
        if fn is None:
            inv = 1.0 / float(n)
            fn = jax.jit(lambda t: jax.tree.map(lambda x: x * inv, t))
            self._mean_cache[n] = fn
        return fn

    def _accumulate(self, grads):
        """Fold one step's device grads into the local window; returns
        the (mean) window to ship, or None while the window fills.  All
        arithmetic is jitted on device — nothing crosses to host here."""
        if self.push_every == 1:
            return grads
        self._accum = (
            grads if self._accum is None
            else self._acc_fn(self._accum, grads)
        )
        self._accum_n += 1
        if self._accum_n < self.push_every:
            return None
        out = self._mean_fn(self._accum_n)(self._accum)
        self._accum, self._accum_n = None, 0
        return out

    def step(self, params, batch):
        """One async step; returns fresh params (stale-gradient model:
        grads computed at ``params`` may land after other workers').
        Hierarchical topology: the device-resident state is
        authoritative, ``params`` is ignored and the returned tree
        stays on device."""
        if self._hier is not None:
            return self._hier.step(batch)
        grads = self._grad_fn(params, batch)
        window = self._accumulate(grads)
        if window is None:
            return self._freshest(params)
        if self.overlap:
            # hand the DEVICE tree to the drain: the readback happens on
            # its thread, this one goes straight back to dispatching
            self._drain.submit(window)
            return self._freshest(params)
        if not self.pipeline:
            return self.client.push_pull(window)
        # enqueue this step's push directly on the shard workers, then
        # collect the PREVIOUS round trip — its wire time overlapped
        # this step's gradient computation.  The new handle replaces
        # _inflight BEFORE collecting the old one: if the old trip
        # failed, the error surfaces once and the next step collects
        # the fresh handle instead of re-raising a stale failure
        prev, self._inflight = self._inflight, self.client.push_pull_async(
            window
        )
        return prev.result() if prev is not None else params

    def _freshest(self, params):
        fresh = self._drain.freshest() if self._drain is not None else None
        return fresh if fresh is not None else params

    def drain(self):
        """Block until every in-flight round trip lands; returns the
        freshest params or None.  Call at epoch/export boundaries so
        checkpoints see every shipped gradient.  A partially-filled
        accumulation window is shipped (mean over its actual count)."""
        if self._hier is not None:
            return self._hier.drain()
        if self._accum is not None:
            window = self._mean_fn(self._accum_n)(self._accum)
            self._accum, self._accum_n = None, 0
            if self._drain is not None:
                self._drain.submit(window)
            else:
                prev, self._inflight = self._inflight, None
                if prev is not None:
                    prev.result()
                return self.client.push_pull(window)
        if self._drain is not None:
            return self._drain.flush()
        if self._inflight is None:
            return None
        fresh = self._inflight.result()
        self._inflight = None
        return fresh

    def stop(self, stop_servers=False):
        if self._hier is not None:
            return self._hier.stop(stop_servers=stop_servers)
        try:
            self.drain()
        except Exception:  # noqa: BLE001 - teardown must proceed
            pass
        if self._drain is not None:
            self._drain.stop()
        if stop_servers:
            self.client.stop()
        else:
            self.client.close()
