"""Asynchronous parameter-server data parallelism.

The reference's async-DP mode delegated everything to TensorFlow's PS
runtime: ``num_ps`` executors ran ``tf.train.Server`` processes that the
framework kept pinned via a control-queue block, with
``ParameterServerStrategy`` in user code (reference:
TFSparkNode.py:409-426, TFCluster.py:186-194,
examples/mnist/estimator/mnist_spark_streaming.py:88).  TPUs have no
native PS runtime, so this module *is* the PS system (SURVEY.md §7
'Hard parts: Async PS on TPU'):

- **ParamServerShard** — a TCP service holding a shard of the model's
  leaves in host memory, applying updates with its own numpy optimizer
  (the parameter-host-over-DCN design: PS traffic rides the data-center
  network while each worker's compute stays on its chips).
- **PSClient** — worker-side: partitions a params pytree across shards
  (size-balanced), then ``push_pull(grads)`` ships gradients and
  returns fresh params in one round trip per shard (DistBelief-style
  async SGD; no barrier between workers, stale gradients by design).
- **run_server(ctx)** — what a ps-role node runs inside ``main_fun``
  (the ``server.join()`` analogue, reference: TFNode.py:120-129): binds
  the clusterspec's ps address and serves until STOP/teardown.
- **AsyncTrainer** — worker-side convenience wrapping grad computation
  (jit on the local chips) + push_pull.

Wire protocol: 4-byte BE header length + JSON header + raw tensor
bytes (no pickle — same hardening rationale as
:mod:`tensorflowonspark_tpu.cluster.reservation`).  Optimizers are
named specs (``("adam", {"learning_rate": 1e-3})``) resolved against
the server's own numpy implementations, never deserialized code.
Leafwise optimizers only (sgd/momentum/adagrad/adam): each shard
updates its leaves independently, which is exact for these rules.
"""

import json
import logging
import socket
import struct
import threading

import numpy as np

logger = logging.getLogger(__name__)

_MAX_HEADER = 16 * 1024 * 1024


# ----------------------------------------------------------------------
# framing: JSON header + raw tensor payloads
# ----------------------------------------------------------------------


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def send_msg(sock, header, tensors=None):
    """Send ``header`` (JSON-able dict) plus named numpy ``tensors``."""
    tensors = tensors or {}
    meta = []
    payloads = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        meta.append(
            {
                "name": name,
                "dtype": arr.dtype.str,
                "shape": list(arr.shape),
                "nbytes": int(arr.nbytes),
            }
        )
        payloads.append(arr)
    header = dict(header, tensors=meta)
    hb = json.dumps(header).encode("utf-8")
    parts = [struct.pack(">I", len(hb)), hb]
    parts.extend(memoryview(p).cast("B") for p in payloads)
    sock.sendall(b"".join(parts))


def recv_msg(sock):
    """Receive one message → ``(header, {name: np.ndarray})``."""
    (hlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    if hlen > _MAX_HEADER:
        raise ConnectionError("header length {0} exceeds limit".format(hlen))
    header = json.loads(_recv_exact(sock, hlen).decode("utf-8"))
    tensors = {}
    for m in header.get("tensors", ()):
        raw = _recv_exact(sock, m["nbytes"])
        tensors[m["name"]] = np.frombuffer(raw, dtype=np.dtype(m["dtype"])).reshape(
            m["shape"]
        )
    return header, tensors


# ----------------------------------------------------------------------
# server-side numpy optimizers (leafwise; no code deserialization)
# ----------------------------------------------------------------------


class _SGD(object):
    def __init__(self, learning_rate=0.01, momentum=0.0):
        self.lr = learning_rate
        self.momentum = momentum
        self._vel = {}

    def update(self, name, param, grad):
        if self.momentum:
            v = self._vel.get(name)
            v = grad if v is None else self.momentum * v + grad
            self._vel[name] = v
            grad = v
        return param - self.lr * grad


class _Adagrad(object):
    def __init__(self, learning_rate=0.01, eps=1e-10):
        self.lr = learning_rate
        self.eps = eps
        self._acc = {}

    def update(self, name, param, grad):
        acc = self._acc.get(name, np.zeros_like(param)) + grad * grad
        self._acc[name] = acc
        return param - self.lr * grad / (np.sqrt(acc) + self.eps)


class _Adam(object):
    def __init__(self, learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8):
        self.lr = learning_rate
        self.b1, self.b2, self.eps = b1, b2, eps
        self._m, self._v, self._t = {}, {}, {}

    def update(self, name, param, grad):
        t = self._t.get(name, 0) + 1
        m = self.b1 * self._m.get(name, np.zeros_like(param)) + (1 - self.b1) * grad
        v = self.b2 * self._v.get(name, np.zeros_like(param)) + (
            1 - self.b2
        ) * grad * grad
        self._m[name], self._v[name], self._t[name] = m, v, t
        mhat = m / (1 - self.b1**t)
        vhat = v / (1 - self.b2**t)
        return param - self.lr * mhat / (np.sqrt(vhat) + self.eps)


OPTIMIZERS = {"sgd": _SGD, "adagrad": _Adagrad, "adam": _Adam}


def _build_optimizer(spec):
    name, kwargs = spec
    if name not in OPTIMIZERS:
        raise ValueError(
            "unknown PS optimizer {0!r}; supported: {1}".format(
                name, sorted(OPTIMIZERS)
            )
        )
    return OPTIMIZERS[name](**(kwargs or {}))


# ----------------------------------------------------------------------
# server
# ----------------------------------------------------------------------


class ParamServerShard(object):
    """One PS shard: parameter store + optimizer + TCP service.

    Thread-per-connection; updates serialized under a lock (each push is
    one atomic read-modify-write, the async-SGD consistency model).
    """

    def __init__(self):
        self._params = {}
        self._opt = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._sock = None
        self.addr = None

    # -- ops -----------------------------------------------------------

    def _op_init(self, header, tensors):
        with self._lock:
            if self._opt is None:
                self._opt = _build_optimizer(header["optimizer"])
                self._params = {k: v.copy() for k, v in tensors.items()}
                logger.info(
                    "ps shard initialized: %d tensors, optimizer %s",
                    len(tensors),
                    header["optimizer"][0],
                )
            # idempotent: late initializers get the live params
            return {"op": "init_ok"}, dict(self._params)

    def _op_pull(self, header, tensors):
        with self._lock:
            return {"op": "pull_ok"}, dict(self._params)

    def _op_push(self, header, tensors):
        with self._lock:
            if self._opt is None:
                return {"op": "error", "error": "shard not initialized"}, {}
            for name, grad in tensors.items():
                p = self._params.get(name)
                if p is None:
                    return {
                        "op": "error",
                        "error": "unknown tensor {0}".format(name),
                    }, {}
                self._params[name] = self._opt.update(
                    name, p, grad.astype(p.dtype, copy=False)
                )
            # piggyback fresh params: push+pull in one round trip
            return {"op": "push_ok"}, dict(self._params)

    # -- service loop --------------------------------------------------

    def start(self, host="", port=0):
        """Bind and serve in background threads; returns ``(host, port)``."""
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.addr = self._sock.getsockname()
        t = threading.Thread(target=self._accept_loop, daemon=True, name="ps-accept")
        t.start()
        return self.addr

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, peer = self._sock.accept()
            except OSError:
                return  # socket closed
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True, name="ps-conn"
            ).start()

    def _serve_conn(self, conn):
        ops = {"init": self._op_init, "pull": self._op_pull, "push": self._op_push}
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._stop.is_set():
                try:
                    header, tensors = recv_msg(conn)
                except (ConnectionError, OSError, json.JSONDecodeError):
                    return
                op = header.get("op")
                if op == "stop":
                    send_msg(conn, {"op": "stop_ok"})
                    self.stop()
                    return
                handler = ops.get(op)
                if handler is None:
                    send_msg(conn, {"op": "error", "error": "bad op " + repr(op)})
                    continue
                out_header, out_tensors = handler(header, tensors)
                send_msg(conn, out_header, out_tensors)
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def join(self, timeout=None):
        """Block until the shard is stopped (the ``server.join()`` role,
        reference: TFNode.py:120-129)."""
        self._stop.wait(timeout)


def run_server(ctx, host=""):
    """Run this ps node's shard until STOP / process teardown.

    Called from ``main_fun`` when ``ctx.job_name == 'ps'`` — the
    reference-parity usage where user code dispatched ps roles to
    ``server.join()`` (reference: TFNode.py:120-129).  The shard binds
    the port the clusterspec advertises for this ps task, so workers
    find it at ``ctx.cluster_spec['ps'][task_index]``.
    """
    addr = ctx.cluster_spec["ps"][ctx.task_index]
    port = int(addr.rsplit(":", 1)[1])
    shard = ParamServerShard()
    shard.start(host, port)
    logger.info("ps shard %d serving at %s", ctx.task_index, shard.addr)
    shard.join()


# ----------------------------------------------------------------------
# client
# ----------------------------------------------------------------------


def _flatten(params):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    return [np.asarray(x) for x in leaves], treedef


class _PushHandle(object):
    """In-flight push_pull: ``result()`` waits for every shard's reply
    and returns the unsharded params."""

    def __init__(self, client, boxes, events):
        self._client = client
        self._boxes = boxes
        self._events = events

    def result(self):
        return self._client._unshard(
            PSClient._collect(self._boxes, self._events)
        )


class PSClient(object):
    """Worker-side connection to every PS shard.

    Args:
      addresses: list of ``"host:port"`` (``ctx.cluster_spec['ps']``).
      timeout: per-socket timeout (secs).
    """

    def __init__(self, addresses, timeout=60):
        from tensorflowonspark_tpu.utils.retry import retry_call

        self.addresses = list(addresses)
        self._socks = []
        for a in self.addresses:
            host, _, port = a.rpartition(":")
            # Backoff-with-jitter under a hard deadline (utils/retry.py)
            # — workers race the ps shards' startup (the shard binds in
            # a background compute process after the rendezvous barrier
            # releases), and a whole fleet reconnecting to a restarted
            # shard must not stampede it in lockstep.
            s = retry_call(
                lambda h=host, p=int(port): socket.create_connection(
                    (h, p), timeout=max(1.0, timeout)
                ),
                "connect to ps shard at {0}".format(a),
                exceptions=(OSError,),
                deadline=timeout,
                base=0.2,
                max_delay=2.0,
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks.append(s)
        self._treedef = None
        self._assignment = None  # leaf index -> shard index
        self._shapes = None
        # persistent per-shard request workers: a round trip costs two
        # queue handoffs instead of a thread spawn per shard per step
        # (measured: thread creation dominated small-model step time)
        import queue as _queue

        self._reqs = [_queue.Queue() for _ in self._socks]
        self._workers = []
        for i in range(len(self._socks)):
            t = threading.Thread(
                target=self._shard_worker, args=(i,), daemon=True
            )
            t.start()
            self._workers.append(t)

    def _shard_worker(self, i):
        sock = self._socks[i]
        q = self._reqs[i]
        while True:
            item = q.get()
            if item is None:
                return
            header, tensors, box, ev = item
            try:
                send_msg(sock, header, tensors)
                h, t = recv_msg(sock)
                if h.get("op") == "error":
                    box[1] = RuntimeError(
                        "ps shard {0}: {1}".format(i, h["error"])
                    )
                else:
                    box[0] = t
            except Exception as e:  # noqa: BLE001 - delivered to caller
                box[1] = e
            ev.set()

    # -- sharding ------------------------------------------------------
    #
    # Two granularities (both DistBelief-style):
    # - small leaves go whole to one shard (size-balanced greedy);
    # - a leaf >= _CHUNK_BYTES with enough rows is split row-wise into
    #   one chunk per shard, so its wire bytes cross ALL shard
    #   connections concurrently instead of serializing through one.
    #   Exact for the leafwise numpy optimizers: every rule is
    #   elementwise, so updating row-chunks independently equals
    #   updating the whole leaf.

    _CHUNK_BYTES = 1 << 18  # 256KB: below this, chunking buys nothing

    def _assign(self, leaves):
        """Deterministic chunk plan: per leaf either ``shard_index`` or
        the list of shard indices its row-chunks land on."""
        n = len(self._socks)
        load = [0] * n
        plan = [None] * len(leaves)
        order = sorted(
            range(len(leaves)), key=lambda i: (-leaves[i].nbytes, i)
        )
        for i in order:
            leaf = leaves[i]
            if (
                n > 1
                and leaf.nbytes >= self._CHUNK_BYTES
                and getattr(leaf, "shape", ())
                and leaf.shape[0] >= n
            ):
                plan[i] = list(range(n))
                for s in range(n):
                    load[s] += leaf.nbytes // n
            else:
                shard = min(range(n), key=lambda s: (load[s], s))
                plan[i] = shard
                load[shard] += max(1, leaf.nbytes)
        return plan

    @staticmethod
    def _chunk_bounds(rows, k):
        """np.array_split's boundary rule, kept explicit so push and
        reassembly can never disagree."""
        base, extra = divmod(rows, k)
        bounds = [0]
        for j in range(k):
            bounds.append(bounds[-1] + base + (1 if j < extra else 0))
        return bounds

    def _shard_tensors(self, leaves):
        per_shard = [dict() for _ in self._socks]
        for i, leaf in enumerate(leaves):
            target = self._assignment[i]
            if isinstance(target, list):
                arr = np.asarray(leaf)
                bounds = self._chunk_bounds(arr.shape[0], len(target))
                for j, s in enumerate(target):
                    per_shard[s]["t{0}c{1}".format(i, j)] = arr[
                        bounds[j]:bounds[j + 1]
                    ]
            else:
                per_shard[target]["t{0}".format(i)] = leaf
        return per_shard

    def _unshard(self, replies):
        flat = {}
        for tensors in replies:
            flat.update(tensors)
        import jax

        leaves = []
        for i, target in enumerate(self._assignment):
            if isinstance(target, list):
                leaves.append(
                    np.concatenate(
                        [
                            flat["t{0}c{1}".format(i, j)]
                            for j in range(len(target))
                        ],
                        axis=0,
                    )
                )
            else:
                leaves.append(flat["t{0}".format(i)])
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    # -- round trips ---------------------------------------------------

    def _enqueue_all(self, headers, per_shard_tensors):
        """Hand one request per shard to the persistent workers (all
        shards in flight concurrently); returns (boxes, events)."""
        boxes = []
        events = []
        for i in range(len(self._socks)):
            box = [None, None]  # [reply, error]
            ev = threading.Event()
            boxes.append(box)
            events.append(ev)
            self._reqs[i].put((headers[i], per_shard_tensors[i], box, ev))
        return boxes, events

    @staticmethod
    def _collect(boxes, events):
        for ev in events:
            ev.wait()
        errors = [
            (i, box[1]) for i, box in enumerate(boxes) if box[1] is not None
        ]
        if errors:
            raise RuntimeError(
                "PS round trip failed: "
                + "; ".join("shard {0}: {1}".format(i, e) for i, e in errors)
            )
        return [box[0] for box in boxes]

    def _roundtrip_all(self, headers, per_shard_tensors):
        return self._collect(*self._enqueue_all(headers, per_shard_tensors))

    def init(self, params, optimizer=("sgd", {"learning_rate": 0.01})):
        """Initialize (or join) the PS ensemble; returns the live params.

        Idempotent across workers: the first ``init`` seeds the shards,
        later ones receive the current values — the chief/worker race is
        harmless by construction.
        """
        leaves, self._treedef = _flatten(params)
        self._shapes = [x.shape for x in leaves]
        self._assignment = self._assign(leaves)
        per_shard = self._shard_tensors(leaves)
        headers = [
            {"op": "init", "optimizer": [optimizer[0], optimizer[1] or {}]}
            for _ in self._socks
        ]
        return self._unshard(self._roundtrip_all(headers, per_shard))

    def pull(self):
        """Fetch current params from all shards.  Requires a prior
        :meth:`init` on this client (it defines the pytree structure and
        leaf→shard assignment; init is idempotent, so calling it with a
        params template is the way to *join* a live ensemble)."""
        if self._assignment is None:
            raise RuntimeError(
                "call init(params_template, optimizer) before pull()/"
                "push_pull(): it defines the leaf->shard assignment "
                "(idempotent; the template does not overwrite live params)"
            )
        headers = [{"op": "pull"} for _ in self._socks]
        return self._unshard(self._roundtrip_all(headers, [{}] * len(self._socks)))

    def push_pull(self, grads):
        """Ship gradients, get fresh params back (one async-SGD step)."""
        return self.push_pull_async(grads).result()

    def push_pull_async(self, grads):
        """Enqueue the push on every shard worker and return a handle;
        ``handle.result()`` blocks for the replies and unshards.  The
        pipelined :class:`AsyncTrainer` uses this to overlap the round
        trip with the next gradient computation without an extra relay
        thread (each hop in the wakeup chain costs a context switch —
        measured on the bench model, a pool-thread relay ate the whole
        overlap win)."""
        if self._assignment is None:
            raise RuntimeError(
                "call init(params_template, optimizer) before pull()/"
                "push_pull(): it defines the leaf->shard assignment "
                "(idempotent; the template does not overwrite live params)"
            )
        leaves, _ = _flatten(grads)
        per_shard = self._shard_tensors(leaves)
        headers = [{"op": "push"} for _ in self._socks]
        return _PushHandle(self, *self._enqueue_all(headers, per_shard))

    def _join_workers(self):
        for q in self._reqs:
            q.put(None)
        for t in self._workers:
            t.join(timeout=5)
        self._workers = []

    def stop(self):
        """Stop every shard (end of training; the driver's control-queue
        teardown is the backstop, reference: TFCluster.py:186-194)."""
        self._join_workers()  # sockets must have no reader in flight
        for s in self._socks:
            try:
                send_msg(s, {"op": "stop"})
                recv_msg(s)
            except (ConnectionError, OSError):
                pass
        self.close()

    def close(self):
        if self._workers:
            self._join_workers()
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass


# ----------------------------------------------------------------------
# worker-side trainer
# ----------------------------------------------------------------------


class AsyncTrainer(object):
    """Async-PS worker loop: local grads on this node's chips, updates on
    the parameter hosts.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar``.
      ps_addresses: ``ctx.cluster_spec['ps']``.
      optimizer: named spec, e.g. ``("adam", {"learning_rate": 1e-3})``.
      pipeline: overlap the PS round trip with the next gradient
        computation (a background single-slot sender).  The params a
        step trains on are then one round trip staler than fully
        synchronous pulls — exactly the async-PS staleness model, one
        deeper — in exchange for hiding the TCP latency behind compute.
        The reference's between-graph PS mode had the same overlap
        implicitly (TF queued send ops against the next session.run).
    """

    def __init__(self, loss_fn, ps_addresses,
                 optimizer=("sgd", {"learning_rate": 0.01}),
                 pipeline=True):
        import jax

        self.client = PSClient(ps_addresses)
        self.optimizer = optimizer
        self.pipeline = pipeline
        self._grad_fn = jax.jit(jax.grad(loss_fn))
        self._inflight = None

    def init(self, params):
        return self.client.init(params, self.optimizer)

    def step(self, params, batch):
        """One async step; returns fresh params (stale-gradient model:
        grads computed at ``params`` may land after other workers')."""
        grads = self._grad_fn(params, batch)
        if not self.pipeline:
            return self.client.push_pull(grads)
        # enqueue this step's push directly on the shard workers, then
        # collect the PREVIOUS round trip — its wire time overlapped
        # this step's gradient computation.  The new handle replaces
        # _inflight BEFORE collecting the old one: if the old trip
        # failed, the error surfaces once and the next step collects
        # the fresh handle instead of re-raising a stale failure
        prev, self._inflight = self._inflight, self.client.push_pull_async(
            grads
        )
        return prev.result() if prev is not None else params

    def drain(self):
        """Block until the in-flight round trip (if any) lands; returns
        the freshest params or None.  Call at epoch/export boundaries so
        checkpoints see every shipped gradient."""
        if self._inflight is None:
            return None
        fresh = self._inflight.result()
        self._inflight = None
        return fresh

    def stop(self, stop_servers=False):
        try:
            self.drain()
        except Exception:  # noqa: BLE001 - teardown must proceed
            pass
        if stop_servers:
            self.client.stop()
        else:
            self.client.close()
