"""TPU-native parallelism core (SURVEY.md §7 step 5).

The reference delegated every parallelism strategy to TensorFlow
(MultiWorkerMirroredStrategy / ParameterServerStrategy constructed in user
code, reference: tensorflowonspark/TFSparkNode.py:354-362); this package
owns them natively as mesh programs:

- :mod:`.mesh` — device mesh construction over ICI/DCN axes;
- :mod:`.sharding` — logical-axis sharding rules → ``PartitionSpec``;
- :mod:`.dp` — synchronous data parallelism (the MWMS equivalent) with a
  principled global-stop for uneven feeds;
- :mod:`.tp` — tensor parallelism (sharded matmuls);
- :mod:`.pp` — pipeline parallelism (stage mesh + microbatch loop);
- :mod:`.cp` — sequence/context parallelism (ring attention, Ulysses);
- :mod:`.ep` — expert parallelism (MoE all-to-all);
- :mod:`.ps` — asynchronous parameter-server emulation.
"""

from tensorflowonspark_tpu.parallel.mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPELINE,
    AXIS_SEQ,
    AXIS_TENSOR,
    MeshSpec,
    build_mesh,
)
from tensorflowonspark_tpu.parallel.sharding import (  # noqa: F401
    apply_rules,
    batch_sharding,
    replicated,
    shard_batch,
    shard_params,
)
