"""Sequence/context parallelism: long-context attention over the mesh.

Absent from the reference entirely (grep-verified, SURVEY.md §5
'Long-context / sequence parallelism'); built fresh for the TPU
framework.  Two interchangeable strategies over the ``seq`` mesh axis:

- **ring attention** (:func:`ring_attention`): KV blocks rotate around
  the ring via ``ppermute`` while each device holds its query block —
  communication overlaps compute, memory stays O(seq/devices);
- **Ulysses** (:func:`ulysses_attention`): all-to-all re-shards from
  sequence-split to head-split and back — cheaper at moderate head
  counts, one collective pair per attention.

Both produce numerics matching full attention (see
tests/test_attention.py) and compose with DP/FSDP via the mesh axes.
"""

from tensorflowonspark_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention,
    ring_attention_sharded,
)
from tensorflowonspark_tpu.ops.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_attention_sharded,
)

STRATEGIES = {
    "ring": ring_attention_sharded,
    "ulysses": ulysses_attention_sharded,
}


def context_parallel_attention(q, k, v, mesh, strategy="ring", **kwargs):
    """Dispatch sequence-parallel attention by strategy name.
    ``strategy="auto"`` picks via :func:`choose_strategy`."""
    if strategy == "auto":
        strategy = choose_strategy(
            seq_len=q.shape[1],
            num_heads=q.shape[2],
            head_dim=q.shape[3],
            seq_devices=mesh.shape.get(kwargs.get("axis_name", "seq"), 1),
        )
    if strategy not in STRATEGIES:
        raise ValueError(
            "unknown context-parallel strategy {0!r}; options: {1}".format(
                strategy, sorted(STRATEGIES)
            )
        )
    return STRATEGIES[strategy](q, k, v, mesh, **kwargs)


def choose_strategy(seq_len, num_heads, head_dim, seq_devices):
    """Pick ring vs Ulysses for a ``seq``-sharded attention.

    The decision follows the communication structure (scaling-book
    reasoning, volumes per device per attention call, N = seq devices):

    - **Ulysses** re-shards seq<->heads with two all-to-all pairs:
      ~``4 * (S/N) * H * D * (N-1)/N`` elements, one shot, latency
      2 collectives — but requires ``heads % N == 0`` and caps N at H.
    - **ring** rotates K and V around the ring: ``2 * (S/N) * H * D``
      elements per hop x (N-1) hops ≈ ``2 * S * H * D * (N-1)/N`` —
      ~S/(2·S/N) = N/2 x more volume than Ulysses, but every hop
      overlaps with a block of attention compute, so at long S the
      transfer hides entirely and ring wins on memory locality (no
      full-seq head shard ever materializes).

    Policy: Ulysses when the head count divides cleanly and the
    per-device sequence is short enough that ring's compute blocks
    could not hide the hops (S/N below ~4k tokens); ring otherwise.
    """
    if seq_devices <= 1:
        return "ring"  # degenerates to plain attention either way
    ulysses_ok = num_heads % seq_devices == 0
    local_seq = seq_len // max(1, seq_devices)
    if ulysses_ok and local_seq < 4096:
        return "ulysses"
    return "ring"


def plan(seq_len, batch, num_heads, head_dim, seq_devices, dtype_bytes=2):
    """Memory/communication plan for context-parallel attention.

    Returns per-device quantities: local sequence, Q/K/V bytes, the
    attention-score working set a *naive* (unsharded) computation would
    need (the number that forces CP in the first place), and per-call
    communication volume for each strategy."""
    local_seq = -(-seq_len // seq_devices)
    qkv_bytes = 3 * batch * local_seq * num_heads * head_dim * dtype_bytes
    n = max(1, seq_devices)
    ring_hop = 2 * batch * local_seq * num_heads * head_dim * dtype_bytes
    return {
        "local_seq": local_seq,
        "qkv_bytes_per_device": qkv_bytes,
        "naive_scores_bytes": batch * num_heads * seq_len * seq_len * 4,
        "ring_bytes_per_call": ring_hop * (n - 1),
        "ring_hops": n - 1,
        "ulysses_bytes_per_call": (
            4 * batch * local_seq * num_heads * head_dim * dtype_bytes
            * (n - 1) // n
        ),
        "ulysses_valid": num_heads % n == 0,
        "recommended": choose_strategy(
            seq_len, num_heads, head_dim, seq_devices
        ),
    }
