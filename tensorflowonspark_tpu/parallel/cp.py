"""Sequence/context parallelism: long-context attention over the mesh.

Absent from the reference entirely (grep-verified, SURVEY.md §5
'Long-context / sequence parallelism'); built fresh for the TPU
framework.  Two interchangeable strategies over the ``seq`` mesh axis:

- **ring attention** (:func:`ring_attention`): KV blocks rotate around
  the ring via ``ppermute`` while each device holds its query block —
  communication overlaps compute, memory stays O(seq/devices);
- **Ulysses** (:func:`ulysses_attention`): all-to-all re-shards from
  sequence-split to head-split and back — cheaper at moderate head
  counts, one collective pair per attention.

Both produce numerics matching full attention (see
tests/test_attention.py) and compose with DP/FSDP via the mesh axes.
"""

from tensorflowonspark_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention,
    ring_attention_sharded,
)
from tensorflowonspark_tpu.ops.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_attention_sharded,
)

STRATEGIES = {
    "ring": ring_attention_sharded,
    "ulysses": ulysses_attention_sharded,
}


def context_parallel_attention(q, k, v, mesh, strategy="ring", **kwargs):
    """Dispatch sequence-parallel attention by strategy name."""
    if strategy not in STRATEGIES:
        raise ValueError(
            "unknown context-parallel strategy {0!r}; options: {1}".format(
                strategy, sorted(STRATEGIES)
            )
        )
    return STRATEGIES[strategy](q, k, v, mesh, **kwargs)
