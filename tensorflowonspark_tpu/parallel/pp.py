"""Pipeline parallelism — microbatched stage loop over the ``pipe`` axis.

New TPU-first capability; the reference has no pipeline parallelism
(SURVEY.md §2.3: 'Tensor/Pipeline/... parallelism: absent').

Design (GPipe-style, the scaling-book recipe):

- layer parameters are *stacked* with a leading stage dimension and
  sharded over the ``pipe`` mesh axis, so each device holds one stage's
  layers and XLA never materializes the full model anywhere;
- the batch is split into M microbatches; a ``lax.scan`` runs
  ``M + P - 1`` ticks, each tick = one stage compute + one
  ``ppermute`` handing activations to the next stage (XLA lowers the
  permute onto neighbor ICI links, and overlaps it with the next tick's
  compute);
- stage 0 injects microbatch ``t`` at tick ``t``; the last stage's
  output for tick ``t`` is microbatch ``t - (P-1)``;
- reverse-mode AD through scan + ppermute *is* the backward pipeline
  (ppermute's transpose is the inverse permutation) — no hand-written
  backward schedule;
- bubble fraction is the usual ``(P-1)/(M+P-1)``: choose
  ``num_microbatches >= 4*P`` to amortize.

Two layers of API:

- :func:`pipeline` — the raw primitive, called under ``shard_map``
  (composes with TP/DP axes in the same mesh);
- :class:`PipelineTrainer` — a jitted training loop for stacked-block
  models (first/last-stage extras like embedding and loss heads handled
  via ``first_stage_fn``/``last_stage_fn``).
"""

import functools
import logging

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from tensorflowonspark_tpu import compat
from tensorflowonspark_tpu.compat import shard_map

logger = logging.getLogger(__name__)


def pipeline(stage_fn, stage_params, microbatches, axis_name="pipe",
             broadcast_result=True):
    """GPipe microbatch loop; call under ``shard_map``.

    Args:
      stage_fn: ``stage_fn(stage_params, x) -> y`` — one stage's compute
        on one microbatch (same output/input shape so activations can
        flow stage to stage).
      stage_params: this device's stage parameters (the local shard of a
        stacked-parameter pytree).
      microbatches: ``[M, mb, ...]`` microbatched input.  Only stage 0
        reads it (other stages may pass the same array; it is ignored).
      broadcast_result: if True, psum-broadcast the last stage's results
        to every stage (convenient for inference).  Training code that
        derives a *loss* from the result must pass False and mask to the
        last stage itself — a loss computed from the broadcast copy on
        every stage would backprop P cotangents through the psum and
        scale all gradients by the stage count.
    Returns ``[M, mb, ...]`` outputs: on the last stage (or everywhere
    with ``broadcast_result``) the pipelined results; zeros elsewhere.
    """
    p = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    total = m + p - 1
    perm = [(i, (i + 1) % p) for i in range(p)]

    buf0 = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    out0 = jnp.zeros(microbatches.shape, microbatches.dtype)

    def tick(carry, t):
        buf, outs = carry
        # stage 0 injects microbatch t (clamped index keeps the gather
        # in-bounds on the drain ticks where t >= m)
        inj = microbatches[jnp.minimum(t, m - 1)]
        x = jnp.where(idx == 0, inj, buf)
        y = stage_fn(stage_params, x)
        # last stage banks microbatch t-(p-1) during the valid window
        mb_idx = jnp.clip(t - (p - 1), 0, m - 1)
        is_valid = jnp.logical_and(idx == p - 1, t >= p - 1)
        outs = lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(is_valid, y, outs[mb_idx]),
            mb_idx,
            axis=0,
        )
        # hand activations to the next stage (wraparound write into
        # stage 0 is overwritten by injection next tick)
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, outs), None

    (_, outs), _ = lax.scan(tick, (buf0, out0), jnp.arange(total))
    # banked outputs live on the last stage; zero the other stages'
    # buffers (they hold stale intermediates)
    outs = jnp.where(idx == p - 1, outs, jnp.zeros_like(outs))
    if broadcast_result:
        outs = lax.psum(outs, axis_name)
    return outs


def stack_stage_params(per_layer_params, num_stages, interleave=1):
    """Stack an L-element list of per-layer param pytrees into a
    ``[num_stages, L/num_stages, ...]`` pytree (leading stage dim for
    ``pipe`` sharding, second dim scanned within a stage).

    With ``interleave=v > 1`` (Megatron interleaved schedule) the
    result is ``[num_stages, v, L/(num_stages*v), ...]``: element
    ``[d, c]`` holds the layers of *absolute* virtual stage
    ``c*num_stages + d`` — device d owns chunks ``d, d+P, ...``."""
    n = len(per_layer_params)
    v = interleave
    if n % (num_stages * v) != 0:
        raise ValueError(
            "num_layers ({0}) must divide by num_stages*interleave "
            "({1}*{2})".format(n, num_stages, v)
        )
    per_chunk = n // (num_stages * v)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer_params)
    if v == 1:
        return jax.tree.map(
            lambda x: x.reshape((num_stages, per_chunk) + x.shape[1:]),
            stacked,
        )
    # layers of abs chunk a = c*P + d sit at [a*per_chunk : ...]; a
    # reshape to [v, P, per_chunk] puts chunk a at [c, d] — swap to the
    # device-major [P, v, per_chunk] the pipe sharding wants
    return jax.tree.map(
        lambda x: jnp.swapaxes(
            x.reshape((v, num_stages, per_chunk) + x.shape[1:]), 0, 1
        ),
        stacked,
    )


def local_stage(stacked_local):
    """Drop the size-1 stage dim a ``P('pipe')``-sharded stacked-param
    pytree carries inside ``shard_map`` (local shard ``[1, L/P, ...]`` →
    ``[L/P, ...]``)."""
    return jax.tree.map(lambda x: x[0], stacked_local)


def _layers_scan(layer_fn, stage_params, x):
    """Apply a stage's stacked layers sequentially via ``lax.scan``
    (single compiled layer body regardless of depth)."""

    def body(h, layer_params):
        return layer_fn(layer_params, h), None

    out, _ = lax.scan(body, x, stage_params)
    return out


class PipelineTrainer(object):
    """Jitted pipeline-parallel training over a mesh with a ``pipe`` axis
    (optionally combined with ``data`` for 2D pp x dp).

    The model contract mirrors how deep nets factor naturally:

    - ``layer_fn(layer_params, h) -> h`` — the repeated block;
    - ``first_stage_fn(extra_params, batch) -> h0`` — embedding/stem,
      runs only on stage 0 (params replicated, unused elsewhere);
    - ``last_stage_fn(extra_params, h, batch) -> (loss, metrics)`` —
      head + loss, runs only on the last stage;
    - optimizer: optax transformation applied to the whole param tree.

    Parameters are a dict ``{"stages": stacked [P, L/P, ...] pytree,
    "first": ..., "last": ...}``; ``stages`` is sharded over ``pipe``,
    the extras are replicated.
    """

    def __init__(
        self,
        layer_fn,
        first_stage_fn,
        last_stage_fn,
        optimizer,
        mesh,
        num_microbatches,
        axis_name="pipe",
        data_axes=("data", "fsdp"),
        schedule="gpipe",
        interleave=2,
        stage_specs=None,
        first_specs=None,
        last_specs=None,
        batch_spec=None,
        grad_sync_axes=None,
    ):
        """``schedule``: ``"gpipe"`` (fwd scan + AD backward; activation
        memory O(M) microbatches/stage), ``"1f1b"`` (hand-scheduled
        PipeDream-flush: same bubble, activation stash bounded at O(P)
        stage *inputs* with the stage forward recomputed in the
        backward unit — the remat trade, ~1.3-1.7x stage FLOPs for
        M/P x less activation memory), or ``"interleaved"`` (Megatron
        interleaved 1F1B: each device runs ``interleave`` virtual-stage
        chunks — ``params["stages"]`` is ``[P, v, L/(P*v), ...]``, see
        :func:`stack_stage_params` — cutting the bubble fraction by
        ~1/v; see parallel/pp_schedule.py for the schedule tables and
        their measured properties).  ``interleave`` is only read for
        the interleaved schedule.

        ``stage_specs``/``first_specs``/``last_specs`` override the
        default param PartitionSpecs (``P(pipe)`` for stages, fully
        replicated for first/last) — pass a pytree of specs matching
        the corresponding subtree to shard stage weights on additional
        mesh axes (PP x TP: e.g. ``P(pipe, None, None, "model")``
        column-parallel and ``P(pipe, None, "model", None)``
        row-parallel, with ``layer_fn`` using
        :func:`~tensorflowonspark_tpu.parallel.tp.tp_copy` /
        :func:`~tensorflowonspark_tpu.parallel.tp.tp_reduce` around its
        sharded matmuls).

        ``batch_spec`` overrides the default batch PartitionSpec
        (``P(data_axes)`` on the leading dim) — pass e.g.
        ``P("data", "seq")`` to ALSO shard a non-leading activation dim
        (PP x SP composition: stage attention then runs a seq-axis
        collective like ring attention inside ``layer_fn``).  When the
        override shards extra axes, name them in ``grad_sync_axes``
        (defaults to ``data_axes``) so gradients and metrics are
        averaged over every axis that splits the batch."""
        if mesh.shape.get(axis_name, 1) < 2:
            raise ValueError(
                "PipelineTrainer needs a mesh with a >=2-wide {0!r} axis, "
                "got {1}".format(axis_name, dict(mesh.shape))
            )
        if schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError("unknown schedule {0!r}".format(schedule))
        if schedule == "interleaved" and interleave < 2:
            raise ValueError(
                "interleaved schedule needs interleave >= 2, got "
                "{0}".format(interleave)
            )
        self.layer_fn = layer_fn
        self.first_stage_fn = first_stage_fn
        self.last_stage_fn = last_stage_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.num_microbatches = num_microbatches
        self.axis_name = axis_name
        self.schedule = schedule
        self.interleave = interleave if schedule == "interleaved" else 1
        self.data_axes = tuple(
            a for a in data_axes if mesh.shape.get(a, 1) > 1
        )
        self.batch_spec_override = batch_spec
        self.grad_sync_axes = (
            tuple(
                a for a in grad_sync_axes if mesh.shape.get(a, 1) > 1
            )
            if grad_sync_axes is not None
            else self.data_axes
        )
        if stage_specs is not None:
            # a spec that forgets the leading pipe dim leaves the stage
            # stack replicated, and local_stage's x[0] would then run
            # stage 0's weights everywhere — silently wrong numerics
            for spec in jax.tree.leaves(
                stage_specs, is_leaf=lambda n: isinstance(n, P)
            ):
                first = spec[0] if len(spec) else None
                if not (
                    first == axis_name
                    or (isinstance(first, tuple) and axis_name in first)
                ):
                    raise ValueError(
                        "every stage_specs leaf must shard its leading "
                        "(stage-stack) dim on {0!r}; got {1}".format(
                            axis_name, spec
                        )
                    )
        self.stage_specs = (
            stage_specs if stage_specs is not None else P(axis_name)
        )
        self.first_specs = first_specs if first_specs is not None else P()
        self.last_specs = last_specs if last_specs is not None else P()
        if schedule == "gpipe":
            self._step = self._build_step()
        elif schedule == "1f1b":
            self._step = self._build_step_1f1b()
        else:
            self._step = self._build_step_interleaved()

    # -- sharding ------------------------------------------------------

    def _spec_tree(self):
        """The shard_map param specs: default P(pipe)/replicated, or the
        caller's per-subtree overrides (PP x TP)."""
        return {
            "stages": self.stage_specs,
            "first": self.first_specs,
            "last": self.last_specs,
        }

    def _param_shardings(self, params):
        def _expand(subtree, spec):
            if isinstance(spec, P):
                return jax.tree.map(
                    lambda x: NamedSharding(self.mesh, spec), subtree
                )
            # multi-tree map: `subtree` (arrays) fixes the structure and
            # `spec` is flattened up to it, so P leaves survive intact
            return jax.tree.map(
                lambda x, s: NamedSharding(self.mesh, s), subtree, spec
            )

        specs = self._spec_tree()
        return {
            key: _expand(params[key], specs[key])
            for key in ("stages", "first", "last")
        }

    def create_state(self, params):
        """``params = {"stages": [P, L/P, ...], "first": ..., "last"}``
        (see :func:`stack_stage_params`)."""
        from tensorflowonspark_tpu.parallel.dp import TrainState

        shardings = self._param_shardings(params)
        # jnp.array copy: device_put may alias source buffers into the
        # placed shards, and the donated train step would then delete
        # the caller's originals (see sharding.shard_params)
        params = jax.tree.map(
            lambda p, s: jax.device_put(jnp.array(p), s), params, shardings
        )
        # optax states mirror the param tree, so jitted init inherits the
        # params' shardings (stage slots stay on their stage's devices);
        # input-independent scalars need re-placing onto the mesh
        from tensorflowonspark_tpu.parallel import sharding as sh

        opt_state = sh.canonicalize_on_mesh(
            jax.jit(self.optimizer.init)(params), self.mesh
        )
        step = jax.device_put(
            jnp.zeros((), jnp.int32), NamedSharding(self.mesh, P())
        )
        return TrainState(step, params, opt_state)

    # -- the step ------------------------------------------------------

    def _build_step(self):
        layer_fn = self.layer_fn
        first_fn = self.first_stage_fn
        last_fn = self.last_stage_fn
        optimizer = self.optimizer
        pipe = self.axis_name
        m = self.num_microbatches
        data_axes = self.data_axes
        mesh = self.mesh

        sync_axes = self.grad_sync_axes
        batch_spec = (
            self.batch_spec_override
            if self.batch_spec_override is not None
            else P(data_axes if data_axes else None)
        )
        param_specs = self._spec_tree()

        def local_loss(params, batch):
            """Runs under shard_map: params['stages'] is the local stage,
            batch is the local data shard."""
            p = compat.axis_size(pipe)
            idx = lax.axis_index(pipe)

            h0 = first_fn(params["first"], batch)  # [B_local, ...]
            b = h0.shape[0]
            if b % m != 0:
                raise ValueError(
                    "local batch {0} not divisible by num_microbatches "
                    "{1}".format(b, m)
                )
            mb = b // m
            micro = h0.reshape((m, mb) + h0.shape[1:])

            stage = functools.partial(_layers_scan, layer_fn)
            # banked results: valid on the last stage only (see the
            # broadcast_result gradient note in `pipeline`)
            outs = pipeline(
                stage, local_stage(params["stages"]), micro, axis_name=pipe,
                broadcast_result=False,
            )
            h_out = outs.reshape((b,) + outs.shape[2:])
            loss_l, metrics_l = last_fn(params["last"], h_out, batch)
            # Return the MASKED local loss (real on the last stage, 0
            # elsewhere) with no collective: under check_vma=False a
            # psum inside the differentiated region transposes to
            # another psum and scales every gradient by the axis size.
            # All sharing/averaging collectives run on the grads and
            # metrics outside autodiff (grad_fn below).
            is_last = idx == p - 1
            loss = jnp.where(is_last, loss_l, 0.0)
            metrics = jax.tree.map(
                lambda x: jnp.where(is_last, x, jnp.zeros_like(x)),
                metrics_l,
            )
            return loss, metrics

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(param_specs, batch_spec),
            out_specs=(param_specs, P()),
            check_vma=False,
        )
        def grad_fn(params, batch):
            (loss, metrics), grads = jax.value_and_grad(
                local_loss, has_aux=True
            )(params, batch)
            # Post-autodiff reductions (always transpose-safe out here):
            # - each device's grads are d(its data shard's loss)/d(its
            #   params): mean over the data axes gives the global-batch
            #   gradient (per-shard losses are already shard means);
            # - first/last grads are nonzero only on the first/last
            #   stage: psum over pipe shares them to every stage's
            #   replicated copy.
            def _dmean(g):
                return lax.pmean(g, sync_axes) if sync_axes else g

            grads = {
                "stages": jax.tree.map(_dmean, grads["stages"]),
                "first": jax.tree.map(
                    lambda g: _dmean(lax.psum(g, pipe)), grads["first"]
                ),
                "last": jax.tree.map(
                    lambda g: _dmean(lax.psum(g, pipe)), grads["last"]
                ),
            }
            # loss/metrics are masked to the last stage: share + average
            metrics = dict(metrics)
            metrics["loss"] = loss
            metrics = jax.tree.map(
                lambda x: _dmean(lax.psum(x, pipe)), metrics
            )
            return grads, metrics

        def train_step(state, batch):
            grads, metrics = grad_fn(state.params, batch)
            updates, opt_state = optimizer.update(
                grads, state.opt_state, state.params
            )
            import optax

            params = optax.apply_updates(state.params, updates)
            from tensorflowonspark_tpu.parallel.dp import TrainState

            return TrainState(state.step + 1, params, opt_state), metrics

        return jax.jit(train_step, donate_argnums=(0,))

    # -- 1F1B ----------------------------------------------------------

    def _build_step_1f1b(self):
        """Hand-scheduled 1F1B train step (see __init__ docstring).

        Every device runs the same tick program (one masked forward
        unit + one masked backward unit per tick) driven by the static
        schedule tables; activations hand off through single-slot
        ppermute buffers (the schedule guarantees a producer never
        overruns an unconsumed slot — property-checked in
        tests/test_pp.py), and the backward unit re-runs the stage
        forward from the stashed stage *input* under ``jax.vjp``.
        """
        from tensorflowonspark_tpu.parallel import pp_schedule

        layer_fn = self.layer_fn
        first_fn = self.first_stage_fn
        last_fn = self.last_stage_fn
        optimizer = self.optimizer
        pipe = self.axis_name
        m = self.num_microbatches
        data_axes = self.data_axes
        mesh = self.mesh
        p = mesh.shape[pipe]

        prog = pp_schedule.stage_program(p, m, "1f1b")
        do_f = jnp.asarray(prog["do_f"])
        f_mb = jnp.asarray(prog["f_mb"])
        do_b = jnp.asarray(prog["do_b"])
        b_mb = jnp.asarray(prog["b_mb"])
        n_ticks = int(prog["do_f"].shape[0])
        stash_slots = min(p, m)

        sync_axes = self.grad_sync_axes
        batch_spec = (
            self.batch_spec_override
            if self.batch_spec_override is not None
            else P(data_axes if data_axes else None)
        )
        param_specs = self._spec_tree()

        stage_fn = functools.partial(_layers_scan, layer_fn)

        def local_grads(params, batch):
            idx = lax.axis_index(pipe)
            is_first = idx == 0
            is_last = idx == p - 1
            fwd_perm = [(i, (i + 1) % p) for i in range(p)]
            bwd_perm = [(i, (i - 1) % p) for i in range(p)]

            stage_params = local_stage(params["stages"])
            h0 = first_fn(params["first"], batch)
            b = h0.shape[0]
            if b % m != 0:
                raise ValueError(
                    "local batch {0} not divisible by num_microbatches "
                    "{1}".format(b, m)
                )
            mb = b // m
            micro = h0.reshape((m, mb) + h0.shape[1:])
            batch_micro = jax.tree.map(
                lambda x: x.reshape((m, mb) + x.shape[1:]), batch
            )

            # metrics structure (zeros) via abstract eval of last_fn
            mb_batch0 = jax.tree.map(lambda x: x[0], batch_micro)
            _, metrics_shape = jax.eval_shape(
                last_fn, params["last"], jax.ShapeDtypeStruct(
                    micro.shape[1:], micro.dtype
                ), mb_batch0,
            )
            metrics0 = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), metrics_shape
            )

            zeros_act = jnp.zeros(micro.shape[1:], micro.dtype)
            carry = dict(
                fwd_recv=zeros_act,
                bwd_recv=zeros_act,
                stash=jnp.zeros((stash_slots,) + micro.shape[1:], micro.dtype),
                d_h0=jnp.zeros_like(micro),
                stage_g=jax.tree.map(jnp.zeros_like, stage_params),
                last_g=jax.tree.map(jnp.zeros_like, params["last"]),
                loss=jnp.zeros((), jnp.float32),
                metrics=metrics0,
            )

            def acc(flag, old, new):
                return jax.tree.map(
                    lambda o, n: jnp.where(flag, o + n, o), old, new
                )

            def tick(carry, t):
                myf = do_f[t, idx].astype(bool)
                myb = do_b[t, idx].astype(bool)
                fj = f_mb[t, idx]
                bj = b_mb[t, idx]

                # ---- forward unit (masked) --------------------------
                x_in = jnp.where(is_first, micro[fj], carry["fwd_recv"])
                y = stage_fn(stage_params, x_in)
                stash = jnp.where(
                    myf,
                    lax.dynamic_update_index_in_dim(
                        carry["stash"], x_in, fj % stash_slots, axis=0
                    ),
                    carry["stash"],
                )

                # ---- backward unit (masked; remat from stashed input)
                x_b = carry["stash"][bj % stash_slots]
                y_b, pull = jax.vjp(stage_fn, stage_params, x_b)
                mb_batch = jax.tree.map(lambda a: a[bj], batch_micro)
                loss_j, last_pull, metrics_j = jax.vjp(
                    lambda lp, h: last_fn(lp, h, mb_batch),
                    params["last"],
                    y_b,
                    has_aux=True,
                )
                d_last, d_y_last = last_pull(jnp.ones_like(loss_j))
                ct = jnp.where(is_last, d_y_last, carry["bwd_recv"])
                d_stage, d_x = pull(ct)

                bl = jnp.logical_and(myb, is_last)
                new = dict(
                    stash=stash,
                    stage_g=acc(myb, carry["stage_g"], d_stage),
                    last_g=acc(bl, carry["last_g"], d_last),
                    loss=jnp.where(
                        bl, carry["loss"] + loss_j.astype(jnp.float32),
                        carry["loss"],
                    ),
                    metrics=acc(bl, carry["metrics"], metrics_j),
                    d_h0=jnp.where(
                        jnp.logical_and(myb, is_first),
                        lax.dynamic_update_index_in_dim(
                            carry["d_h0"], d_x, bj, axis=0
                        ),
                        carry["d_h0"],
                    ),
                )

                # ---- handoffs (single slot; masked by sender's flag)
                recv_y = lax.ppermute(y, pipe, fwd_perm)
                recv_ct = lax.ppermute(d_x, pipe, bwd_perm)
                sent_f = do_f[t, (idx - 1) % p].astype(bool)
                sent_b = do_b[t, (idx + 1) % p].astype(bool)
                new["fwd_recv"] = jnp.where(sent_f, recv_y, carry["fwd_recv"])
                new["bwd_recv"] = jnp.where(sent_b, recv_ct, carry["bwd_recv"])
                return new, None

            carry, _ = lax.scan(tick, carry, jnp.arange(n_ticks))

            # first-stage grads: one vjp of the whole-batch embedding with
            # the accumulated per-microbatch cotangents (nonzero only on
            # stage 0 — psum shares them to every replicated copy)
            _, first_pull = jax.vjp(lambda fp: first_fn(fp, batch), params["first"])
            (d_first,) = first_pull(
                carry["d_h0"].reshape((b,) + carry["d_h0"].shape[2:])
            )
            d_first = jax.tree.map(
                lambda g: jnp.where(is_first, g, jnp.zeros_like(g)), d_first
            )

            def _dmean(g):
                return lax.pmean(g, sync_axes) if sync_axes else g

            inv_m = 1.0 / m
            grads = {
                # restore the leading (local size-1) stage dim for the
                # P(pipe) out_spec
                "stages": jax.tree.map(
                    lambda g: _dmean(g * inv_m)[None], carry["stage_g"]
                ),
                "first": jax.tree.map(
                    lambda g: _dmean(lax.psum(g * inv_m, pipe)), d_first
                ),
                "last": jax.tree.map(
                    lambda g: _dmean(lax.psum(g * inv_m, pipe)),
                    carry["last_g"],
                ),
            }
            metrics = dict(carry["metrics"])
            metrics["loss"] = carry["loss"]
            metrics = jax.tree.map(
                lambda x: _dmean(lax.psum(x * inv_m, pipe)), metrics
            )
            return grads, metrics

        grad_fn = functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(param_specs, batch_spec),
            out_specs=(param_specs, P()),
            check_vma=False,
        )(local_grads)

        def train_step(state, batch):
            grads, metrics = grad_fn(state.params, batch)
            updates, opt_state = optimizer.update(
                grads, state.opt_state, state.params
            )
            import optax

            params = optax.apply_updates(state.params, updates)
            from tensorflowonspark_tpu.parallel.dp import TrainState

            return TrainState(state.step + 1, params, opt_state), metrics

        return jax.jit(train_step, donate_argnums=(0,))

    # -- interleaved 1F1B ----------------------------------------------

    def _build_step_interleaved(self):
        """Megatron interleaved-1F1B train step.

        Same masked-SPMD structure as :meth:`_build_step_1f1b`, with
        ``interleave`` virtual-stage chunks per device: stage params
        carry a leading ``[v, ...]`` chunk axis the tick program
        dynamic-indexes, and the single-slot handoff buffers become
        per-chunk slot banks whose depths come from the *static* buffer
        analysis (``pp_schedule.analyze_program``) — the schedule is
        property-checked at build time, so an overrun is impossible at
        run time rather than merely untested.  Activation hand-off
        routing: absolute chunk ``a = c*P + d`` forwards to
        ``a+1`` — the ring neighbor ``d+1`` at the same local chunk,
        except device P-1 wraps to device 0 at local chunk ``c+1``.
        """
        from tensorflowonspark_tpu.parallel import pp_schedule

        layer_fn = self.layer_fn
        first_fn = self.first_stage_fn
        last_fn = self.last_stage_fn
        optimizer = self.optimizer
        pipe = self.axis_name
        m = self.num_microbatches
        v = self.interleave
        data_axes = self.data_axes
        mesh = self.mesh
        p = mesh.shape[pipe]

        table = pp_schedule.simulate(p, m, "1f1b", interleave=v)
        geom = pp_schedule.analyze_program(table, p, interleave=v)
        prog = pp_schedule.stage_program(p, m, "1f1b", interleave=v)
        do_f = jnp.asarray(prog["do_f"])
        f_mb = jnp.asarray(prog["f_mb"])
        f_ch = jnp.asarray(prog["f_chunk"])
        do_b = jnp.asarray(prog["do_b"])
        b_mb = jnp.asarray(prog["b_mb"])
        b_ch = jnp.asarray(prog["b_chunk"])
        n_ticks = int(prog["do_f"].shape[0])
        stash_slots = geom["stash_slots"]
        qf = geom["fwd_slots"]
        qb = geom["bwd_slots"]

        sync_axes = self.grad_sync_axes
        batch_spec = (
            self.batch_spec_override
            if self.batch_spec_override is not None
            else P(data_axes if data_axes else None)
        )
        param_specs = self._spec_tree()

        stage_fn = functools.partial(_layers_scan, layer_fn)

        def _pick_chunk(tree_v, c):
            return jax.tree.map(
                lambda x: lax.dynamic_index_in_dim(x, c, 0, keepdims=False),
                tree_v,
            )

        def _bank_get(bank, c, s):
            """``bank[c, s]`` with traced scalar indices."""
            row = lax.dynamic_index_in_dim(bank, c, 0, keepdims=False)
            return lax.dynamic_index_in_dim(row, s, 0, keepdims=False)

        def _bank_put(bank, val, c, s, pred):
            start = (c, s) + (0,) * val.ndim
            return jnp.where(
                pred,
                lax.dynamic_update_slice(bank, val[None, None], start),
                bank,
            )

        def local_grads(params, batch):
            idx = lax.axis_index(pipe)
            is_first = idx == 0
            is_last = idx == p - 1
            fwd_perm = [(i, (i + 1) % p) for i in range(p)]
            bwd_perm = [(i, (i - 1) % p) for i in range(p)]

            stage_params = local_stage(params["stages"])  # [v, lc, ...]
            h0 = first_fn(params["first"], batch)
            b = h0.shape[0]
            if b % m != 0:
                raise ValueError(
                    "local batch {0} not divisible by num_microbatches "
                    "{1}".format(b, m)
                )
            mb = b // m
            micro = h0.reshape((m, mb) + h0.shape[1:])
            batch_micro = jax.tree.map(
                lambda x: x.reshape((m, mb) + x.shape[1:]), batch
            )

            mb_batch0 = jax.tree.map(lambda x: x[0], batch_micro)
            _, metrics_shape = jax.eval_shape(
                last_fn, params["last"], jax.ShapeDtypeStruct(
                    micro.shape[1:], micro.dtype
                ), mb_batch0,
            )
            metrics0 = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), metrics_shape
            )

            act = micro.shape[1:]
            carry = dict(
                fwd_recv=jnp.zeros((v, qf) + act, micro.dtype),
                bwd_recv=jnp.zeros((v, qb) + act, micro.dtype),
                stash=jnp.zeros((v, stash_slots) + act, micro.dtype),
                d_h0=jnp.zeros_like(micro),
                stage_g=jax.tree.map(jnp.zeros_like, stage_params),
                last_g=jax.tree.map(jnp.zeros_like, params["last"]),
                loss=jnp.zeros((), jnp.float32),
                metrics=metrics0,
            )

            def acc(flag, old, new):
                return jax.tree.map(
                    lambda o, n: jnp.where(flag, o + n, o), old, new
                )

            def tick(carry, t):
                myf = do_f[t, idx].astype(bool)
                myb = do_b[t, idx].astype(bool)
                fj, fc = f_mb[t, idx], f_ch[t, idx]
                bj, bc = b_mb[t, idx], b_ch[t, idx]

                # ---- forward unit (masked; chunk fc) ----------------
                params_f = _pick_chunk(stage_params, fc)
                inject = jnp.logical_and(is_first, fc == 0)
                x_in = jnp.where(
                    inject, micro[fj], _bank_get(carry["fwd_recv"], fc, fj % qf)
                )
                y = stage_fn(params_f, x_in)
                stash = _bank_put(
                    carry["stash"], x_in, fc, fj % stash_slots, myf
                )

                # ---- backward unit (masked; chunk bc; remat) --------
                params_b = _pick_chunk(stage_params, bc)
                x_b = _bank_get(carry["stash"], bc, bj % stash_slots)
                y_b, pull = jax.vjp(stage_fn, params_b, x_b)
                mb_batch = jax.tree.map(lambda a: a[bj], batch_micro)
                loss_j, last_pull, metrics_j = jax.vjp(
                    lambda lp, h: last_fn(lp, h, mb_batch),
                    params["last"],
                    y_b,
                    has_aux=True,
                )
                d_last, d_y_last = last_pull(jnp.ones_like(loss_j))
                own_loss = jnp.logical_and(is_last, bc == v - 1)
                ct = jnp.where(
                    own_loss, d_y_last, _bank_get(carry["bwd_recv"], bc, bj % qb)
                )
                d_chunk, d_x = pull(ct)

                bl = jnp.logical_and(myb, own_loss)
                stage_g = jax.tree.map(
                    lambda gacc, gnew: jnp.where(
                        myb,
                        lax.dynamic_update_index_in_dim(
                            gacc,
                            lax.dynamic_index_in_dim(
                                gacc, bc, 0, keepdims=False
                            ) + gnew,
                            bc,
                            axis=0,
                        ),
                        gacc,
                    ),
                    carry["stage_g"],
                    d_chunk,
                )
                new = dict(
                    stash=stash,
                    stage_g=stage_g,
                    last_g=acc(bl, carry["last_g"], d_last),
                    loss=jnp.where(
                        bl, carry["loss"] + loss_j.astype(jnp.float32),
                        carry["loss"],
                    ),
                    metrics=acc(bl, carry["metrics"], metrics_j),
                    d_h0=jnp.where(
                        jnp.logical_and(
                            myb, jnp.logical_and(is_first, bc == 0)
                        ),
                        lax.dynamic_update_index_in_dim(
                            carry["d_h0"], d_x, bj, axis=0
                        ),
                        carry["d_h0"],
                    ),
                )

                # ---- handoffs (per-chunk slot banks; static routing)
                recv_y = lax.ppermute(y, pipe, fwd_perm)
                recv_ct = lax.ppermute(d_x, pipe, bwd_perm)
                sd = (idx - 1) % p  # fwd sender on the ring
                sent_f = do_f[t, sd].astype(bool)
                s_ch = f_ch[t, sd] + jnp.where(idx == 0, 1, 0)
                s_mb = f_mb[t, sd]
                valid_f = jnp.logical_and(sent_f, s_ch < v)
                new["fwd_recv"] = _bank_put(
                    carry["fwd_recv"], recv_y,
                    jnp.clip(s_ch, 0, v - 1), s_mb % qf, valid_f,
                )
                su = (idx + 1) % p  # bwd sender on the ring
                sent_b = do_b[t, su].astype(bool)
                r_ch = b_ch[t, su] - jnp.where(idx == p - 1, 1, 0)
                r_mb = b_mb[t, su]
                valid_b = jnp.logical_and(sent_b, r_ch >= 0)
                new["bwd_recv"] = _bank_put(
                    carry["bwd_recv"], recv_ct,
                    jnp.clip(r_ch, 0, v - 1), r_mb % qb, valid_b,
                )
                return new, None

            carry, _ = lax.scan(tick, carry, jnp.arange(n_ticks))

            _, first_pull = jax.vjp(
                lambda fp: first_fn(fp, batch), params["first"]
            )
            (d_first,) = first_pull(
                carry["d_h0"].reshape((b,) + carry["d_h0"].shape[2:])
            )
            d_first = jax.tree.map(
                lambda g: jnp.where(is_first, g, jnp.zeros_like(g)), d_first
            )

            def _dmean(g):
                return lax.pmean(g, sync_axes) if sync_axes else g

            inv_m = 1.0 / m
            grads = {
                "stages": jax.tree.map(
                    lambda g: _dmean(g * inv_m)[None], carry["stage_g"]
                ),
                "first": jax.tree.map(
                    lambda g: _dmean(lax.psum(g * inv_m, pipe)), d_first
                ),
                "last": jax.tree.map(
                    lambda g: _dmean(lax.psum(g * inv_m, pipe)),
                    carry["last_g"],
                ),
            }
            metrics = dict(carry["metrics"])
            metrics["loss"] = carry["loss"]
            metrics = jax.tree.map(
                lambda x: _dmean(lax.psum(x * inv_m, pipe)), metrics
            )
            return grads, metrics

        grad_fn = functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(param_specs, batch_spec),
            out_specs=(param_specs, P()),
            check_vma=False,
        )(local_grads)

        def train_step(state, batch):
            grads, metrics = grad_fn(state.params, batch)
            updates, opt_state = optimizer.update(
                grads, state.opt_state, state.params
            )
            import optax

            params = optax.apply_updates(state.params, updates)
            from tensorflowonspark_tpu.parallel.dp import TrainState

            return TrainState(state.step + 1, params, opt_state), metrics

        return jax.jit(train_step, donate_argnums=(0,))

    def step(self, state, batch):
        """One pipelined step on a host-local batch pytree."""
        if self.batch_spec_override is not None:
            # place with the override's FULL spec (e.g. P('data','seq')
            # for PP x SP): placing on data_axes alone would land the
            # extra-sharded dims replicated and make jit reshard the
            # whole batch every step
            sharding = NamedSharding(self.mesh, self.batch_spec_override)
            device_batch = jax.tree.map(
                lambda x: jax.device_put(x, sharding), batch
            )
        else:
            from tensorflowonspark_tpu.parallel import sharding as sh

            device_batch = sh.shard_batch(
                batch, self.mesh, self.data_axes or ("data",)
            )
        return self._step(state, device_batch)
