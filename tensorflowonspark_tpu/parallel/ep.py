"""Expert parallelism: MoE routing over the ``expert`` mesh axis.

Absent from the reference (SURVEY.md §2.3); built fresh.  The design is
sharding-driven: :class:`~tensorflowonspark_tpu.models.moe.MoEMLP`
computes dense dispatch/combine einsums against expert-sharded weights,
and XLA lowers the resharding to expert all-to-alls over ICI — no
hand-written routing collectives to get wrong.

This module is the strategy surface; the router math lives in
:mod:`tensorflowonspark_tpu.ops.moe` and the layer in
:mod:`tensorflowonspark_tpu.models.moe`.
"""

from tensorflowonspark_tpu.models.moe import MoEMLP, moe_loss_fn  # noqa: F401
from tensorflowonspark_tpu.ops.moe import (  # noqa: F401
    expert_capacity,
    top_k_gating,
)
from tensorflowonspark_tpu.parallel.mesh import AXIS_EXPERT  # noqa: F401


def plan(
    num_experts,
    tokens_per_batch,
    k=2,
    capacity_factor=1.25,
    n_devices=None,
    embed_dim=None,
    mlp_dim=None,
    dtype_bytes=2,
):
    """Expert-parallel capacity plan.

    Answers the sizing questions an EP deployment starts with: how many
    token slots each expert processes per step, how much gets dropped
    when routing is imbalanced, how wide the ``expert`` mesh axis can
    be, and the all-to-all traffic per MoE layer.

    Args:
      num_experts: total experts per MoE layer.
      tokens_per_batch: global tokens per step (batch x seq).
      k: experts per token (top-k routing).
      capacity_factor: slack over the perfectly-balanced load.
      n_devices: devices available for the ``expert`` axis (optional).
      embed_dim / mlp_dim / dtype_bytes: for memory/comm estimates
        (optional).

    Returns a dict of derived quantities (all integers/floats, no jax).
    """
    cap = expert_capacity(
        tokens_per_batch, num_experts, k=k, capacity_factor=capacity_factor
    )
    out = {
        "capacity_per_expert": cap,
        "total_slots": cap * num_experts,
        #: routed assignments that fit if routing were perfectly
        #: balanced (k per token); >1.0 slack absorbs imbalance
        "slack": (cap * num_experts) / float(k * tokens_per_batch),
        #: fraction of the HOTSPOT EXPERT'S OWN assignments dropped when
        #: that one expert attracts 2x its balanced share (the global
        #: dropped fraction is ~this / num_experts for a single hotspot)
        "drop_at_2x_hotspot": max(
            0.0, 1.0 - cap / (2.0 * k * tokens_per_batch / num_experts)
        ),
    }
    if n_devices:
        if num_experts % n_devices == 0:
            out["expert_axis"] = n_devices
            out["experts_per_device"] = num_experts // n_devices
        else:
            divisors = [
                d for d in range(1, n_devices + 1) if num_experts % d == 0
            ]
            out["expert_axis"] = max(divisors)
            out["experts_per_device"] = num_experts // out["expert_axis"]
    if embed_dim and mlp_dim:
        # expert weights per device (wi + wg + wo per expert)
        per_expert = 3 * embed_dim * mlp_dim * dtype_bytes
        out["expert_bytes_per_device"] = per_expert * out.get(
            "experts_per_device", num_experts
        )
        # dispatch+combine all-to-all volume per layer per step: each
        # routed token activation crosses the expert axis twice
        out["alltoall_bytes_per_layer"] = (
            2 * k * tokens_per_batch * embed_dim * dtype_bytes
        )
    return out


def trainer(
    loss_fn,
    optimizer,
    mesh,
    annotations=None,
    has_aux=True,
    **kw,
):
    """A :class:`~tensorflowonspark_tpu.parallel.dp.SyncTrainer` wired
    for expert parallelism: RULES_EP places ``expert``-annotated params
    on the ``expert`` mesh axis (and ``expert_mlp`` on ``model`` when
    present); XLA inserts the dispatch/combine all-to-alls.  MoE losses
    return ``(loss, aux)`` with the load-balance penalty in ``aux``
    (models/moe.moe_loss_fn), hence ``has_aux=True``."""
    from tensorflowonspark_tpu.parallel import dp, sharding as sh

    return dp.SyncTrainer(
        loss_fn,
        optimizer,
        mesh=mesh,
        rules=sh.RULES_EP,
        annotations=annotations,
        has_aux=has_aux,
        **kw,
    )


def utilization(router_probs, num_experts):
    """Expert load-balance diagnostics from router probabilities.

    Args:
      router_probs: ``[tokens, num_experts]`` softmax outputs.
    Returns ``(fraction_per_expert, imbalance)`` where imbalance is the
    max/mean load ratio (1.0 = perfectly balanced)."""
    import jax.numpy as jnp

    load = jnp.mean(router_probs, axis=tuple(range(router_probs.ndim - 1)))
    return load, float(jnp.max(load) * num_experts)
