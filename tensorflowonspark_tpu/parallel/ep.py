"""Expert parallelism: MoE routing over the ``expert`` mesh axis.

Absent from the reference (SURVEY.md §2.3); built fresh.  The design is
sharding-driven: :class:`~tensorflowonspark_tpu.models.moe.MoEMLP`
computes dense dispatch/combine einsums against expert-sharded weights,
and XLA lowers the resharding to expert all-to-alls over ICI — no
hand-written routing collectives to get wrong.

This module is the strategy surface; the router math lives in
:mod:`tensorflowonspark_tpu.ops.moe` and the layer in
:mod:`tensorflowonspark_tpu.models.moe`.
"""

from tensorflowonspark_tpu.models.moe import MoEMLP, moe_loss_fn  # noqa: F401
from tensorflowonspark_tpu.ops.moe import (  # noqa: F401
    expert_capacity,
    top_k_gating,
)
from tensorflowonspark_tpu.parallel.mesh import AXIS_EXPERT  # noqa: F401
