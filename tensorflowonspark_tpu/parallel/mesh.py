"""Device-mesh construction over ICI/DCN.

The TPU-native replacement for the reference's cluster-spec/TF_CONFIG
machinery (reference: tensorflowonspark/TFSparkNode.py:340-362): instead
of wiring gRPC servers by job name, parallelism is expressed as named
axes of a :class:`jax.sharding.Mesh`, and XLA lowers collectives onto
ICI (intra-slice) / DCN (inter-slice) links.

Canonical axis names (used by every strategy module and the models):

========  =====================================================
axis      meaning
========  =====================================================
``data``  pure data parallelism (batch split, grads psum'd)
``ps``    device-resident PS aggregation (hierarchical gradient
          plane: in-pod grads psum/reduce-scatter along this axis,
          see :mod:`tensorflowonspark_tpu.parallel.hier_ps`)
``fsdp``  data parallelism with fully-sharded params (zero-3)
``model`` tensor parallelism (matmul column/row sharding)
``pipe``  pipeline stages (microbatched ppermute loop)
``seq``   sequence/context parallelism (ring attention, Ulysses)
``expert`` expert parallelism (MoE all-to-all dispatch)
========  =====================================================

Mesh-order convention follows the scaling playbook: slowest-varying axis
first = the axis that may span DCN (data), fastest-varying axes last =
the ones needing the tightest ICI locality (model/seq).
"""

import logging
import math

logger = logging.getLogger(__name__)

AXIS_DATA = "data"
AXIS_PS = "ps"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "model"
AXIS_PIPELINE = "pipe"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"

#: All known axes in canonical mesh order (DCN-friendly → ICI-hungry).
#: ``ps`` sits right after ``data``: the in-pod aggregation axis wants
#: ICI locality but never spans DCN (the hierarchical plane's whole
#: point is that only a pod leader crosses it).
CANONICAL_ORDER = (
    AXIS_PIPELINE,
    AXIS_DATA,
    AXIS_PS,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_SEQ,
    AXIS_TENSOR,
)


def distributed_init_from_env(environ=None):
    """``jax.distributed`` bootstrap for pod-launched hosts.

    ``scripts/tpu_pod.py run`` exports ``TFOS_COORDINATOR``
    (host:port of worker 0) and ``TFOS_PROCESS_ID`` on every host of
    the slice; this reads them and initializes the process group
    (num_processes from ``TFOS_NUM_PROCESSES`` when set, otherwise the
    TPU backend infers it from the slice metadata).  No-op when the
    variables are absent (single-host runs) or when jax.distributed is
    already initialized.  The Spark/LocalEngine path wires the same
    thing from the reservation server instead
    (``cluster.node.NodeContext.initialize_distributed``).

    Returns True when initialization ran.
    """
    import os

    env = os.environ if environ is None else environ
    coord = env.get("TFOS_COORDINATOR")
    if not coord:
        return False
    import jax

    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return False
    kwargs = {"coordinator_address": coord}
    if env.get("TFOS_PROCESS_ID") is not None:
        kwargs["process_id"] = int(env["TFOS_PROCESS_ID"])
    if env.get("TFOS_NUM_PROCESSES") is not None:
        kwargs["num_processes"] = int(env["TFOS_NUM_PROCESSES"])
    jax.distributed.initialize(**kwargs)
    logger.info(
        "jax.distributed initialized from env: %s process %s",
        coord, env.get("TFOS_PROCESS_ID"),
    )
    return True


class MeshSpec(object):
    """Declarative mesh shape: ordered ``(axis_name, size)`` pairs.

    ``size == -1`` on at most one axis means "absorb all remaining
    devices".  Example::

        MeshSpec(data=-1, model=2)        # 8 devices -> data=4, model=2
        MeshSpec.from_axes([("pipe", 2), ("data", -1)])
    """

    def __init__(self, **axes):
        # preserve canonical order for kwargs; explicit list via from_axes
        ordered = [(n, axes.pop(n)) for n in CANONICAL_ORDER if n in axes]
        if axes:
            # unknown axis names are allowed (user-defined), appended last
            ordered.extend(sorted(axes.items()))
        self.axes = ordered

    @classmethod
    def from_axes(cls, axes):
        spec = cls()
        spec.axes = [(str(n), int(s)) for n, s in axes]
        return spec

    def resolve(self, num_devices):
        """Concretize ``-1`` and validate the factorization."""
        names = [n for n, _ in self.axes]
        if len(set(names)) != len(names):
            raise ValueError("duplicate axis names in {0}".format(names))
        sizes = [s for _, s in self.axes]
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError("at most one axis may have size -1")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if num_devices % fixed != 0:
                raise ValueError(
                    "fixed axes {0} do not divide device count {1}".format(
                        fixed, num_devices
                    )
                )
            sizes[wild[0]] = num_devices // fixed
        elif fixed != num_devices:
            raise ValueError(
                "mesh {0} needs {1} devices, have {2}".format(
                    self.axes, fixed, num_devices
                )
            )
        return list(zip(names, sizes))


def build_mesh(axes=None, devices=None, allow_split_physical=True):
    """Build a :class:`jax.sharding.Mesh`.

    Args:
      axes: ``None`` (all devices on ``data``), a :class:`MeshSpec`, a
        dict ``{axis: size}``, or an ordered list of ``(axis, size)``
        pairs; ``-1`` absorbs remaining devices.
      devices: override the device list (default ``jax.devices()``).
      allow_split_physical: fall back to a plain reshape when
        ``mesh_utils.create_device_mesh`` rejects the shape (e.g. virtual
        CPU devices with no physical topology).

    The device order is delegated to ``jax.experimental.mesh_utils`` so
    ICI-adjacent chips land adjacent on the fastest-varying axes.
    """
    # Pod-launched hosts (scripts/tpu_pod.py run) carry the rendezvous
    # in env vars; joining the process group must precede the first
    # device query, and every program path funnels through build_mesh —
    # a no-op unless TFOS_COORDINATOR is set and not yet initialized.
    distributed_init_from_env()

    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)

    if axes is None:
        axes = MeshSpec(**{AXIS_DATA: -1})
    elif isinstance(axes, dict):
        axes = MeshSpec(**axes)
    elif isinstance(axes, (list, tuple)):
        axes = MeshSpec.from_axes(axes)

    resolved = axes.resolve(n)
    names = tuple(name for name, _ in resolved)
    shape = tuple(size for _, size in resolved)

    try:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError, NotImplementedError) as e:
        if not allow_split_physical:
            raise
        logger.debug("mesh_utils rejected shape %s (%s); plain reshape", shape, e)
        import numpy as np

        device_array = np.asarray(devices).reshape(shape)

    mesh = Mesh(device_array, names)
    logger.info("built mesh %s over %d devices", dict(resolved), n)
    return mesh


def serving_mesh(tp=None, mesh_shape=None, devices=None):
    """The SERVING stack's mesh (``serving_builder`` ``tp`` /
    ``mesh_shape`` knobs, docs/serving.md "Disaggregated
    prefill/decode & TP sharding").

    ``tp=N`` is the shorthand: a 1-axis ``model=N`` mesh over the
    first N devices — the tensor-parallel degree the SlotDecoder
    shards its weights and KV page pools over.  ``mesh_shape`` (a
    ``{axis: size}`` dict, ``-1`` wildcard allowed) overrides it for
    explicit topologies (e.g. ``{"data": 2, "model": 2}``).  Returns
    ``None`` when neither asks for more than one device — the caller
    then keeps the unsharded single-program path, so the knobs are
    strictly additive.
    """
    if mesh_shape:
        return build_mesh(dict(mesh_shape), devices=devices)
    t = int(tp or 0)
    if t <= 1:
        return None
    import jax

    devs = list(devices) if devices is not None else list(jax.devices())
    if len(devs) < t:
        raise ValueError(
            "tp={0} needs {0} devices, have {1}".format(t, len(devs))
        )
    return build_mesh(MeshSpec(**{AXIS_TENSOR: t}), devices=devs[:t])


def mesh_axis_size(mesh, *axis_names):
    """Product of the named axes' sizes (1 for absent axes) — the standard
    way strategies ask "how wide is my parallelism" without caring which
    axes exist on this particular mesh."""
    size = 1
    for name in axis_names:
        size *= mesh.shape.get(name, 1)
    return size


def local_batch_size(mesh, global_batch_size, data_axes=(AXIS_DATA, AXIS_FSDP)):
    """Per-process batch share for a multi-host mesh (the reference's
    equivalent knob was implicit in RDD partitioning)."""
    width = mesh_axis_size(mesh, *data_axes)
    if global_batch_size % width != 0:
        raise ValueError(
            "global batch {0} not divisible by data-parallel width {1}".format(
                global_batch_size, width
            )
        )
    import jax

    return global_batch_size // jax.process_count()
